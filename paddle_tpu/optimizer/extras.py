"""Optimizer extras: EMA, ModelAverage, Lookahead.

Reference: python/paddle/fluid/optimizer.py ExponentialMovingAverage:3466,
ModelAverage:3157, LookaheadOptimizer:5499 (2.x surface:
paddle.incubate.ExponentialMovingAverage etc.).  TPU-native: shadow
states are plain device arrays updated functionally — under a compiled
step they fuse into the update program; eagerly they are a handful of
fused element-wise kernels per parameter.
"""
from __future__ import annotations

from typing import Dict, List, Optional

import jax.numpy as jnp

from ..core.tensor import Parameter

__all__ = ["ExponentialMovingAverage", "ModelAverage",
           "LookaheadOptimizer", "Lookahead"]


class ExponentialMovingAverage:
    """shadow = decay * shadow + (1 - decay) * param
    (reference: fluid/optimizer.py:3466; thres_steps debiasing included).

    Usage::

        ema = ExponentialMovingAverage(0.999, parameters=model.parameters())
        for batch in data:
            train_step(...)
            ema.update()
        with ema.apply(model):      # evaluate with averaged weights
            evaluate(model)
    """

    def __init__(self, decay: float = 0.999, thres_steps=None,
                 parameters: Optional[List[Parameter]] = None, name=None):
        self._decay = float(decay)
        # reference default: constant decay (thres_steps=None); truthy
        # enables the debiasing ramp min(decay, (1+t)/(10+t))
        self._thres = bool(thres_steps)
        self._params = list(parameters or [])
        self._shadow: Dict[int, jnp.ndarray] = {
            id(p): jnp.asarray(p.data) for p in self._params}
        self._step = 0

    def update(self):
        self._step += 1
        d = self._decay
        if self._thres:
            # debiased decay ramp (reference: min(decay, (1+t)/(10+t)))
            d = min(d, (1.0 + self._step) / (10.0 + self._step))
        for p in self._params:
            s = self._shadow[id(p)]
            self._shadow[id(p)] = d * s + (1.0 - d) * p.data.astype(s.dtype)

    class _Applied:
        def __init__(self, ema, restore):
            self._ema, self._restore = ema, restore

        def __enter__(self):
            return self._ema

        def __exit__(self, *exc):
            if self._restore:
                self._ema.restore()
            return False

    def apply(self, executor=None, need_restore: bool = True):
        """Swap shadow weights in (context manager; reference apply())."""
        self._backup = {id(p): p.data for p in self._params}
        for p in self._params:
            p.data = self._shadow[id(p)].astype(p.data.dtype)
        return self._Applied(self, need_restore)

    def restore(self, executor=None):
        for p in self._params:
            p.data = self._backup[id(p)]

    def state_dict(self):
        return {"step": self._step,
                "shadow": [self._shadow[id(p)] for p in self._params]}

    def set_state_dict(self, sd):
        self._step = sd["step"]
        for p, s in zip(self._params, sd["shadow"]):
            self._shadow[id(p)] = jnp.asarray(s)


class ModelAverage:
    """Running average of parameters over a trailing window (reference:
    fluid/optimizer.py:3157).  Two-block rotation like the reference's
    sum accumulators: the current block accumulates up to
    ``max_average_window`` steps, then rotates into the previous block —
    the effective window stays between max_w and 2*max_w instead of ever
    collapsing to a single step."""

    def __init__(self, average_window_rate: float = 0.15,
                 parameters: Optional[List[Parameter]] = None,
                 min_average_window: int = 10000,
                 max_average_window: int = 10000, name=None):
        self._params = list(parameters or [])
        self._rate = average_window_rate
        self._min_w = min_average_window
        self._max_w = max_average_window
        zeros = lambda p: jnp.zeros_like(p.data, dtype=jnp.float32)
        self._sum = {id(p): zeros(p) for p in self._params}
        self._prev = {id(p): zeros(p) for p in self._params}
        self._count = 0
        self._prev_count = 0

    def step(self):
        if self._count >= self._max_w:
            # rotate blocks (reference: num_accumulates rollover)
            self._prev = self._sum
            self._prev_count = self._count
            self._sum = {id(p): jnp.zeros_like(p.data, dtype=jnp.float32)
                         for p in self._params}
            self._count = 0
        self._count += 1
        for p in self._params:
            self._sum[id(p)] = self._sum[id(p)] + p.data.astype(jnp.float32)

    minimize = step  # fluid-era call-site parity

    class _Applied:
        def __init__(self, ma, restore):
            self._ma, self._restore = ma, restore

        def __enter__(self):
            return self._ma

        def __exit__(self, *exc):
            if self._restore:
                self._ma.restore()
            return False

    def apply(self, executor=None, need_restore: bool = True):
        total = self._count + self._prev_count
        assert total > 0, "ModelAverage.apply before any step()"
        self._backup = {id(p): p.data for p in self._params}
        for p in self._params:
            avg = (self._sum[id(p)] + self._prev[id(p)]) / float(total)
            p.data = avg.astype(p.data.dtype)
        return self._Applied(self, need_restore)

    def restore(self, executor=None):
        for p in self._params:
            p.data = self._backup[id(p)]


class LookaheadOptimizer:
    """k-step lookahead wrapper (reference: fluid/optimizer.py:5499):
    the inner (fast) optimizer runs k steps, then slow weights move
    ``alpha`` of the way toward the fast weights and the fast weights
    reset to the slow ones."""

    def __init__(self, inner_optimizer, alpha: float = 0.5, k: int = 5):
        assert 0.0 < alpha <= 1.0 and k >= 1
        self.inner_optimizer = inner_optimizer
        self._alpha = float(alpha)
        self._k = int(k)
        self._params = list(inner_optimizer._parameter_list or [])
        self._slow = {id(p): jnp.asarray(p.data) for p in self._params}
        self._i = 0

    def step(self):
        self.inner_optimizer.step()
        self._i += 1
        if self._i % self._k == 0:
            a = self._alpha
            for p in self._params:
                slow = self._slow[id(p)]
                slow = slow + a * (p.data.astype(slow.dtype) - slow)
                self._slow[id(p)] = slow
                p.data = slow.astype(p.data.dtype)

    def clear_grad(self, *a, **k):
        return self.inner_optimizer.clear_grad(*a, **k)

    clear_gradients = clear_grad

    def get_lr(self):
        return self.inner_optimizer.get_lr()

    def minimize(self, loss, **kw):
        loss.backward()
        self.step()
        return None, None

    def __getattr__(self, item):
        return getattr(self.inner_optimizer, item)


Lookahead = LookaheadOptimizer
