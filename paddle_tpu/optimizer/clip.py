"""Gradient clipping (reference: python/paddle/fluid/clip.py:152,243,345 —
ClipGradByValue/ByNorm/ByGlobalNorm, applied inside optimizer apply).

SelectedRows grads participate like the reference's merge_selected_rows +
get_tensor_from_selected_rows path (fluid/clip.py:406-414): duplicates are
merged, the values contribute to norms, and scaling stays sparse."""
from __future__ import annotations

import jax.numpy as jnp

from ..core.selected_rows import SelectedRows
from ..core.tensor import Tensor


def _merged(g):
    return g.merge() if isinstance(g, SelectedRows) else g


def _sq_sum(g):
    v = g.values if isinstance(g, SelectedRows) else g
    return jnp.sum(jnp.square(v))


class ClipGradBase:
    def __call__(self, params_grads):
        """params_grads: list of (param, grad_array). Returns same structure
        with clipped grads.  Pure w.r.t. arrays → usable under jit."""
        raise NotImplementedError


class ClipGradByValue(ClipGradBase):
    """reference: fluid/clip.py:152."""

    def __init__(self, max, min=None):
        self.max = float(max)
        self.min = float(min) if min is not None else -self.max

    def __call__(self, params_grads):
        def clip(g):
            if isinstance(g, SelectedRows):
                return SelectedRows(g.rows,
                                    jnp.clip(g.values, self.min, self.max),
                                    g.height)
            return jnp.clip(g, self.min, self.max)
        return [(p, clip(_merged(g))) for p, g in params_grads]


class ClipGradByNorm(ClipGradBase):
    """reference: fluid/clip.py:243 — per-tensor norm clip."""

    def __init__(self, clip_norm):
        self.clip_norm = float(clip_norm)

    def __call__(self, params_grads):
        out = []
        for p, g in params_grads:
            g = _merged(g)
            norm = jnp.sqrt(_sq_sum(g))
            scale = jnp.minimum(self.clip_norm / jnp.maximum(norm, 1e-12),
                                1.0)
            out.append((p, g * scale))
        return out


class ClipGradByGlobalNorm(ClipGradBase):
    """reference: fluid/clip.py:345 — joint norm over all grads."""

    def __init__(self, clip_norm, group_name="default_group"):
        self.clip_norm = float(clip_norm)

    def __call__(self, params_grads):
        if not params_grads:
            return params_grads
        params_grads = [(p, _merged(g)) for p, g in params_grads]
        needs = [(p, g) for p, g in params_grads
                 if getattr(p, "need_clip", True)]
        sq = sum(_sq_sum(g) for _, g in needs)
        global_norm = jnp.sqrt(sq)
        scale = self.clip_norm / jnp.maximum(global_norm, self.clip_norm)
        return [(p, g * scale if getattr(p, "need_clip", True) else g)
                for p, g in params_grads]


def clip_grad_norm_(parameters, max_norm, norm_type=2.0,
                    error_if_nonfinite=False):
    """torch-style helper operating on .grad in place."""
    params = [p for p in parameters if p._grad_data is not None]
    if not params:
        return Tensor(jnp.asarray(0.0))
    if norm_type == float("inf"):
        total = jnp.max(jnp.stack(
            [jnp.max(jnp.abs(p._grad_data)) for p in params]))
    else:
        total = jnp.sum(jnp.stack(
            [jnp.sum(jnp.abs(p._grad_data) ** norm_type)
             for p in params])) ** (1.0 / norm_type)
    scale = jnp.minimum(max_norm / jnp.maximum(total, 1e-12), 1.0)
    for p in params:
        p._grad_data = p._grad_data * scale
    return Tensor(total)
