"""paddle_tpu.optimizer (reference: python/paddle/optimizer/ +
operators/optimizers/ kernel zoo — SURVEY §2.1 'Optimizer ops')."""
from . import lr  # noqa: F401
from .clip import (ClipGradByGlobalNorm, ClipGradByNorm,  # noqa: F401
                   ClipGradByValue, clip_grad_norm_)
from .extras import (ExponentialMovingAverage, Lookahead,  # noqa: F401
                     LookaheadOptimizer, ModelAverage)
from .optimizer import (SGD, Adadelta, Adagrad, Adam, Adamax, AdamW,  # noqa
                        DecayedAdagrad, Dpsgd, Ftrl, Lamb, LarsMomentum,
                        Momentum, Optimizer, ProximalAdagrad, ProximalGD,
                        RMSProp)
from .regularizer import L1Decay, L2Decay  # noqa: F401
