"""paddle.quantization — QAT and PTQ.

Reference: python/paddle/quantization/ (QuantConfig, QAT:quanter.py,
PTQ:ptq.py) and the slim fake-quant op zoo
(operators/fake_quantize_op.cc: FakeQuantizeAbsMax,
FakeChannelWiseQuantizeAbsMax, moving-average abs-max observers).

TPU-native design: quantization is SIMULATED in the graph (quantize →
dequantize with a straight-through estimator), exactly like the
reference's fake-quant training ops; the int8 execution engine is XLA's
(int8 dots lower to the MXU natively).  PTQ observers are plain
abs-max/moving-average statistics collected during calibration forwards.
"""
from __future__ import annotations

from typing import List, Optional

import jax
import jax.numpy as jnp

from ..core.dispatch import apply
from ..core.tensor import Tensor
from .. import nn

__all__ = [
    "fake_quantize_abs_max", "fake_channel_wise_quantize_abs_max",
    "QuantConfig", "QAT", "PTQ", "QuantizedLinear", "QuantizedConv2D",
]


# ---------------------------------------------------------------------------
# fake-quant ops (reference: operators/fake_quantize_op.cc)
# ---------------------------------------------------------------------------

def _ste(a, quantized):
    """Straight-through estimator over the whole quantize step: the
    reference's FakeQuantize*Grad ops are pure identity (dx = dout)."""
    return a + jax.lax.stop_gradient(quantized - a)


def _fq_fn(a, *, bits, axis):
    qmax = float(2 ** (bits - 1) - 1)
    if axis is None:
        scale = jnp.max(jnp.abs(a))
    else:
        red = tuple(i for i in range(a.ndim) if i != axis)
        scale = jnp.max(jnp.abs(a), axis=red, keepdims=True)
    scale = jnp.maximum(scale, 1e-8)
    q = jnp.round(jnp.clip(a / scale * qmax, -qmax, qmax))
    return _ste(a, q * scale / qmax)


def fake_quantize_abs_max(x, bit_length: int = 8, name=None):
    """Per-tensor abs-max fake quant (reference: FakeQuantizeAbsMax)."""
    return apply(_fq_fn, x, op_name="fake_quantize_abs_max",
                 cacheable=True, bits=int(bit_length), axis=None)


def fake_channel_wise_quantize_abs_max(x, bit_length: int = 8,
                                       quant_axis: int = 0, name=None):
    """Per-channel abs-max fake quant (reference:
    FakeChannelWiseQuantizeAbsMax)."""
    return apply(_fq_fn, x, op_name="fake_channel_wise_quantize_abs_max",
                 cacheable=True, bits=int(bit_length),
                 axis=int(quant_axis))


def _fq_with_scale_fn(a, scale, *, bits):
    qmax = float(2 ** (bits - 1) - 1)
    scale = jnp.maximum(scale, 1e-8)
    q = jnp.round(jnp.clip(a / scale * qmax, -qmax, qmax))
    return _ste(a, q * scale / qmax)


# ---------------------------------------------------------------------------
# config + quantized layers
# ---------------------------------------------------------------------------

class QuantConfig:
    """reference: quantization/config.py QuantConfig.

    Custom quanter objects (the reference's activation=/weight= quanters)
    are not supported — the built-in scheme is moving-average abs-max
    activations + channel-wise abs-max weights; passing quanters raises
    rather than silently running the wrong scheme."""

    def __init__(self, activation=None, weight=None, weight_bits: int = 8,
                 activation_bits: int = 8, moving_rate: float = 0.9):
        if activation is not None or weight is not None:
            raise NotImplementedError(
                "custom activation/weight quanters are not supported; use "
                "weight_bits/activation_bits/moving_rate to configure the "
                "built-in abs-max scheme")
        self.weight_bits = weight_bits
        self.activation_bits = activation_bits
        self.moving_rate = moving_rate


class _QuantWrapper(nn.Layer):
    """Wraps a layer: fake-quants activations (moving-average abs-max
    observer, reference: FakeQuantizeMovingAverageAbsMax) and weights
    (channel-wise abs-max) around the wrapped forward."""

    def __init__(self, layer: nn.Layer, config: QuantConfig,
                 weight_name: str = "weight"):
        super().__init__()
        self._inner = layer
        self._cfg = config
        self._weight_name = weight_name
        self.register_buffer("act_scale", Tensor(jnp.zeros((),
                                                           jnp.float32)))
        self._observing = False

    def observe(self, flag: bool = True):
        self._observing = flag
        return self

    def forward(self, x):
        cfg = self._cfg
        if self._observing:
            cur = float(jnp.max(jnp.abs(x.data)))
            prev = float(self.act_scale.data)
            r = cfg.moving_rate
            new = cur if prev == 0.0 else (r * prev + (1 - r) * cur)
            self.act_scale.data = jnp.asarray(new, jnp.float32)
        if self.training or not self._observing:
            if float(self.act_scale.data) > 0:
                x = apply(_fq_with_scale_fn, x, self.act_scale,
                          op_name="fake_quantize_moving_average_abs_max",
                          bits=cfg.activation_bits)
            else:
                x = fake_quantize_abs_max(x, cfg.activation_bits)
        w = getattr(self._inner, self._weight_name)
        w_q = fake_channel_wise_quantize_abs_max(
            w, cfg.weight_bits,
            quant_axis=(1 if isinstance(self._inner, nn.Linear) else 0))
        # run the inner layer with the fake-quantized weight
        orig = w.data
        try:
            w.data = w_q.data
            return self._inner(x)
        finally:
            w.data = orig


class QuantizedLinear(_QuantWrapper):
    def __init__(self, layer: nn.Linear, config: Optional[QuantConfig] = None):
        super().__init__(layer, config or QuantConfig())


class QuantizedConv2D(_QuantWrapper):
    def __init__(self, layer: nn.Conv2D, config: Optional[QuantConfig] = None):
        super().__init__(layer, config or QuantConfig())


def _swap_quantable(model: nn.Layer, config: QuantConfig) -> List[str]:
    """Replace Linear/Conv2D sublayers with quant wrappers, in place."""
    swapped = []
    for name, child in list(model.named_children()):
        if isinstance(child, _QuantWrapper):
            continue
        if isinstance(child, nn.Linear):
            setattr(model, name, QuantizedLinear(child, config))
            swapped.append(name)
        elif isinstance(child, nn.Conv2D):
            setattr(model, name, QuantizedConv2D(child, config))
            swapped.append(name)
        else:
            swapped += [f"{name}.{s}" for s in
                        _swap_quantable(child, config)]
    return swapped


class QAT:
    """Quantization-aware training (reference: quantization/qat.py).

    ``quanted = QAT(config).quantize(model)`` swaps Linear/Conv2D for
    fake-quant wrappers; train as usual (STE gradients flow through the
    rounding), then deploy through jit.save — the fake-quant ops are part
    of the exported program."""

    def __init__(self, config: Optional[QuantConfig] = None):
        self.config = config or QuantConfig()

    def quantize(self, model: nn.Layer, inplace: bool = True) -> nn.Layer:
        assert inplace, "QAT.quantize is in-place (pass the model you train)"
        _swap_quantable(model, self.config)
        return model

    convert = staticmethod(lambda model: model)  # fake-quant stays in-graph


class PTQ:
    """Post-training quantization (reference: quantization/ptq.py).

    ``q = PTQ(config).quantize(model)`` inserts observers;
    run calibration batches through the model, then ``PTQ.convert(q)``
    freezes the observed activation scales (weights quantize from their
    values directly)."""

    def __init__(self, config: Optional[QuantConfig] = None):
        self.config = config or QuantConfig()

    def quantize(self, model: nn.Layer, inplace: bool = True) -> nn.Layer:
        assert inplace, "PTQ.quantize is in-place"
        _swap_quantable(model, self.config)
        for w in _wrappers(model):
            w.observe(True)
        model.eval()
        return model

    @staticmethod
    def convert(model: nn.Layer, inplace: bool = True) -> nn.Layer:
        for w in _wrappers(model):
            w.observe(False)
        return model


def _wrappers(model):
    out = []
    for child in model.sublayers(include_self=True):
        if isinstance(child, _QuantWrapper):
            out.append(child)
    return out


# ---------------------------------------------------------------------------
# int8 deployment (VERDICT r4 #8) — reference: contrib/slim post-training
# quant convert flow (quant2_int8 pass): fake-quant programs become real
# int8 weights + scale metadata baked into the jit.save artifact, served
# by the Predictor with int8 MXU matmuls (static activation scales) or
# fused weight-dequant (dynamic).
# ---------------------------------------------------------------------------

def _quantize_weight(w, bits, axis):
    qmax = 2.0 ** (bits - 1) - 1
    red = tuple(i for i in range(w.ndim) if i != axis)
    scale = jnp.maximum(jnp.max(jnp.abs(w), axis=red, keepdims=True), 1e-8)
    q = jnp.round(jnp.clip(w / scale * qmax, -qmax, qmax)).astype(jnp.int8)
    return q, (scale / qmax).astype(jnp.float32)


class Int8Linear(nn.Layer):
    """Deployed int8 linear: int8 weight buffer + per-output-channel
    scales.  With a calibrated activation scale the matmul itself runs
    int8 x int8 -> int32 on the MXU (reference: quant2_int8 mkldnn/TRT
    pass); without one it is weight-only int8 (dequant fused into the
    matmul by XLA)."""

    def __init__(self, linear: nn.Linear, act_scale: float,
                 weight_bits: int = 8, activation_bits: int = 8):
        super().__init__()
        w = linear.weight.data                     # [in, out]
        q, s = _quantize_weight(w, weight_bits, axis=1)
        self.register_buffer("qweight", Tensor(q))
        self.register_buffer("w_scale", Tensor(s.reshape(-1)))
        self.register_buffer("act_scale",
                             Tensor(jnp.asarray(act_scale, jnp.float32)))
        self._act_qmax = 2.0 ** (activation_bits - 1) - 1
        # static-vs-dynamic is a conversion-time property (a calibrated
        # scale exists or not), snapshot it as a Python bool — the buffer
        # is traced at jit time and cannot drive Python control flow
        self._static_act = float(act_scale) > 0.0
        self.bias = (None if linear.bias is None else linear.bias)

    def forward(self, x):
        from ..core.dispatch import apply
        qmax = self._act_qmax
        static = self._static_act
        args = [x, self.qweight, self.w_scale, self.act_scale] + (
            [self.bias] if self.bias is not None else [])

        def fn(a, qw, ws, as_, *mb):
            if static:
                # static int8 activations: int8 x int8 -> int32 MXU path
                aq = jnp.round(jnp.clip(a / as_ * qmax, -qmax, qmax)
                               ).astype(jnp.int8)
                acc = jax.lax.dot_general(
                    aq, qw, (((a.ndim - 1,), (0,)), ((), ())),
                    preferred_element_type=jnp.int32)
                out = acc.astype(jnp.float32) * (as_ / qmax) * ws
            else:
                # weight-only: dequant fused into the matmul epilogue
                out = a @ (qw.astype(a.dtype) * ws.astype(a.dtype))
            if mb:
                out = out + mb[0]
            return out.astype(a.dtype)

        return apply(fn, *args, op_name="int8_linear", nondiff=True)


class Int8Conv2D(nn.Layer):
    """Deployed weight-only int8 conv (per-out-channel scales; dequant
    fuses into the conv)."""

    def __init__(self, conv: nn.Conv2D, act_scale: float,
                 weight_bits: int = 8):
        super().__init__()
        w = conv.weight.data                       # [out, in, kh, kw]
        q, s = _quantize_weight(w, weight_bits, axis=0)
        self.register_buffer("qweight", Tensor(q))
        self.register_buffer("w_scale", Tensor(s))
        self.bias = (None if conv.bias is None else conv.bias)
        # copy conv attrs only — registering the conv itself would keep
        # its f32 weight in the state dict and erase the artifact saving
        self._stride = conv._stride
        self._padding = conv._padding
        self._dilation = conv._dilation
        self._groups = conv._groups
        self._data_format = conv._data_format

    def forward(self, x):
        import paddle_tpu.nn.functional as F
        w = Tensor(self.qweight.data.astype(x.data.dtype)
                   * self.w_scale.data.astype(x.data.dtype))
        return F.conv2d(x, w, self.bias, stride=self._stride,
                        padding=self._padding, dilation=self._dilation,
                        groups=self._groups,
                        data_format=self._data_format)


def convert_to_int8(model: nn.Layer, inplace: bool = True) -> nn.Layer:
    """Replace fake-quant wrappers (QAT/PTQ output) with real int8
    layers whose int8 weights + scales live in the state dict — so
    ``jit.save`` exports an int8 artifact the Predictor serves directly.
    Reference: contrib/slim quant2_int8 conversion."""
    assert inplace, "convert_to_int8 is in-place"
    for name, child in list(model.named_children()):
        if isinstance(child, _QuantWrapper):
            scale = float(child.act_scale.data)
            inner = child._inner
            cfg = child._cfg
            if isinstance(inner, nn.Linear):
                setattr(model, name, Int8Linear(
                    inner, scale, cfg.weight_bits, cfg.activation_bits))
            elif isinstance(inner, nn.Conv2D):
                setattr(model, name, Int8Conv2D(
                    inner, scale, cfg.weight_bits))
        else:
            convert_to_int8(child)
    return model
