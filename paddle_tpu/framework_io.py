"""paddle.save / paddle.load parity.

The reference pickles ``state_dict`` (reference:
python/paddle/framework/io.py:202,292).  We serialise nested containers of
Tensors/ndarrays to a single file: an ``npz`` payload for array data plus a
pickled structure skeleton — no pickled code objects, loadable anywhere.
"""
from __future__ import annotations

import io as _io
import os
import pickle
from typing import Any

import jax.numpy as jnp
import numpy as np

from .core.tensor import Tensor

_MAGIC = b"PDTPU001"


def _flatten(obj, prefix, arrays, skeleton):
    if isinstance(obj, Tensor):
        arrays[prefix] = np.asarray(obj.data)
        return ("__tensor__", prefix)
    if isinstance(obj, np.ndarray):
        arrays[prefix] = obj
        return ("__ndarray__", prefix)
    if hasattr(obj, "dtype") and hasattr(obj, "shape"):  # jax array
        arrays[prefix] = np.asarray(obj)
        return ("__ndarray__", prefix)
    if isinstance(obj, dict):
        return {k: _flatten(v, f"{prefix}.{k}", arrays, skeleton)
                for k, v in obj.items()}
    if isinstance(obj, (list, tuple)):
        t = [_flatten(v, f"{prefix}[{i}]", arrays, skeleton)
             for i, v in enumerate(obj)]
        return tuple(t) if isinstance(obj, tuple) else t
    return ("__leaf__", obj)


def _unflatten(spec, arrays, to_tensor_cls):
    if isinstance(spec, dict):
        return {k: _unflatten(v, arrays, to_tensor_cls) for k, v in spec.items()}
    if isinstance(spec, list):
        return [_unflatten(v, arrays, to_tensor_cls) for v in spec]
    if isinstance(spec, tuple):
        if len(spec) == 2 and spec[0] == "__tensor__":
            return Tensor(jnp.asarray(arrays[spec[1]]))
        if len(spec) == 2 and spec[0] == "__ndarray__":
            return arrays[spec[1]]
        if len(spec) == 2 and spec[0] == "__leaf__":
            return spec[1]
        return tuple(_unflatten(v, arrays, to_tensor_cls) for v in spec)
    return spec


def dumps(obj: Any, protocol: int = 4, encryption_key=None) -> bytes:
    """Serialise to the ``paddle_tpu.save`` wire format in memory —
    checkpoint integrity digests hash exactly these bytes."""
    arrays: dict = {}
    skeleton = _flatten(obj, "r", arrays, None)
    buf = _io.BytesIO()
    np.savez(buf, **{k: v for k, v in arrays.items()})
    out = _io.BytesIO()
    out.write(_MAGIC)
    sk = pickle.dumps(skeleton, protocol=protocol)
    out.write(len(sk).to_bytes(8, "little"))
    out.write(sk)
    out.write(buf.getvalue())
    payload = out.getvalue()
    if encryption_key is not None:
        from .utils import crypto
        payload = crypto.encrypt(payload, encryption_key)
    return payload


def loads(payload: bytes, encryption_key=None, source: str = "<bytes>") -> Any:
    from .utils import crypto
    if crypto.is_encrypted(payload[:8]):
        if encryption_key is None:
            raise ValueError(
                f"'{source}' is encrypted — pass encryption_key= to load")
        payload = crypto.decrypt(payload, encryption_key)
    f = _io.BytesIO(payload)
    magic = f.read(8)
    if magic != _MAGIC:
        # fall back: plain pickle (reference-compatible style)
        f.seek(0)
        return pickle.load(f)
    n = int.from_bytes(f.read(8), "little")
    skeleton = pickle.loads(f.read(n))
    arrays = dict(np.load(_io.BytesIO(f.read()), allow_pickle=False))
    return _unflatten(skeleton, arrays, Tensor)


def save(obj: Any, path: str, protocol: int = 4, encryption_key=None,
         **configs):
    """paddle.save parity: state_dicts, nested dict/list of tensors,
    scalars.  ``path`` may carry a registered filesystem scheme
    (``hdfs://...`` — utils/fs.py, reference framework/io/fs.cc);
    ``encryption_key`` encrypts the artifact at rest (AES-256-GCM,
    reference framework/io/crypto).  The artifact lands via tmp-file +
    rename, so a crash mid-save never leaves a truncated ``.pdparams``
    (atomic on LocalFS; best-effort delete+rename on ShellFS)."""
    payload = dumps(obj, protocol=protocol, encryption_key=encryption_key)
    from .utils import fs as _fs
    _fs.write_atomic(path, payload)


def load(path: str, encryption_key=None, **configs) -> Any:
    from .utils import fs as _fs
    with _fs.open_read(path) as f:
        payload = f.read()
    return loads(payload, encryption_key=encryption_key, source=path)
