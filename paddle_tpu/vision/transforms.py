"""Image transforms over numpy CHW arrays (reference:
python/paddle/vision/transforms/)."""
from __future__ import annotations

import numbers

import numpy as np


class Compose:
    def __init__(self, transforms):
        self.transforms = list(transforms)

    def __call__(self, img):
        for t in self.transforms:
            img = t(img)
        return img


class BaseTransform:
    def __call__(self, img):
        return self._apply_image(np.asarray(img))

    def _apply_image(self, img):
        raise NotImplementedError


class ToTensor(BaseTransform):
    """HWC uint8 [0,255] → CHW float32 [0,1]."""

    def __init__(self, data_format="CHW"):
        self.data_format = data_format

    def _apply_image(self, img):
        if img.ndim == 2:
            img = img[..., None]
        if img.dtype == np.uint8:
            img = img.astype(np.float32) / 255.0
        if self.data_format == "CHW" and img.shape[-1] in (1, 3, 4):
            img = np.transpose(img, (2, 0, 1))
        return img.astype(np.float32)


class Normalize(BaseTransform):
    def __init__(self, mean=0.0, std=1.0, data_format="CHW", to_rgb=False):
        self.mean = np.asarray(mean, np.float32)
        self.std = np.asarray(std, np.float32)
        self.data_format = data_format

    def _apply_image(self, img):
        mean, std = self.mean, self.std
        if self.data_format == "CHW":
            shape = (-1,) + (1,) * (img.ndim - 1)
        else:
            shape = (1,) * (img.ndim - 1) + (-1,)
        return ((img - mean.reshape(shape)) /
                std.reshape(shape)).astype(np.float32)


class Transpose(BaseTransform):
    def __init__(self, order=(2, 0, 1)):
        self.order = tuple(order)

    def _apply_image(self, img):
        return np.transpose(img, self.order)


def _chw(img):
    return img.ndim == 3 and img.shape[0] in (1, 3, 4)


class Resize(BaseTransform):
    def __init__(self, size, interpolation="bilinear"):
        self.size = ((size, size) if isinstance(size, numbers.Number)
                     else tuple(size))

    def _apply_image(self, img):
        import jax
        import jax.numpy as jnp
        chw = _chw(img)
        a = jnp.asarray(img)
        if chw:
            out = jax.image.resize(a, (a.shape[0], *self.size), "linear")
        else:
            out = jax.image.resize(a, (*self.size, a.shape[-1]), "linear")
        return np.asarray(out)


class CenterCrop(BaseTransform):
    def __init__(self, size):
        self.size = ((size, size) if isinstance(size, numbers.Number)
                     else tuple(size))

    def _apply_image(self, img):
        th, tw = self.size
        if _chw(img):
            h, w = img.shape[1:]
            i, j = (h - th) // 2, (w - tw) // 2
            return img[:, i:i + th, j:j + tw]
        h, w = img.shape[:2]
        i, j = (h - th) // 2, (w - tw) // 2
        return img[i:i + th, j:j + tw]


class RandomCrop(BaseTransform):
    def __init__(self, size, padding=None, pad_if_needed=False):
        self.size = ((size, size) if isinstance(size, numbers.Number)
                     else tuple(size))
        self.padding = padding

    def _apply_image(self, img):
        th, tw = self.size
        chw = _chw(img)
        if self.padding:
            p = self.padding
            pad = ((0, 0), (p, p), (p, p)) if chw else ((p, p), (p, p),
                                                        (0, 0))
            img = np.pad(img, pad[:img.ndim], mode="constant")
        h, w = img.shape[1:] if chw else img.shape[:2]
        i = np.random.randint(0, h - th + 1)
        j = np.random.randint(0, w - tw + 1)
        if chw:
            return img[:, i:i + th, j:j + tw]
        return img[i:i + th, j:j + tw]


class RandomHorizontalFlip(BaseTransform):
    def __init__(self, prob=0.5):
        self.prob = prob

    def _apply_image(self, img):
        if np.random.rand() < self.prob:
            return img[..., ::-1].copy() if _chw(img) else \
                img[:, ::-1].copy()
        return img


class RandomVerticalFlip(BaseTransform):
    def __init__(self, prob=0.5):
        self.prob = prob

    def _apply_image(self, img):
        if np.random.rand() < self.prob:
            return (img[:, ::-1].copy() if _chw(img)
                    else img[::-1].copy())
        return img


class BrightnessTransform(BaseTransform):
    def __init__(self, value):
        self.value = value

    def _apply_image(self, img):
        alpha = 1 + np.random.uniform(-self.value, self.value)
        out = img.astype(np.float32) * alpha
        if np.issubdtype(np.asarray(img).dtype, np.integer):
            return np.clip(out, 0, 255).astype(img.dtype)
        return np.clip(out, 0, 1).astype(np.float32)


class Pad(BaseTransform):
    def __init__(self, padding, fill=0, padding_mode="constant"):
        self.padding = (padding if isinstance(padding, (list, tuple))
                        else (padding,) * 4)
        self.fill = fill

    def _apply_image(self, img):
        l, t, r, b = (self.padding * 2)[:4] if len(self.padding) == 2 \
            else self.padding
        if _chw(img):
            return np.pad(img, ((0, 0), (t, b), (l, r)),
                          constant_values=self.fill)
        return np.pad(img, ((t, b), (l, r)) + ((0, 0),) * (img.ndim - 2),
                      constant_values=self.fill)


# -- round-4 breadth (reference: transforms/transforms.py full suite) -----

class ContrastTransform(BaseTransform):
    """reference: transforms.py ContrastTransform — blend with the mean."""

    def __init__(self, value):
        self.value = float(value)

    def _apply_image(self, img):
        import random
        f = 1.0 + random.uniform(-self.value, self.value)
        x = img.astype(np.float32)
        mean = x.mean()
        out = mean + (x - mean) * f
        return _like(out, img)


class SaturationTransform(BaseTransform):
    """Blend with the grayscale image (HWC or CHW, 3 channels)."""

    def __init__(self, value):
        self.value = float(value)

    def _apply_image(self, img):
        import random
        f = 1.0 + random.uniform(-self.value, self.value)
        x = img.astype(np.float32)
        gray = _to_gray(x)
        out = gray + (x - gray) * f
        return _like(out, img)


class HueTransform(BaseTransform):
    """Channel-roll hue approximation in RGB space (value in [0, 0.5])."""

    def __init__(self, value):
        self.value = float(value)

    def _apply_image(self, img):
        import random
        f = random.uniform(-self.value, self.value)
        x = img.astype(np.float32)
        ch_axis = 0 if x.shape[0] in (1, 3) else -1
        if x.shape[ch_axis] != 3:
            return img
        other = x.sum(axis=ch_axis, keepdims=True) - x
        out = x + f * (other / 2.0 - x)
        return _like(out, img)


class ColorJitter(BaseTransform):
    """reference: transforms.py ColorJitter — random order of
    brightness/contrast/saturation/hue."""

    def __init__(self, brightness=0, contrast=0, saturation=0, hue=0):
        self.ts = []
        if brightness:
            self.ts.append(BrightnessTransform(brightness))
        if contrast:
            self.ts.append(ContrastTransform(contrast))
        if saturation:
            self.ts.append(SaturationTransform(saturation))
        if hue:
            self.ts.append(HueTransform(hue))

    def _apply_image(self, img):
        import random
        order = list(self.ts)
        random.shuffle(order)
        for t in order:
            img = t._apply_image(img)
        return img


class Grayscale(BaseTransform):
    def __init__(self, num_output_channels=1):
        self.n = num_output_channels

    def _apply_image(self, img):
        x = img.astype(np.float32)
        gray = _to_gray(x)
        ch_axis = 0 if x.shape[0] in (1, 3) else -1
        take = [0] * self.n
        out = np.take(gray, take, axis=ch_axis)
        return _like(out, img)


class RandomResizedCrop(BaseTransform):
    """reference: transforms.py RandomResizedCrop (scale/ratio sampling,
    resize to target)."""

    def __init__(self, size, scale=(0.08, 1.0), ratio=(3 / 4, 4 / 3)):
        self.size = (size, size) if isinstance(size, int) else tuple(size)
        self.scale = scale
        self.ratio = ratio

    def _apply_image(self, img):
        import random
        chw = img.ndim == 3 and img.shape[0] in (1, 3)
        h, w = (img.shape[1:], img.shape[:2])[0 if chw else 1]
        area = h * w
        for _ in range(10):
            target = random.uniform(*self.scale) * area
            ar = random.uniform(*self.ratio)
            cw = int(round((target * ar) ** 0.5))
            ch = int(round((target / ar) ** 0.5))
            if 0 < cw <= w and 0 < ch <= h:
                top = random.randint(0, h - ch)
                left = random.randint(0, w - cw)
                if chw:
                    crop = img[:, top:top + ch, left:left + cw]
                else:
                    crop = img[top:top + ch, left:left + cw]
                return Resize(self.size)._apply_image(crop)
        return Resize(self.size)._apply_image(img)


class RandomRotation(BaseTransform):
    """Nearest-neighbour rotation about the center (reference
    RandomRotation without the PIL resample modes)."""

    def __init__(self, degrees):
        self.degrees = ((-degrees, degrees)
                        if isinstance(degrees, numbers.Number)
                        else tuple(degrees))

    def _apply_image(self, img):
        import random
        ang = np.deg2rad(random.uniform(*self.degrees))
        chw = img.ndim == 3 and img.shape[0] in (1, 3)
        x = img if chw else (np.moveaxis(img, -1, 0)
                             if img.ndim == 3 else img[None])
        C, H, W = x.shape
        cy, cx = (H - 1) / 2.0, (W - 1) / 2.0
        yy, xx = np.mgrid[0:H, 0:W]
        ys = cy + (yy - cy) * np.cos(ang) - (xx - cx) * np.sin(ang)
        xs = cx + (yy - cy) * np.sin(ang) + (xx - cx) * np.cos(ang)
        yi = np.clip(np.round(ys).astype(int), 0, H - 1)
        xi = np.clip(np.round(xs).astype(int), 0, W - 1)
        valid = (ys >= 0) & (ys <= H - 1) & (xs >= 0) & (xs <= W - 1)
        out = x[:, yi, xi] * valid[None]
        out = out.astype(img.dtype)
        if chw:
            return out
        return np.moveaxis(out, 0, -1) if img.ndim == 3 else out[0]


class RandomErasing(BaseTransform):
    """reference: transforms.py RandomErasing — cutout regularizer."""

    def __init__(self, prob=0.5, scale=(0.02, 0.33), ratio=(0.3, 3.3),
                 value=0):
        self.prob = prob
        self.scale = scale
        self.ratio = ratio
        self.value = value

    def _apply_image(self, img):
        import random
        if random.random() > self.prob:
            return img
        chw = img.ndim == 3 and img.shape[0] in (1, 3)
        h, w = (img.shape[1:] if chw else img.shape[:2])
        area = h * w
        for _ in range(10):
            target = random.uniform(*self.scale) * area
            ar = random.uniform(*self.ratio)
            eh = int(round((target / ar) ** 0.5))
            ew = int(round((target * ar) ** 0.5))
            if eh < h and ew < w:
                top = random.randint(0, h - eh)
                left = random.randint(0, w - ew)
                out = img.copy()
                if chw:
                    out[:, top:top + eh, left:left + ew] = self.value
                else:
                    out[top:top + eh, left:left + ew] = self.value
                return out
        return img


def _to_gray(x):
    ch_axis = 0 if x.shape[0] in (1, 3) else -1
    if x.shape[ch_axis] == 1:
        return x
    wts = np.asarray([0.299, 0.587, 0.114], np.float32)
    shape = [1, 1, 1]
    shape[ch_axis] = 3
    g = (x * wts.reshape(shape)).sum(axis=ch_axis, keepdims=True)
    return np.repeat(g, 3, axis=ch_axis)


def _like(out, img):
    if np.issubdtype(img.dtype, np.integer):
        return np.clip(out, 0, 255).astype(img.dtype)
    return out.astype(img.dtype)
