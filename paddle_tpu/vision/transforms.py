"""Image transforms over numpy CHW arrays (reference:
python/paddle/vision/transforms/)."""
from __future__ import annotations

import numbers

import numpy as np


class Compose:
    def __init__(self, transforms):
        self.transforms = list(transforms)

    def __call__(self, img):
        for t in self.transforms:
            img = t(img)
        return img


class BaseTransform:
    def __call__(self, img):
        return self._apply_image(np.asarray(img))

    def _apply_image(self, img):
        raise NotImplementedError


class ToTensor(BaseTransform):
    """HWC uint8 [0,255] → CHW float32 [0,1]."""

    def __init__(self, data_format="CHW"):
        self.data_format = data_format

    def _apply_image(self, img):
        if img.ndim == 2:
            img = img[..., None]
        if img.dtype == np.uint8:
            img = img.astype(np.float32) / 255.0
        if self.data_format == "CHW" and img.shape[-1] in (1, 3, 4):
            img = np.transpose(img, (2, 0, 1))
        return img.astype(np.float32)


class Normalize(BaseTransform):
    def __init__(self, mean=0.0, std=1.0, data_format="CHW", to_rgb=False):
        self.mean = np.asarray(mean, np.float32)
        self.std = np.asarray(std, np.float32)
        self.data_format = data_format

    def _apply_image(self, img):
        mean, std = self.mean, self.std
        if self.data_format == "CHW":
            shape = (-1,) + (1,) * (img.ndim - 1)
        else:
            shape = (1,) * (img.ndim - 1) + (-1,)
        return ((img - mean.reshape(shape)) /
                std.reshape(shape)).astype(np.float32)


class Transpose(BaseTransform):
    def __init__(self, order=(2, 0, 1)):
        self.order = tuple(order)

    def _apply_image(self, img):
        return np.transpose(img, self.order)


def _chw(img):
    return img.ndim == 3 and img.shape[0] in (1, 3, 4)


class Resize(BaseTransform):
    def __init__(self, size, interpolation="bilinear"):
        self.size = ((size, size) if isinstance(size, numbers.Number)
                     else tuple(size))

    def _apply_image(self, img):
        import jax
        import jax.numpy as jnp
        chw = _chw(img)
        a = jnp.asarray(img)
        if chw:
            out = jax.image.resize(a, (a.shape[0], *self.size), "linear")
        else:
            out = jax.image.resize(a, (*self.size, a.shape[-1]), "linear")
        return np.asarray(out)


class CenterCrop(BaseTransform):
    def __init__(self, size):
        self.size = ((size, size) if isinstance(size, numbers.Number)
                     else tuple(size))

    def _apply_image(self, img):
        th, tw = self.size
        if _chw(img):
            h, w = img.shape[1:]
            i, j = (h - th) // 2, (w - tw) // 2
            return img[:, i:i + th, j:j + tw]
        h, w = img.shape[:2]
        i, j = (h - th) // 2, (w - tw) // 2
        return img[i:i + th, j:j + tw]


class RandomCrop(BaseTransform):
    def __init__(self, size, padding=None, pad_if_needed=False):
        self.size = ((size, size) if isinstance(size, numbers.Number)
                     else tuple(size))
        self.padding = padding

    def _apply_image(self, img):
        th, tw = self.size
        chw = _chw(img)
        if self.padding:
            p = self.padding
            pad = ((0, 0), (p, p), (p, p)) if chw else ((p, p), (p, p),
                                                        (0, 0))
            img = np.pad(img, pad[:img.ndim], mode="constant")
        h, w = img.shape[1:] if chw else img.shape[:2]
        i = np.random.randint(0, h - th + 1)
        j = np.random.randint(0, w - tw + 1)
        if chw:
            return img[:, i:i + th, j:j + tw]
        return img[i:i + th, j:j + tw]


class RandomHorizontalFlip(BaseTransform):
    def __init__(self, prob=0.5):
        self.prob = prob

    def _apply_image(self, img):
        if np.random.rand() < self.prob:
            return img[..., ::-1].copy() if _chw(img) else \
                img[:, ::-1].copy()
        return img


class RandomVerticalFlip(BaseTransform):
    def __init__(self, prob=0.5):
        self.prob = prob

    def _apply_image(self, img):
        if np.random.rand() < self.prob:
            return (img[:, ::-1].copy() if _chw(img)
                    else img[::-1].copy())
        return img


class BrightnessTransform(BaseTransform):
    def __init__(self, value):
        self.value = value

    def _apply_image(self, img):
        alpha = 1 + np.random.uniform(-self.value, self.value)
        return np.clip(img * alpha, 0, 1).astype(np.float32)


class Pad(BaseTransform):
    def __init__(self, padding, fill=0, padding_mode="constant"):
        self.padding = (padding if isinstance(padding, (list, tuple))
                        else (padding,) * 4)
        self.fill = fill

    def _apply_image(self, img):
        l, t, r, b = (self.padding * 2)[:4] if len(self.padding) == 2 \
            else self.padding
        if _chw(img):
            return np.pad(img, ((0, 0), (t, b), (l, r)),
                          constant_values=self.fill)
        return np.pad(img, ((t, b), (l, r)) + ((0, 0),) * (img.ndim - 2),
                      constant_values=self.fill)
