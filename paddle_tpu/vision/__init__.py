"""paddle_tpu.vision (reference: python/paddle/vision/ — datasets, models,
transforms; SURVEY §2.4)."""
from . import datasets  # noqa: F401
from . import models  # noqa: F401
from . import transforms  # noqa: F401
from . import ops  # noqa: F401,E402
