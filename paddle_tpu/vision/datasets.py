"""Vision datasets (reference: python/paddle/vision/datasets/: mnist.py,
cifar.py, flowers.py, voc2012.py).

Zero-egress environment: datasets load from a local ``data_file``/``image_path``
when given, else generate a DETERMINISTIC synthetic stand-in with the real
shapes/classes (documented divergence — the reference downloads from
dataset.bj.bcebos.com, which is unreachable here).  Synthetic mode keeps all
pipelines (transforms, loaders, training scripts) runnable end-to-end."""
from __future__ import annotations

import gzip
import os
import struct

import numpy as np

from ..io import Dataset


def _synthetic_images(n, shape, num_classes, seed):
    rs = np.random.RandomState(seed)
    labels = rs.randint(0, num_classes, n).astype(np.int64)
    imgs = rs.rand(n, *shape).astype(np.float32)
    # make images weakly class-dependent so models can actually learn
    for c in range(num_classes):
        mask = labels == c
        imgs[mask] += 0.5 * np.sin(
            np.linspace(0, 3.14 * (c + 1), int(np.prod(shape)))
        ).reshape(shape).astype(np.float32)
    return imgs, labels


class MNIST(Dataset):
    """reference: vision/datasets/mnist.py.  Reads idx-format files when
    ``image_path``/``label_path`` provided; synthetic otherwise."""

    def __init__(self, image_path=None, label_path=None, mode="train",
                 transform=None, download=True, backend="cv2"):
        self.mode = mode
        self.transform = transform
        if image_path and os.path.exists(image_path):
            with gzip.open(image_path, "rb") as f:
                magic, n, rows, cols = struct.unpack(">IIII", f.read(16))
                self.images = np.frombuffer(
                    f.read(), np.uint8).reshape(n, rows, cols).astype(
                        np.float32) / 255.0
            with gzip.open(label_path, "rb") as f:
                struct.unpack(">II", f.read(8))
                self.labels = np.frombuffer(f.read(), np.uint8).astype(
                    np.int64)
        else:
            n = 6000 if mode == "train" else 1000
            self.images, self.labels = _synthetic_images(
                n, (28, 28), 10, seed=0 if mode == "train" else 1)

    def __getitem__(self, idx):
        img = self.images[idx][None]  # [1, 28, 28]
        if self.transform is not None:
            img = self.transform(img)
        return img.astype(np.float32), np.asarray(self.labels[idx])

    def __len__(self):
        return len(self.images)


class FashionMNIST(MNIST):
    pass


class Cifar10(Dataset):
    """reference: vision/datasets/cifar.py."""

    NUM_CLASSES = 10

    def __init__(self, data_file=None, mode="train", transform=None,
                 download=True, backend="cv2"):
        self.transform = transform
        if data_file and os.path.exists(data_file):
            import pickle
            import tarfile
            imgs, labels = [], []
            with tarfile.open(data_file) as tf:
                names = [m for m in tf.getnames()
                         if ("data_batch" in m if mode == "train"
                             else "test_batch" in m)]
                for name in sorted(names):
                    d = pickle.load(tf.extractfile(name), encoding="bytes")
                    imgs.append(d[b"data"])
                    labels.extend(d.get(b"labels", d.get(b"fine_labels")))
            self.images = (np.concatenate(imgs).reshape(-1, 3, 32, 32)
                           .astype(np.float32) / 255.0)
            self.labels = np.asarray(labels, np.int64)
        else:
            n = 5000 if mode == "train" else 1000
            self.images, self.labels = _synthetic_images(
                n, (3, 32, 32), self.NUM_CLASSES,
                seed=2 if mode == "train" else 3)

    def __getitem__(self, idx):
        img = self.images[idx]
        if self.transform is not None:
            img = self.transform(img)
        return img.astype(np.float32), np.asarray(self.labels[idx])

    def __len__(self):
        return len(self.images)


class Cifar100(Cifar10):
    NUM_CLASSES = 100


class DatasetFolder(Dataset):
    """Class-per-subdirectory dataset (reference:
    vision/datasets/folder.py DatasetFolder): ``root/<class>/<file>``
    layouts, with classes sorted alphabetically into label ids.

    Supports ``.npy`` arrays natively and standard image files via PIL
    when installed (the reference uses cv2/PIL loaders)."""

    IMG_EXTS = (".jpg", ".jpeg", ".png", ".bmp", ".webp")

    def __init__(self, root, loader=None, extensions=None, transform=None,
                 is_valid_file=None):
        import os
        self.root = root
        self.transform = transform
        self.loader = loader or self._default_loader
        exts = tuple(e.lower() for e in (extensions or
                                         (".npy",) + self.IMG_EXTS))
        classes = sorted(d for d in os.listdir(root)
                         if os.path.isdir(os.path.join(root, d)))
        if not classes:
            raise ValueError(
                f"DatasetFolder: no class subdirectories under {root}")
        self.classes = classes
        self.class_to_idx = {c: i for i, c in enumerate(classes)}
        self.samples = []
        for c in classes:
            cdir = os.path.join(root, c)
            for dirpath, _, files in sorted(os.walk(cdir)):
                for fn in sorted(files):
                    path = os.path.join(dirpath, fn)
                    ok = (is_valid_file(path) if is_valid_file
                          else fn.lower().endswith(exts))
                    if ok:
                        self.samples.append((path, self.class_to_idx[c]))
        if not self.samples:
            raise ValueError(
                f"DatasetFolder: no files with extensions {exts} under "
                f"{root}")

    @staticmethod
    def _default_loader(path):
        if path.lower().endswith(".npy"):
            return np.load(path)
        try:
            from PIL import Image
        except ImportError as e:
            raise RuntimeError(
                f"loading {path} needs PIL (or pass loader=)") from e
        with Image.open(path) as im:
            return np.asarray(im.convert("RGB"))

    def __getitem__(self, idx):
        path, label = self.samples[idx]
        img = self.loader(path)
        if self.transform is not None:
            img = self.transform(img)
        return img, np.int64(label)

    def __len__(self):
        return len(self.samples)


class ImageFolder(DatasetFolder):
    """reference: folder.py ImageFolder — unlabeled flat/recursive image
    tree; __getitem__ returns just the image."""

    def __init__(self, root, loader=None, extensions=None, transform=None,
                 is_valid_file=None):
        import os
        self.root = root
        self.transform = transform
        self.loader = loader or self._default_loader
        exts = tuple(e.lower() for e in (extensions or
                                         (".npy",) + self.IMG_EXTS))
        self.samples = []
        for dirpath, _, files in sorted(os.walk(root)):
            for fn in sorted(files):
                path = os.path.join(dirpath, fn)
                ok = (is_valid_file(path) if is_valid_file
                      else fn.lower().endswith(exts))
                if ok:
                    self.samples.append((path, -1))
        if not self.samples:
            raise ValueError(f"ImageFolder: no images under {root}")

    def __getitem__(self, idx):
        path, _ = self.samples[idx]
        img = self.loader(path)
        if self.transform is not None:
            img = self.transform(img)
        return img
