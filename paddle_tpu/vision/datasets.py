"""Vision datasets (reference: python/paddle/vision/datasets/: mnist.py,
cifar.py, flowers.py, voc2012.py).

Zero-egress environment: datasets load from a local ``data_file``/``image_path``
when given, else generate a DETERMINISTIC synthetic stand-in with the real
shapes/classes (documented divergence — the reference downloads from
dataset.bj.bcebos.com, which is unreachable here).  Synthetic mode keeps all
pipelines (transforms, loaders, training scripts) runnable end-to-end."""
from __future__ import annotations

import gzip
import os
import struct

import numpy as np

from ..io import Dataset


def _synthetic_images(n, shape, num_classes, seed):
    rs = np.random.RandomState(seed)
    labels = rs.randint(0, num_classes, n).astype(np.int64)
    imgs = rs.rand(n, *shape).astype(np.float32)
    # make images weakly class-dependent so models can actually learn
    for c in range(num_classes):
        mask = labels == c
        imgs[mask] += 0.5 * np.sin(
            np.linspace(0, 3.14 * (c + 1), int(np.prod(shape)))
        ).reshape(shape).astype(np.float32)
    return imgs, labels


class MNIST(Dataset):
    """reference: vision/datasets/mnist.py.  Reads idx-format files when
    ``image_path``/``label_path`` provided; synthetic otherwise."""

    def __init__(self, image_path=None, label_path=None, mode="train",
                 transform=None, download=True, backend="cv2"):
        self.mode = mode
        self.transform = transform
        if image_path and os.path.exists(image_path):
            with gzip.open(image_path, "rb") as f:
                magic, n, rows, cols = struct.unpack(">IIII", f.read(16))
                self.images = np.frombuffer(
                    f.read(), np.uint8).reshape(n, rows, cols).astype(
                        np.float32) / 255.0
            with gzip.open(label_path, "rb") as f:
                struct.unpack(">II", f.read(8))
                self.labels = np.frombuffer(f.read(), np.uint8).astype(
                    np.int64)
        else:
            n = 6000 if mode == "train" else 1000
            self.images, self.labels = _synthetic_images(
                n, (28, 28), 10, seed=0 if mode == "train" else 1)

    def __getitem__(self, idx):
        img = self.images[idx][None]  # [1, 28, 28]
        if self.transform is not None:
            img = self.transform(img)
        return img.astype(np.float32), np.asarray(self.labels[idx])

    def __len__(self):
        return len(self.images)


class FashionMNIST(MNIST):
    pass


class Cifar10(Dataset):
    """reference: vision/datasets/cifar.py."""

    NUM_CLASSES = 10

    def __init__(self, data_file=None, mode="train", transform=None,
                 download=True, backend="cv2"):
        self.transform = transform
        if data_file and os.path.exists(data_file):
            import pickle
            import tarfile
            imgs, labels = [], []
            with tarfile.open(data_file) as tf:
                names = [m for m in tf.getnames()
                         if ("data_batch" in m if mode == "train"
                             else "test_batch" in m)]
                for name in sorted(names):
                    d = pickle.load(tf.extractfile(name), encoding="bytes")
                    imgs.append(d[b"data"])
                    labels.extend(d.get(b"labels", d.get(b"fine_labels")))
            self.images = (np.concatenate(imgs).reshape(-1, 3, 32, 32)
                           .astype(np.float32) / 255.0)
            self.labels = np.asarray(labels, np.int64)
        else:
            n = 5000 if mode == "train" else 1000
            self.images, self.labels = _synthetic_images(
                n, (3, 32, 32), self.NUM_CLASSES,
                seed=2 if mode == "train" else 3)

    def __getitem__(self, idx):
        img = self.images[idx]
        if self.transform is not None:
            img = self.transform(img)
        return img.astype(np.float32), np.asarray(self.labels[idx])

    def __len__(self):
        return len(self.images)


class Cifar100(Cifar10):
    NUM_CLASSES = 100


class DatasetFolder(Dataset):
    """Class-per-subdirectory dataset (reference:
    vision/datasets/folder.py DatasetFolder): ``root/<class>/<file>``
    layouts, with classes sorted alphabetically into label ids.

    Supports ``.npy`` arrays natively and standard image files via PIL
    when installed (the reference uses cv2/PIL loaders)."""

    IMG_EXTS = (".jpg", ".jpeg", ".png", ".bmp", ".webp")

    def __init__(self, root, loader=None, extensions=None, transform=None,
                 is_valid_file=None):
        import os
        self.root = root
        self.transform = transform
        self.loader = loader or self._default_loader
        exts = tuple(e.lower() for e in (extensions or
                                         (".npy",) + self.IMG_EXTS))
        classes = sorted(d for d in os.listdir(root)
                         if os.path.isdir(os.path.join(root, d)))
        if not classes:
            raise ValueError(
                f"DatasetFolder: no class subdirectories under {root}")
        self.classes = classes
        self.class_to_idx = {c: i for i, c in enumerate(classes)}
        self.samples = []
        for c in classes:
            cdir = os.path.join(root, c)
            for dirpath, _, files in sorted(os.walk(cdir)):
                for fn in sorted(files):
                    path = os.path.join(dirpath, fn)
                    ok = (is_valid_file(path) if is_valid_file
                          else fn.lower().endswith(exts))
                    if ok:
                        self.samples.append((path, self.class_to_idx[c]))
        if not self.samples:
            raise ValueError(
                f"DatasetFolder: no files with extensions {exts} under "
                f"{root}")

    @staticmethod
    def _default_loader(path):
        if path.lower().endswith(".npy"):
            return np.load(path)
        try:
            from PIL import Image
        except ImportError as e:
            raise RuntimeError(
                f"loading {path} needs PIL (or pass loader=)") from e
        with Image.open(path) as im:
            return np.asarray(im.convert("RGB"))

    def __getitem__(self, idx):
        path, label = self.samples[idx]
        img = self.loader(path)
        if self.transform is not None:
            img = self.transform(img)
        return img, np.int64(label)

    def __len__(self):
        return len(self.samples)


class ImageFolder(DatasetFolder):
    """reference: folder.py ImageFolder — unlabeled flat/recursive image
    tree; __getitem__ returns just the image."""

    def __init__(self, root, loader=None, extensions=None, transform=None,
                 is_valid_file=None):
        import os
        self.root = root
        self.transform = transform
        self.loader = loader or self._default_loader
        exts = tuple(e.lower() for e in (extensions or
                                         (".npy",) + self.IMG_EXTS))
        self.samples = []
        for dirpath, _, files in sorted(os.walk(root)):
            for fn in sorted(files):
                path = os.path.join(dirpath, fn)
                ok = (is_valid_file(path) if is_valid_file
                      else fn.lower().endswith(exts))
                if ok:
                    self.samples.append((path, -1))
        if not self.samples:
            raise ValueError(f"ImageFolder: no images under {root}")

    def __getitem__(self, idx):
        path, _ = self.samples[idx]
        img = self.loader(path)
        if self.transform is not None:
            img = self.transform(img)
        return img


class _LazyTarReader:
    """Per-thread tarfile handles over one archive path: a single shared
    TarFile is neither picklable (DataLoader worker processes) nor
    thread-safe (the prefetch threads seek one shared offset).  TarInfo
    members carry their own data offsets, so any handle can serve any
    member; the handle cache is excluded from pickling."""

    def _init_tar(self, data_file):
        import tarfile
        import threading
        self._tar_path = self._ensure_seekable(data_file)
        self._tar_local = threading.local()
        with tarfile.open(self._tar_path) as tf:
            self.name2mem = {m.name: m for m in tf.getmembers()}

    # archive identity -> decompressed temp path (one decompression per
    # archive even across train/valid/test splits)
    _SEEKABLE_CACHE: dict = {}

    @classmethod
    def _ensure_seekable(cls, data_file):
        """gzip has no random access: a seek backwards inside a .tgz
        re-decompresses from byte 0, making shuffled epochs
        quasi-quadratic.  Decompress ONCE to an uncompressed temp tar
        and serve offsets from that (deleted at interpreter exit)."""
        import gzip as _gz
        with open(data_file, "rb") as f:
            magic = f.read(2)
        if magic != b"\x1f\x8b":
            return data_file
        st = os.stat(data_file)
        key = (os.path.abspath(data_file), st.st_size, st.st_mtime_ns)
        cached = cls._SEEKABLE_CACHE.get(key)
        if cached is not None and os.path.exists(cached):
            return cached
        import atexit
        import shutil
        import tempfile
        tmp = tempfile.NamedTemporaryFile(suffix=".tar", delete=False)
        with _gz.open(data_file, "rb") as src:
            shutil.copyfileobj(src, tmp)
        tmp.close()
        atexit.register(lambda p=tmp.name: os.path.exists(p)
                        and os.unlink(p))
        cls._SEEKABLE_CACHE[key] = tmp.name
        return tmp.name

    def _read_member(self, name):
        import tarfile
        tf = getattr(self._tar_local, "tf", None)
        if tf is None:
            tf = tarfile.open(self._tar_path)
            self._tar_local.tf = tf
        return tf.extractfile(self.name2mem[name]).read()

    def __getstate__(self):
        state = dict(self.__dict__)
        state.pop("_tar_local", None)
        return state

    def __setstate__(self, state):
        import threading
        self.__dict__.update(state)
        self._tar_local = threading.local()


class Flowers(_LazyTarReader, Dataset):
    """reference: vision/datasets/flowers.py:47 (102flowers jpg tarball +
    imagelabels.mat 'labels' + setid.mat subset indices; NOTE the
    reference maps train->'tstid' and test->'trnid' on purpose — the
    official split has more test data, flowers.py:37-40).  Images decode
    lazily per __getitem__, exactly like the reference."""

    MODE_FLAG_MAP = {"train": "tstid", "test": "trnid", "valid": "valid"}

    def __init__(self, data_file=None, label_file=None, setid_file=None,
                 mode="train", transform=None, download=True,
                 backend="cv2"):
        assert mode.lower() in self.MODE_FLAG_MAP, mode
        self.flag = self.MODE_FLAG_MAP[mode.lower()]
        self.transform = transform
        for name, p in (("data_file", data_file),
                        ("label_file", label_file),
                        ("setid_file", setid_file)):
            if p is None or not os.path.exists(p):
                raise ValueError(
                    f"Flowers: {name} must point at a local file "
                    f"(102flowers.tgz / imagelabels.mat / setid.mat; no "
                    f"downloads in this environment), got {p!r}")
        import scipy.io as scio
        self.labels = scio.loadmat(label_file)["labels"][0]
        self.indexes = scio.loadmat(setid_file)[self.flag][0]
        self._init_tar(data_file)

    def _decode(self, raw):
        import io as _io

        from PIL import Image
        with Image.open(_io.BytesIO(raw)) as im:
            return np.asarray(im.convert("RGB"))

    def __getitem__(self, idx):
        index = int(self.indexes[idx])
        label = np.array([self.labels[index - 1]])
        img = self._decode(self._read_member(f"jpg/image_{index:05d}.jpg"))
        if self.transform is not None:
            img = self.transform(img)
        return img, label.astype(np.int64)

    def __len__(self):
        return len(self.indexes)


class VOC2012(_LazyTarReader, Dataset):
    """reference: vision/datasets/voc2012.py:40 (VOCdevkit tar;
    ImageSets/Segmentation/{flag}.txt name lists; JPEGImages/{name}.jpg
    inputs and SegmentationClass/{name}.png masks; train->'trainval',
    test->'train', valid->'val' per voc2012.py:37)."""

    SET_FILE = "VOCdevkit/VOC2012/ImageSets/Segmentation/{}.txt"
    DATA_FILE = "VOCdevkit/VOC2012/JPEGImages/{}.jpg"
    LABEL_FILE = "VOCdevkit/VOC2012/SegmentationClass/{}.png"
    MODE_FLAG_MAP = {"train": "trainval", "test": "train", "valid": "val"}

    def __init__(self, data_file=None, mode="train", transform=None,
                 download=True, backend="cv2"):
        assert mode.lower() in self.MODE_FLAG_MAP, mode
        self.flag = self.MODE_FLAG_MAP[mode.lower()]
        self.transform = transform
        if data_file is None or not os.path.exists(data_file):
            raise ValueError(
                f"VOC2012: data_file must point at a local VOCtrainval "
                f"tar (no downloads in this environment), got "
                f"{data_file!r}")
        self._init_tar(data_file)
        names = self._read_member(self.SET_FILE.format(self.flag))
        self.name_list = [ln.strip() for ln in names.decode().splitlines()
                          if ln.strip()]

    def _decode(self, raw, mode):
        import io as _io

        from PIL import Image
        with Image.open(_io.BytesIO(raw)) as im:
            return np.asarray(im if mode is None else im.convert(mode))

    def __getitem__(self, idx):
        name = self.name_list[idx]
        image = self._decode(
            self._read_member(self.DATA_FILE.format(name)), "RGB")
        label = self._decode(
            self._read_member(self.LABEL_FILE.format(name)), None)
        if self.transform is not None:
            image = self.transform(image)
        return image, label.astype(np.int64)

    def __len__(self):
        return len(self.name_list)
