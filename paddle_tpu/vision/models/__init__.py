"""Model zoo (reference: python/paddle/vision/models/: lenet.py:21,
resnet.py, vgg.py, mobilenetv1.py, mobilenetv2.py)."""
from .lenet import LeNet  # noqa: F401
from .mobilenet import (MobileNetV1, MobileNetV2, mobilenet_v1,  # noqa
                        mobilenet_v2)
from .resnet import (ResNet, resnet18, resnet34, resnet50, resnet101,  # noqa
                     resnet152)
from .vgg import VGG, vgg11, vgg13, vgg16, vgg19  # noqa: F401
