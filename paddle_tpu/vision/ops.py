"""paddle.vision.ops — detection primitives.

Reference: python/paddle/vision/ops.py (nms:1586, box IoU in
operators/detection/).  TPU-first shapes: the suppression sweep is a
``lax.scan`` over a precomputed [N, N] IoU matrix — fixed shapes, no
data-dependent loops, so the same code runs eagerly, under jit (with
``top_k`` for a static result size), and on the accelerator.
"""
from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from ..core.dispatch import apply
from ..core.tensor import Tensor

__all__ = ["box_iou", "nms", "box_area"]


def _area(b):
    return jnp.maximum(b[..., 2] - b[..., 0], 0) * jnp.maximum(
        b[..., 3] - b[..., 1], 0)


def box_area(boxes, name=None):
    """[..., 4] xyxy boxes -> areas."""
    return apply(_area, boxes, op_name="box_area")


def _iou_matrix(a, b):
    lt = jnp.maximum(a[:, None, :2], b[None, :, :2])
    rb = jnp.minimum(a[:, None, 2:], b[None, :, 2:])
    wh = jnp.maximum(rb - lt, 0)
    inter = wh[..., 0] * wh[..., 1]
    union = _area(a)[:, None] + _area(b)[None, :] - inter
    return inter / jnp.maximum(union, 1e-9)


def box_iou(boxes1, boxes2, name=None):
    """Pairwise IoU [N, M] for xyxy boxes (reference: iou_similarity_op)."""
    return apply(_iou_matrix, boxes1, boxes2, op_name="box_iou")


def _nms_mask(boxes, scores, iou_threshold):
    """Greedy NMS keep-mask in score order (static shapes)."""
    order = jnp.argsort(-scores)
    iou = _iou_matrix(boxes[order], boxes[order])
    n = boxes.shape[0]

    def body(suppressed, i):
        keep_i = ~suppressed[i]
        sup_by_i = (iou[i] > iou_threshold) & keep_i
        sup_by_i = jnp.where(jnp.arange(n) <= i, False, sup_by_i)
        return suppressed | sup_by_i, keep_i

    _, keep_sorted = jax.lax.scan(body, jnp.zeros(n, bool), jnp.arange(n))
    return order, keep_sorted


def nms(boxes, iou_threshold: float = 0.3, scores=None,
        category_idxs=None, categories=None, top_k: Optional[int] = None,
        name=None):
    """Greedy hard NMS (reference: vision/ops.py nms:1586).

    Returns kept box indices, best score first.  Eager returns the
    variable-length result like the reference; pass ``top_k`` for a
    static-size result (padded with -1) usable under jit.
    ``category_idxs``/``categories`` run class-aware NMS (boxes of
    different categories never suppress each other)."""
    b = boxes.data if isinstance(boxes, Tensor) else jnp.asarray(boxes)
    n = b.shape[0]
    s = (scores.data if isinstance(scores, Tensor)
         else jnp.asarray(scores)) if scores is not None else None
    if s is None:
        s = jnp.arange(n, 0, -1, dtype=jnp.float32)  # input order

    if category_idxs is not None:
        # class-aware: offset boxes per category so cross-class IoU = 0
        # (the standard batched-NMS trick)
        ci = (category_idxs.data if isinstance(category_idxs, Tensor)
              else jnp.asarray(category_idxs))
        if categories is not None and not isinstance(
                ci, jax.core.Tracer):
            cats = set(int(v) for v in np.asarray(categories).reshape(-1))
            bad = set(int(v) for v in np.unique(np.asarray(ci))) - cats
            if bad:
                raise ValueError(
                    f"category_idxs contains {sorted(bad)} not present "
                    f"in categories {sorted(cats)}")
        c = ci.astype(b.dtype)
        span = jnp.max(b) - jnp.min(b) + 1.0
        b = b + (c * span)[:, None]

    def run(b, s):
        order, keep_sorted = _nms_mask(b, s, iou_threshold)
        if top_k is not None:
            # static result: rank kept entries first, pad with -1
            rank = jnp.where(keep_sorted, jnp.arange(n), n)
            sel = jnp.argsort(rank)[:top_k]
            idx = order[sel]
            valid = jnp.sort(rank)[:top_k] < n
            return jnp.where(valid, idx, -1)
        return order, keep_sorted

    if top_k is not None:
        return apply(run, b, s, op_name="nms", nondiff=True)

    # eager / variable-length (reference semantics)
    order, keep_sorted = run(b, s)
    order = np.asarray(order)
    kept = order[np.asarray(keep_sorted)]
    idx_dt = (jnp.int64 if jax.config.read("jax_enable_x64")
              else jnp.int32)
    return Tensor(jnp.asarray(kept, idx_dt))
