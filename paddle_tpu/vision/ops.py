"""paddle.vision.ops — detection primitives.

Reference: python/paddle/vision/ops.py (nms:1586, box IoU in
operators/detection/).  TPU-first shapes: the suppression sweep is a
``lax.scan`` over a precomputed [N, N] IoU matrix — fixed shapes, no
data-dependent loops, so the same code runs eagerly, under jit (with
``top_k`` for a static result size), and on the accelerator.
"""
from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from ..core.dispatch import apply
from ..core.tensor import Tensor

__all__ = ["box_iou", "nms", "box_area", "roi_align", "yolo_box",
           "prior_box", "box_coder", "multiclass_nms", "box_clip",
           "iou_similarity"]


def _area(b):
    return jnp.maximum(b[..., 2] - b[..., 0], 0) * jnp.maximum(
        b[..., 3] - b[..., 1], 0)


def box_area(boxes, name=None):
    """[..., 4] xyxy boxes -> areas."""
    return apply(_area, boxes, op_name="box_area")


def _iou_matrix(a, b):
    lt = jnp.maximum(a[:, None, :2], b[None, :, :2])
    rb = jnp.minimum(a[:, None, 2:], b[None, :, 2:])
    wh = jnp.maximum(rb - lt, 0)
    inter = wh[..., 0] * wh[..., 1]
    union = _area(a)[:, None] + _area(b)[None, :] - inter
    return inter / jnp.maximum(union, 1e-9)


def box_iou(boxes1, boxes2, name=None):
    """Pairwise IoU [N, M] for xyxy boxes (reference: iou_similarity_op)."""
    return apply(_iou_matrix, boxes1, boxes2, op_name="box_iou")


def _nms_mask(boxes, scores, iou_threshold):
    """Greedy NMS keep-mask in score order (static shapes)."""
    order = jnp.argsort(-scores)
    iou = _iou_matrix(boxes[order], boxes[order])
    n = boxes.shape[0]

    def body(suppressed, i):
        keep_i = ~suppressed[i]
        sup_by_i = (iou[i] > iou_threshold) & keep_i
        sup_by_i = jnp.where(jnp.arange(n) <= i, False, sup_by_i)
        return suppressed | sup_by_i, keep_i

    _, keep_sorted = jax.lax.scan(body, jnp.zeros(n, bool), jnp.arange(n))
    return order, keep_sorted


def nms(boxes, iou_threshold: float = 0.3, scores=None,
        category_idxs=None, categories=None, top_k: Optional[int] = None,
        name=None):
    """Greedy hard NMS (reference: vision/ops.py nms:1586).

    Returns kept box indices, best score first.  Eager returns the
    variable-length result like the reference; pass ``top_k`` for a
    static-size result (padded with -1) usable under jit.
    ``category_idxs``/``categories`` run class-aware NMS (boxes of
    different categories never suppress each other)."""
    b = boxes.data if isinstance(boxes, Tensor) else jnp.asarray(boxes)
    n = b.shape[0]
    s = (scores.data if isinstance(scores, Tensor)
         else jnp.asarray(scores)) if scores is not None else None
    if s is None:
        s = jnp.arange(n, 0, -1, dtype=jnp.float32)  # input order

    if category_idxs is not None:
        # class-aware: offset boxes per category so cross-class IoU = 0
        # (the standard batched-NMS trick)
        ci = (category_idxs.data if isinstance(category_idxs, Tensor)
              else jnp.asarray(category_idxs))
        if categories is not None and not isinstance(
                ci, jax.core.Tracer):
            cats = set(int(v) for v in np.asarray(categories).reshape(-1))
            bad = set(int(v) for v in np.unique(np.asarray(ci))) - cats
            if bad:
                raise ValueError(
                    f"category_idxs contains {sorted(bad)} not present "
                    f"in categories {sorted(cats)}")
        c = ci.astype(b.dtype)
        span = jnp.max(b) - jnp.min(b) + 1.0
        b = b + (c * span)[:, None]

    def run(b, s):
        order, keep_sorted = _nms_mask(b, s, iou_threshold)
        if top_k is not None:
            # static result: rank kept entries first, pad with -1
            rank = jnp.where(keep_sorted, jnp.arange(n), n)
            sel = jnp.argsort(rank)[:top_k]
            idx = order[sel]
            valid = jnp.sort(rank)[:top_k] < n
            return jnp.where(valid, idx, -1)
        return order, keep_sorted

    if top_k is not None:
        return apply(run, b, s, op_name="nms", nondiff=True)

    # eager / variable-length (reference semantics)
    order, keep_sorted = run(b, s)
    order = np.asarray(order)
    kept = order[np.asarray(keep_sorted)]
    idx_dt = (jnp.int64 if jax.config.read("jax_enable_x64")
              else jnp.int32)
    return Tensor(jnp.asarray(kept, idx_dt))


# ---------------------------------------------------------------------------
# detection zoo (VERDICT r4 #5) — TPU-first redesigns of
# operators/detection/: fixed shapes, masked outputs instead of LoD,
# gathers instead of scalar loops, everything jittable and vmappable.
# ---------------------------------------------------------------------------

def iou_similarity(x, y, box_normalized=True, name=None):
    """Pairwise IoU matrix (reference: iou_similarity_op.cc)."""
    return box_iou(x, y)


def _clip_fn(b, im_info):
    # im_info rows: [h, w, scale]; boxes clipped to [0, dim - 1]
    h = im_info[..., 0:1] - 1.0
    w = im_info[..., 1:2] - 1.0
    x1 = jnp.clip(b[..., 0], 0, w)
    y1 = jnp.clip(b[..., 1], 0, h)
    x2 = jnp.clip(b[..., 2], 0, w)
    y2 = jnp.clip(b[..., 3], 0, h)
    return jnp.stack([x1, y1, x2, y2], axis=-1)


def box_clip(input, im_info, name=None):
    """Clip [.., 4] xyxy boxes to image bounds (box_clip_op.cc).
    ``im_info``: [h, w, scale] (broadcast over leading dims)."""
    return apply(_clip_fn, input, im_info, op_name="box_clip")


def _roi_align_fn(x, boxes, batch_idx, *, output_size, spatial_scale,
                  sampling_ratio, aligned):
    R = boxes.shape[0]
    C, H, W = x.shape[1:]
    ph, pw = output_size
    S = sampling_ratio if sampling_ratio > 0 else 2
    off = 0.5 if aligned else 0.0
    b = boxes * spatial_scale
    x1 = b[:, 0] - off
    y1 = b[:, 1] - off
    roi_w = b[:, 2] - b[:, 0]
    roi_h = b[:, 3] - b[:, 1]
    if not aligned:                      # legacy: min size 1 (roi_align_op.h)
        roi_w = jnp.maximum(roi_w, 1.0)
        roi_h = jnp.maximum(roi_h, 1.0)
    bin_w = roi_w / pw
    bin_h = roi_h / ph
    # sample grid: for output bin (i,j), S x S points at
    # y = y1 + (i + (sy + .5)/S) * bin_h   (roi_align_op.h bilinear loop)
    iy = (jnp.arange(ph)[:, None] + (jnp.arange(S)[None, :] + 0.5) / S)
    ix = (jnp.arange(pw)[:, None] + (jnp.arange(S)[None, :] + 0.5) / S)
    ys = y1[:, None, None] + iy[None] * bin_h[:, None, None]   # [R,ph,S]
    xs = x1[:, None, None] + ix[None] * bin_w[:, None, None]   # [R,pw,S]

    def bilinear_1d(coord, size):
        c = jnp.clip(coord, 0.0, size - 1.0)
        lo = jnp.clip(jnp.floor(c).astype(jnp.int32), 0, size - 1)
        hi = jnp.minimum(lo + 1, size - 1)
        frac = c - lo
        # out-of-range samples contribute 0 (roi_align_op.h: skip when
        # y < -1 or y > height, clamp the [-1, 0) band to 0)
        valid = (coord >= -1.0) & (coord <= size)
        return lo, hi, frac, valid

    ylo, yhi, fy, vy = bilinear_1d(ys, H)        # [R,ph,S]
    xlo, xhi, fx, vx = bilinear_1d(xs, W)        # [R,pw,S]
    bi = batch_idx[:, None, None]

    def gather_rows(yi):                          # yi [R,ph,S] -> [R,ph,S,C,W]
        return x[bi, :, yi, :]

    top, bot = gather_rows(ylo), gather_rows(yhi)
    rows = top + (bot - top) * fy[..., None, None]     # [R,ph,S,C,W]
    rows = rows * vy[..., None, None]

    # gather along W: result [R, ph, Sy, C, pw, Sx]
    left = jnp.take_along_axis(
        rows[:, :, :, :, None, None, :],
        xlo[:, None, None, None, :, :, None].astype(jnp.int32), axis=-1)[..., 0]
    right = jnp.take_along_axis(
        rows[:, :, :, :, None, None, :],
        xhi[:, None, None, None, :, :, None].astype(jnp.int32), axis=-1)[..., 0]
    vals = left + (right - left) * fx[:, None, None, None, :, :]
    vals = vals * vx[:, None, None, None, :, :]
    # average over the S x S samples -> [R, C, ph, pw]
    out = vals.mean(axis=(2, 5))                  # [R, ph, C, pw]
    return out.transpose(0, 2, 1, 3)


def roi_align(x, boxes, boxes_num, output_size, spatial_scale=1.0,
              sampling_ratio=-1, aligned=True, name=None):
    """RoIAlign (reference: roi_align_op.cc / vision/ops.py roi_align).

    ``x``: [N, C, H, W]; ``boxes``: [R, 4] xyxy in input-image coords;
    ``boxes_num``: [N] rois per image.  Output [R, C, ph, pw].

    TPU deviation (documented): ``sampling_ratio=-1`` uses a fixed 2x2
    sample grid per bin instead of the reference's per-RoI adaptive
    ``ceil(roi_size / pooled_size)`` — adaptive counts are data-dependent
    shapes XLA cannot compile.  Pass an explicit ``sampling_ratio`` for
    bit-matched parity with the reference kernel."""
    if isinstance(output_size, int):
        output_size = (output_size, output_size)
    xa = x.data if isinstance(x, Tensor) else jnp.asarray(x)
    ba = boxes.data if isinstance(boxes, Tensor) else jnp.asarray(boxes)
    bn = (boxes_num.data if isinstance(boxes_num, Tensor)
          else jnp.asarray(boxes_num)).astype(jnp.int32)
    # roi -> image index: searchsorted over the cumulative roi counts
    # (replaces the reference's LoD offsets, roi_align_op.cc:74)
    batch_idx = jnp.searchsorted(jnp.cumsum(bn), jnp.arange(ba.shape[0]),
                                 side="right").astype(jnp.int32)
    return apply(_roi_align_fn, xa, ba, Tensor(batch_idx),
                 op_name="roi_align", output_size=tuple(output_size),
                 spatial_scale=float(spatial_scale),
                 sampling_ratio=int(sampling_ratio), aligned=bool(aligned))


def _yolo_box_fn(x, img_size, *, anchors, class_num, conf_thresh,
                 downsample_ratio, clip_bbox, scale_x_y):
    n, c, h, w = x.shape
    an = len(anchors) // 2
    anc = jnp.asarray(anchors, x.dtype).reshape(an, 2)
    bias = -0.5 * (scale_x_y - 1.0)
    xv = x.reshape(n, an, class_num + 5, h, w)
    tx, ty, tw, th = xv[:, :, 0], xv[:, :, 1], xv[:, :, 2], xv[:, :, 3]
    obj = jax.nn.sigmoid(xv[:, :, 4])                       # [n,an,h,w]
    cls = jax.nn.sigmoid(xv[:, :, 5:])                      # [n,an,cls,h,w]
    img_h = img_size[:, 0].astype(x.dtype)[:, None, None, None]
    img_w = img_size[:, 1].astype(x.dtype)[:, None, None, None]
    in_h, in_w = downsample_ratio * h, downsample_ratio * w
    gx = jnp.arange(w, dtype=x.dtype)[None, None, None, :]
    gy = jnp.arange(h, dtype=x.dtype)[None, None, :, None]
    cx = (gx + jax.nn.sigmoid(tx) * scale_x_y + bias) * img_w / w
    cy = (gy + jax.nn.sigmoid(ty) * scale_x_y + bias) * img_h / h
    bw = jnp.exp(tw) * anc[None, :, 0, None, None] * img_w / in_w
    bh = jnp.exp(th) * anc[None, :, 1, None, None] * img_h / in_h
    x1, y1 = cx - bw / 2, cy - bh / 2
    x2, y2 = cx + bw / 2, cy + bh / 2
    if clip_bbox:
        x1 = jnp.maximum(x1, 0.0)
        y1 = jnp.maximum(y1, 0.0)
        x2 = jnp.minimum(x2, img_w - 1.0)
        y2 = jnp.minimum(y2, img_h - 1.0)
    keep = obj >= conf_thresh                               # [n,an,h,w]
    boxes = jnp.stack([x1, y1, x2, y2], axis=-1) * keep[..., None]
    scores = obj[:, :, None] * cls * keep[:, :, None]       # [n,an,cls,h,w]
    # layout parity (yolo_box_op.h GetEntryIndex): anchor-major, then h, w
    boxes = boxes.reshape(n, an * h * w, 4)
    scores = scores.transpose(0, 1, 3, 4, 2).reshape(n, an * h * w,
                                                     class_num)
    return boxes, scores


def yolo_box(x, img_size, anchors, class_num, conf_thresh,
             downsample_ratio, clip_bbox=True, scale_x_y=1.0, name=None):
    """YOLOv3 box decode (reference: yolo_box_op.cc/.h).

    ``x``: [N, an*(5+classes), H, W]; ``img_size``: [N, 2] (h, w).
    Returns (boxes [N, an*H*W, 4] xyxy, scores [N, an*H*W, classes]);
    entries with objectness below ``conf_thresh`` are zeroed (the masked
    analog of the reference's sparse write into zeroed outputs)."""
    return apply(_yolo_box_fn, x, img_size, op_name="yolo_box",
                 anchors=tuple(int(a) for a in anchors),
                 class_num=int(class_num), conf_thresh=float(conf_thresh),
                 downsample_ratio=int(downsample_ratio),
                 clip_bbox=bool(clip_bbox), scale_x_y=float(scale_x_y))


def prior_box(input, image, min_sizes, max_sizes=None, aspect_ratios=(1.0,),
              variance=(0.1, 0.1, 0.2, 0.2), flip=False, clip=False,
              steps=(0.0, 0.0), offset=0.5,
              min_max_aspect_ratios_order=False, name=None):
    """SSD prior boxes (reference: prior_box_op.cc/.h).

    Returns (boxes [H, W, P, 4] normalized xyxy, variances [H, W, P, 4]).
    Pure host-side construction (priors depend only on shapes/attrs, like
    the reference's CPU kernel) — the result is a constant for a given
    feature size, so XLA folds it."""
    xa = input.data if isinstance(input, Tensor) else jnp.asarray(input)
    im = image.data if isinstance(image, Tensor) else jnp.asarray(image)
    fh, fw = xa.shape[2], xa.shape[3]
    ih, iw = im.shape[2], im.shape[3]
    # ExpandAspectRatios (prior_box_op.h:28): dedup, keep 1.0 first
    ars = [1.0]
    for ar in aspect_ratios:
        if not any(abs(ar - e) < 1e-6 for e in ars):
            ars.append(float(ar))
            if flip:
                ars.append(1.0 / float(ar))
    min_sizes = [float(s) for s in np.atleast_1d(min_sizes)]
    max_sizes = ([float(s) for s in np.atleast_1d(max_sizes)]
                 if max_sizes is not None else [])
    step_w = steps[0] or iw / fw
    step_h = steps[1] or ih / fh
    cx = (np.arange(fw) + offset) * step_w          # [fw]
    cy = (np.arange(fh) + offset) * step_h          # [fh]
    whs = []
    for s, mn in enumerate(min_sizes):
        variants = []
        if min_max_aspect_ratios_order:
            variants.append((mn / 2.0, mn / 2.0))
            if max_sizes:
                m = (mn * max_sizes[s]) ** 0.5 / 2.0
                variants.append((m, m))
            for ar in ars:
                if abs(ar - 1.0) < 1e-6:
                    continue
                variants.append((mn * ar ** 0.5 / 2.0, mn / ar ** 0.5 / 2.0))
        else:
            for ar in ars:
                variants.append((mn * ar ** 0.5 / 2.0, mn / ar ** 0.5 / 2.0))
            if max_sizes:
                m = (mn * max_sizes[s]) ** 0.5 / 2.0
                variants.append((m, m))
        whs.extend(variants)
    whs_np = np.asarray(whs, np.float32)            # [P, 2] half sizes
    P = whs_np.shape[0]
    gx = np.broadcast_to(cx[None, :, None], (fh, fw, P))
    gy = np.broadcast_to(cy[:, None, None], (fh, fw, P))
    hw = np.broadcast_to(whs_np[None, None, :, 0], (fh, fw, P))
    hh = np.broadcast_to(whs_np[None, None, :, 1], (fh, fw, P))
    boxes = np.stack([(gx - hw) / iw, (gy - hh) / ih,
                      (gx + hw) / iw, (gy + hh) / ih], axis=-1)
    if clip:
        boxes = np.clip(boxes, 0.0, 1.0)
    var = np.broadcast_to(np.asarray(variance, np.float32),
                          (fh, fw, P, 4)).copy()
    return Tensor(jnp.asarray(boxes)), Tensor(jnp.asarray(var))


def _encode_center(t, p, pv, normalized):
    norm = 0.0 if normalized else 1.0
    pw = p[:, 2] - p[:, 0] + norm
    ph = p[:, 3] - p[:, 1] + norm
    px = p[:, 0] + pw * 0.5
    py = p[:, 1] + ph * 0.5
    tw = t[:, 2] - t[:, 0] + norm
    th = t[:, 3] - t[:, 1] + norm
    tx = t[:, 0] + tw * 0.5
    ty = t[:, 1] + th * 0.5
    out = jnp.stack([
        (tx[:, None] - px[None]) / pw[None],
        (ty[:, None] - py[None]) / ph[None],
        jnp.log(tw[:, None] / pw[None]),
        jnp.log(th[:, None] / ph[None])], axis=-1)     # [N, M, 4]
    if pv is not None:
        out = out / pv[None]
    return out


def _decode_center(t, p, pv, normalized, axis):
    norm = 0.0 if normalized else 1.0
    pw = p[:, 2] - p[:, 0] + norm
    ph = p[:, 3] - p[:, 1] + norm
    px = p[:, 0] + pw * 0.5
    py = p[:, 1] + ph * 0.5
    # box_coder_op.h DecodeCenterSize: axis==0 indexes priors by the
    # COLUMN (dim 1) of the [N, M, 4] codes; axis==1 by the row
    ex = (slice(None), None) if axis == 1 else (None, slice(None))
    pw, ph, px, py = (a[ex] for a in (pw, ph, px, py))
    v = pv[ex + (slice(None),)] if pv is not None else jnp.ones((4,), t.dtype)
    ox = v[..., 0] * t[..., 0] * pw + px
    oy = v[..., 1] * t[..., 1] * ph + py
    ow = jnp.exp(v[..., 2] * t[..., 2]) * pw
    oh = jnp.exp(v[..., 3] * t[..., 3]) * ph
    return jnp.stack([ox - ow / 2 + norm * 0.5, oy - oh / 2 + norm * 0.5,
                      ox + ow / 2 - norm * 0.5, oy + oh / 2 - norm * 0.5],
                     axis=-1)


def box_coder(prior_box, prior_box_var, target_box,
              code_type="encode_center_size", box_normalized=True,
              axis=0, name=None):
    """Encode/decode boxes against priors (reference: box_coder_op.cc/.h).

    encode: target [N, 4], priors [M, 4] -> [N, M, 4] offsets.
    decode: target [N, M, 4] codes -> [N, M, 4] xyxy boxes (``axis``
    selects which dim the priors broadcast over, as in the reference)."""
    pv = prior_box_var
    if pv is not None and not hasattr(pv, "shape"):
        pv = jnp.asarray(pv, jnp.float32)
    args = [prior_box, target_box] + ([pv] if pv is not None else [])

    def fn(p, t, *rest):
        v = rest[0] if rest else None
        if v is not None and v.ndim == 1:
            v = jnp.broadcast_to(v, p.shape)
        if code_type == "encode_center_size":
            return _encode_center(t, p, v, box_normalized)
        return _decode_center(t, p, v, box_normalized, axis)

    return apply(fn, *args, op_name="box_coder")


def _multiclass_nms_fn(bboxes, scores, *, score_threshold, nms_top_k,
                       keep_top_k, nms_threshold, normalized, nms_eta,
                       background_label):
    N, M, _ = bboxes.shape
    C = scores.shape[1]

    def per_class(boxes, sc):
        # sc [M]; stage 1 (multiclass_nms_op.cc NMSFast): threshold,
        # top-k by score, greedy NMS with adaptive eta
        sc = jnp.where(sc > score_threshold, sc, 0.0)
        if 0 < nms_top_k < M:
            top = jnp.sort(sc)[::-1][nms_top_k - 1]
            sc = jnp.where(sc >= jnp.maximum(top, 1e-38), sc, 0.0)
        order = jnp.argsort(-sc)
        iou = _iou_matrix(boxes[order], boxes[order])
        n = M

        def body(carry, i):
            suppressed, thresh = carry
            keep_i = (~suppressed[i]) & (sc[order[i]] > 0)
            sup = (iou[i] > thresh) & keep_i
            sup = jnp.where(jnp.arange(n) <= i, False, sup)
            thresh = jnp.where(keep_i & (thresh > 0.5), thresh * nms_eta,
                               thresh)
            return (suppressed | sup, thresh), keep_i

        (_, _), keep_sorted = jax.lax.scan(
            body, (jnp.zeros(n, bool), jnp.asarray(nms_threshold)),
            jnp.arange(n))
        keep = jnp.zeros(n, bool).at[order].set(keep_sorted)
        return jnp.where(keep, sc, 0.0)

    def per_image(boxes, sc):
        kept = jax.vmap(lambda s: per_class(boxes, s))(sc)   # [C, M]
        if background_label >= 0:
            kept = kept.at[background_label].set(0.0)
        flat = kept.reshape(-1)                              # [C*M]
        K = keep_top_k if keep_top_k > 0 else flat.shape[0]
        K = min(K, flat.shape[0])
        top_sc, top_ix = jax.lax.top_k(flat, K)
        label = (top_ix // M).astype(jnp.float32)
        box = boxes[top_ix % M]
        valid = top_sc > 0.0
        out = jnp.concatenate(
            [jnp.where(valid, label, -1.0)[:, None], top_sc[:, None], box],
            axis=1)
        index = jnp.where(valid, top_ix % M, -1)
        return out, index, valid.sum().astype(jnp.int32)

    return jax.vmap(per_image)(bboxes, scores)


def multiclass_nms(bboxes, scores, score_threshold=0.05, nms_top_k=400,
                   keep_top_k=100, nms_threshold=0.3, normalized=True,
                   nms_eta=1.0, background_label=-1, name=None):
    """Multi-class NMS (reference: multiclass_nms_op.cc).

    ``bboxes`` [N, M, 4], ``scores`` [N, C, M].  Returns
    (out [N, K, 6] rows ``[label, score, x1, y1, x2, y2]``,
    index [N, K] box indices, nms_num [N]) where K = keep_top_k; invalid
    rows carry label/index -1 — the masked fixed-shape redesign of the
    reference's LoD output (SURVEY §7 LoD -> padding)."""
    return apply(_multiclass_nms_fn, bboxes, scores,
                 op_name="multiclass_nms", nondiff=True,
                 score_threshold=float(score_threshold),
                 nms_top_k=int(nms_top_k), keep_top_k=int(keep_top_k),
                 nms_threshold=float(nms_threshold),
                 normalized=bool(normalized), nms_eta=float(nms_eta),
                 background_label=int(background_label))
