"""Placeholder — populated in subsequent milestones."""
