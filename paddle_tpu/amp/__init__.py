"""Automatic mixed precision.

Reference: python/paddle/amp/ (auto_cast.py:20, grad_scaler.py:20) backed by
C++ autocast hooks in the dygraph tracer (imperative/amp_auto_cast.h:31) and
static-mode decoration (fluid/contrib/mixed_precision/decorator.py:415).

TPU-first: the preferred low-precision dtype is **bfloat16** (MXU-native, no
loss scaling needed); float16 is supported for parity and engages the
GradScaler.  The cast hook lives at the shared dispatch point
(core/dispatch.py) so it applies identically in eager and traced modes —
the same design as the reference's single autocast hook in Tracer::TraceOp
(tracer.cc:160-163).
"""
from __future__ import annotations

import contextlib
import threading

import jax.numpy as jnp
import numpy as np

from ..core.dtype import convert_dtype
from ..core.tensor import Tensor

# op lists (reference: fluid/contrib/mixed_precision/fp16_lists.py)
WHITE_LIST = {
    "matmul", "mm", "bmm", "linear", "conv1d", "conv2d", "conv3d",
    "conv2d_transpose", "einsum", "scaled_dot_product_attention",
    "flash_attention",
}
BLACK_LIST = {
    "exp", "log", "square", "mean", "sum", "softmax", "log_softmax",
    "cross_entropy", "nll_loss", "bce_with_logits", "binary_cross_entropy",
    "layer_norm", "batch_norm", "group_norm", "instance_norm", "norm",
    "logsumexp", "softmax_with_cross_entropy", "cosine_similarity",
    "kl_div", "sigmoid_focal_loss", "erf", "erfinv", "pow", "cumsum",
}

_tls = threading.local()


def _state():
    if not hasattr(_tls, "amp"):
        _tls.amp = None
    return _tls.amp


class _AmpState:
    __slots__ = ("dtype", "level", "white", "black")

    def __init__(self, dtype, level, white, black):
        self.dtype = dtype
        self.level = level
        self.white = white
        self.black = black


def amp_active():
    return _state() is not None


def amp_cast_inputs(op_name: str, arrays):
    """Called from core.dispatch.apply for every op when AMP is on."""
    st = _state()
    if st is None:
        return arrays

    def _cast(a, dt):
        if hasattr(a, "dtype") and jnp.issubdtype(
                np.dtype(a.dtype), np.floating) and a.dtype != dt:
            if np.dtype(a.dtype) in (np.dtype(np.float16),
                                     np.dtype(jnp.bfloat16),
                                     np.dtype(np.float32)):
                return a.astype(dt)
        return a

    if op_name in st.black:
        return [_cast(a, jnp.float32) for a in arrays]
    if op_name in st.white or st.level == "O2":
        return [_cast(a, st.dtype) for a in arrays]
    return arrays


@contextlib.contextmanager
def auto_cast(enable=True, custom_white_list=None, custom_black_list=None,
              level="O1", dtype="bfloat16"):
    """paddle.amp.auto_cast parity (reference: amp/auto_cast.py:20).

    level O1: white-listed ops run in low precision; black-listed forced to
    float32.  level O2: everything except the black list runs low-precision.
    """
    prev = _state()
    if enable:
        white = set(WHITE_LIST)
        black = set(BLACK_LIST)
        if custom_white_list:
            white |= set(custom_white_list)
            black -= set(custom_white_list)
        if custom_black_list:
            black |= set(custom_black_list)
            white -= set(custom_black_list)
        _tls.amp = _AmpState(convert_dtype(dtype), level, white, black)
    else:
        _tls.amp = None
    try:
        yield
    finally:
        _tls.amp = prev


amp_guard = auto_cast  # fluid-era alias


def decorate(models=None, optimizers=None, level="O2", dtype="bfloat16",
             master_weight=None, save_dtype=None):
    """paddle.amp.decorate parity: cast model params to the AMP dtype
    (pure-fp16/bf16 mode) and enable optimizer master weights."""
    dt = convert_dtype(dtype)
    single = not isinstance(models, (list, tuple))
    ms = [models] if single else list(models)
    for m in ms:
        if m is not None:
            m.to(dtype=dt)
            # record the decorated dtype; jit.TrainStep(amp_level=...)
            # uses it when the caller opts into tracing under auto_cast
            m._amp_dtype = dt
    if optimizers is not None:
        opts = ([optimizers] if not isinstance(optimizers, (list, tuple))
                else list(optimizers))
        for o in opts:
            o._multi_precision = True if master_weight is None else bool(
                master_weight)
        if single and not isinstance(optimizers, (list, tuple)):
            return models, optimizers
        return ms, opts
    return models if single else ms


class GradScaler:
    """Dynamic loss scaling (reference: amp/grad_scaler.py:20; static twin:
    check_finite_and_unscale + update_loss_scaling ops, operators/amp/).

    Needed only for float16; bfloat16 training normally runs unscaled."""

    def __init__(self, enable=True, init_loss_scaling=2.0 ** 15,
                 incr_ratio=2.0, decr_ratio=0.5, incr_every_n_steps=1000,
                 decr_every_n_nan_or_inf=1, use_dynamic_loss_scaling=True):
        self._enable = enable
        self._scale = float(init_loss_scaling)
        self._incr_ratio = incr_ratio
        self._decr_ratio = decr_ratio
        self._incr_every = incr_every_n_steps
        self._decr_every = decr_every_n_nan_or_inf
        self._dynamic = use_dynamic_loss_scaling
        self._good_steps = 0
        self._bad_steps = 0
        self._found_inf = False
        self._unscaled = {}  # id(optimizer) -> found_inf for this step

    def scale(self, var):
        if not self._enable:
            return var
        return var * self._scale

    def unscale_(self, optimizer):
        if not self._enable:
            return
        if id(optimizer) in self._unscaled:
            raise RuntimeError(
                "unscale_() has already been called on this optimizer "
                "since the last update()")
        if not self._unscaled:
            # first unscale of this step: recompute found_inf fresh so a
            # stale inf from a prior skipped-update iteration can't leak
            # into this step's decision
            self._found_inf = False
        inv = 1.0 / self._scale
        checks = []
        for p in optimizer._parameter_list or []:
            if p._grad_data is None:
                continue
            g = p._grad_data * inv
            checks.append(jnp.all(jnp.isfinite(g)))
            p._grad_data = g
        # one host sync for the whole param list, not one per param
        found = bool(not jnp.all(jnp.stack(checks))) if checks else False
        self._unscaled[id(optimizer)] = found
        self._found_inf = self._found_inf or found

    def step(self, optimizer):
        """Unscale (if not already) and apply the optimizer step unless inf/
        nan was found.  Call ``update()`` once per iteration afterwards
        (paddle 2.x flow); ``minimize`` does both."""
        if not self._enable:
            optimizer.step()
            return
        if id(optimizer) not in self._unscaled:
            self.unscale_(optimizer)
        # pop: the entry covers exactly one step, so the next iteration's
        # step() re-unscales even if the user skips update()
        if not self._unscaled.pop(id(optimizer)):
            optimizer.step()

    def minimize(self, optimizer, scaled_loss):
        scaled_loss.backward()
        self.step(optimizer)
        self.update()

    def update(self):
        self._unscaled.clear()
        if not (self._enable and self._dynamic):
            return
        if self._found_inf:
            self._bad_steps += 1
            self._good_steps = 0
            if self._bad_steps >= self._decr_every:
                self._scale = max(self._scale * self._decr_ratio, 1.0)
                self._bad_steps = 0
        else:
            self._good_steps += 1
            self._bad_steps = 0
            if self._good_steps >= self._incr_every:
                self._scale *= self._incr_ratio
                self._good_steps = 0
        self._found_inf = False

    def is_enable(self):
        return self._enable

    def is_use_dynamic_loss_scaling(self):
        return self._dynamic

    def get_init_loss_scaling(self):
        return self._scale

    def state_dict(self):
        self._sync_from_bound_step()
        return {"scale": self._scale, "good_steps": self._good_steps,
                "bad_steps": self._bad_steps}

    def load_state_dict(self, sd):
        self._scale = sd["scale"]
        self._good_steps = sd["good_steps"]
        self._bad_steps = sd["bad_steps"]
        # invalidate any compiled TrainStep's in-graph state so the next
        # step reinitialises from the loaded values
        step = getattr(self, "_bound_step", None)
        if step is not None:
            step._scaler_state = None

    def _sync_from_bound_step(self):
        """Pull the in-graph loss-scaling state from a TrainStep that
        threads this scaler through its compiled step (jit/train_step.py);
        one host sync, used at checkpoint time only."""
        step = getattr(self, "_bound_step", None)
        st = getattr(step, "_scaler_state", None)
        if st and "scale" in st:
            self._scale = float(st["scale"])
            self._good_steps = int(st["good"])
            self._bad_steps = int(st["bad"])
