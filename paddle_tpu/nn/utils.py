"""paddle.nn.utils — weight normalization (reference:
python/paddle/nn/utils/weight_norm_hook.py).

Reparameterises ``layer.<name>`` as ``g * v / ||v||`` with trainable
``<name>_g`` / ``<name>_v``; a forward-pre-hook recomputes the derived
weight as a TENSOR expression each call, so gradients flow to g and v
through the tape exactly like the reference's WeightNorm hook (which
also swaps the attribute for a computed Variable per forward)."""
from __future__ import annotations

from ..core.tensor import Parameter, Tensor

__all__ = ["weight_norm", "remove_weight_norm"]


def _norm_tensor(v: Tensor, dim):
    sq = (v * v)
    if dim is None:
        return sq.sum().sqrt()
    axes = [i for i in range(len(v.shape_tuple)) if i != dim]
    return sq.sum(axis=axes, keepdim=True).sqrt()


def weight_norm(layer, name="weight", dim=0):
    """Apply weight normalization to ``layer.<name>`` (in place)."""
    w = getattr(layer, name)
    if not isinstance(w, Parameter):
        raise ValueError(f"{name!r} is not a Parameter of {layer}")
    import numpy as np

    v0 = w.data
    g0 = _norm_tensor(Tensor(v0), dim).data
    g = layer.create_parameter(list(np.asarray(g0).shape),
                               dtype=str(v0.dtype))
    g.data = g0
    v = layer.create_parameter(list(v0.shape), dtype=str(v0.dtype))
    v.data = v0
    setattr(layer, f"{name}_g", g)
    setattr(layer, f"{name}_v", v)
    # the plain weight leaves the parameter set (reference hook does the
    # same); it becomes a derived tensor recomputed per forward
    layer._parameters.pop(name, None)
    object.__setattr__(layer, name, Tensor(v0))
    layer._wn_dim = dim

    def pre_hook(lyr, inputs):
        gg = getattr(lyr, f"{name}_g")
        vv = getattr(lyr, f"{name}_v")
        n = _norm_tensor(vv, dim)
        derived = gg.reshape(n.shape_tuple) * vv / n if dim is not None \
            else gg * vv / n
        object.__setattr__(lyr, name, derived)
        return inputs

    layer._wn_hook = layer.register_forward_pre_hook(pre_hook)
    return layer


def remove_weight_norm(layer, name="weight"):
    """Fold g/v back into a plain trainable ``layer.<name>``."""
    if not hasattr(layer, f"{name}_g"):
        raise ValueError(f"{layer} has no weight_norm on {name!r}")
    g = getattr(layer, f"{name}_g")
    v = getattr(layer, f"{name}_v")
    dim = layer._wn_dim
    n = _norm_tensor(Tensor(v.data), dim)
    folded = (Tensor(g.data).reshape(n.shape_tuple) * Tensor(v.data) / n
              if dim is not None else Tensor(g.data) * Tensor(v.data) / n)
    layer._wn_hook.remove()
    for suffix in ("_g", "_v"):
        pname = f"{name}{suffix}"
        layer._parameters.pop(pname, None)
        if hasattr(layer, pname):
            try:
                object.__delattr__(layer, pname)
            except AttributeError:
                pass
    w = Parameter(folded.data)
    setattr(layer, name, w)
    return layer
