"""Dynamic decoding: BeamSearchDecoder + dynamic_decode.

Reference: python/paddle/nn/decode.py (BeamSearchDecoder:64,
dynamic_decode:997) lowering to the while/beam-search op stack
(operators/controlflow/while_op.cc, beam_search_op, gather_tree).

TPU-native design: the decode loop is ``lax.while_loop`` with
static-shape state — scores [B, K], token history [B, K, T_max] written
by step index — so one compiled program serves any actual decode length;
early exit is the loop predicate (all beams finished), the reference's
is_finished plumbing.  ``gather_tree`` (backtracking predecessors into
final beams) is a reverse ``lax.scan``.
"""
from __future__ import annotations

import collections
import threading
from typing import Callable, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..core.dispatch import apply
from ..core.tensor import Tensor

__all__ = ["BeamSearchDecoder", "dynamic_decode", "gather_tree",
           "cell_step"]


def _gather_tree_impl(step_ids, parent_ids):
    """[T, B, K] ids + parent beam indices -> backtracked [T, B, K]."""
    T = step_ids.shape[0]

    def back(carry, xs):
        beams = carry                       # [B, K] current beam index
        ids_t, par_t = xs
        tok = jnp.take_along_axis(ids_t, beams, axis=1)
        beams = jnp.take_along_axis(par_t, beams, axis=1)
        return beams, tok

    B, K = step_ids.shape[1:]
    init = jnp.tile(jnp.arange(K)[None, :], (B, 1))
    _, toks = jax.lax.scan(back, init, (step_ids[::-1], parent_ids[::-1]))
    return toks[::-1]


def gather_tree(step_ids, parent_ids):
    """reference: paddle.nn.functional.gather_tree / gather_tree_op.cc."""
    return apply(_gather_tree_impl, step_ids, parent_ids,
                 op_name="gather_tree", nondiff=True)


class BeamSearchDecoder:
    """reference: nn/decode.py BeamSearchDecoder:64.

    ``cell(inputs, states) -> (logits_or_out, new_states)``;
    ``output_fn`` maps cell output to vocab logits (e.g. the projection
    layer) when the cell itself doesn't."""

    def __init__(self, cell, start_token: int, end_token: int,
                 beam_size: int, embedding_fn: Optional[Callable] = None,
                 output_fn: Optional[Callable] = None):
        self.cell = cell
        self.start_token = int(start_token)
        self.end_token = int(end_token)
        self.beam_size = int(beam_size)
        self.embedding_fn = embedding_fn
        self.output_fn = output_fn


def _arr(t):
    return t.data if isinstance(t, Tensor) else jnp.asarray(t)


def cell_step(decoder: BeamSearchDecoder, tokens, states):
    """One step of the decoder's cell contract — the single-step API a
    token-level scheduler (serving/generation.py) or a hand-rolled loop
    can drive directly.

    ``tokens``: [N] token ids (Tensor or array); ``states``: the cell
    state pytree with leading dim N.  Embeds via ``embedding_fn``, runs
    ``cell(inputs, states)``, projects via ``output_fn``, and returns
    ``(log_probs [N, V] float32, new_states)`` with raw-array leaves.
    ``dynamic_decode`` runs exactly this inside its loop."""
    dec = decoder
    inp = tokens if isinstance(tokens, Tensor) else Tensor(
        jnp.asarray(tokens))
    if dec.embedding_fn is not None:
        inp = dec.embedding_fn(inp)
    out, new_states = dec.cell(inp, jax.tree.map(
        Tensor, states,
        is_leaf=lambda x: not isinstance(x, (list, tuple, dict))))
    if dec.output_fn is not None:
        out = dec.output_fn(out)
    logits = _arr(out)
    new_states = jax.tree.map(_arr, new_states,
                              is_leaf=lambda x: isinstance(x, Tensor))
    return jax.nn.log_softmax(logits.astype(jnp.float32)), new_states


# cache=True: compiled decode programs keyed on (decoder, shapes).  The
# entry holds the decoder strongly, so an id can never be recycled into
# a live key; bounded LRU so abandoned decoders don't pile up.
_DECODE_CACHE: "collections.OrderedDict" = collections.OrderedDict()
_DECODE_CACHE_MAX = 32
_DECODE_LOCK = threading.Lock()


def dynamic_decode(decoder: BeamSearchDecoder, inits=None,
                   max_step_num: int = 64, is_test: bool = True,
                   return_length: bool = False, cache: bool = False,
                   **kwargs):
    """Beam-search decode loop (reference: nn/decode.py
    dynamic_decode:997).  ``inits``: initial cell state pytree of
    Tensors/arrays with leading batch dim B.  Returns
    ``(token_ids [B, K, max_step_num], beam_scores [B, K])`` (+ lengths
    with ``return_length=True``), beams sorted best-first; positions
    past a beam's end are padded with ``end_token``.

    ``cache=True`` compiles the whole decode loop ONCE per (decoder,
    state shapes, max_step_num) and replays it for later calls — the
    per-request serving path (start token is a traced input, so
    requests differing only in start/initial state hit the same
    executable).  Caching bakes the current parameter *values* into the
    executable: use it for frozen-weight inference, not mid-training.
    """
    dec = decoder
    K, V_end = dec.beam_size, dec.end_token

    states0 = jax.tree.map(_arr, inits,
                           is_leaf=lambda x: isinstance(x, Tensor))
    leaves, treedef = jax.tree.flatten(states0)
    assert leaves, "dynamic_decode needs initial states with a batch dim"
    B = leaves[0].shape[0]
    T = int(max_step_num)

    def decode_run(leaves, start_tok):
        states0 = jax.tree.unflatten(treedef, leaves)
        # tile the initial state across beams: [B, ...] -> [B*K, ...]
        states = jax.tree.map(
            lambda a: jnp.repeat(a, K, axis=0), states0)
        NEG = jnp.float32(-1e9)
        # only beam 0 is live at t=0 so identical start beams don't
        # multiply (reference kInitialBeamScores)
        scores = jnp.tile(jnp.where(jnp.arange(K) == 0, 0.0, NEG)[None],
                          (B, 1))
        tokens = jnp.full((B, K), start_tok, jnp.int32)
        finished = jnp.zeros((B, K), bool)
        # unwritten history must be self-describing for an early exit:
        # ids pad with end_token, parents with the identity permutation
        # (so gather_tree backtracks through unwritten steps unchanged)
        ids_hist = jnp.full((T, B, K), V_end, jnp.int32)
        par_hist = jnp.tile(jnp.arange(K, dtype=jnp.int32)[None, None],
                            (T, B, 1))
        lengths = jnp.zeros((B, K), jnp.int32)

        def cond(carry):
            t, _, _, _, finished, _, _, _ = carry
            return jnp.logical_and(t < T, ~jnp.all(finished))

        def body(carry):
            t, tokens, scores, states, finished, ids_h, par_h, lens = carry
            logp, new_states = cell_step(dec, tokens.reshape(-1), states)
            V = logp.shape[-1]
            logp = logp.reshape(B, K, V)
            # finished beams only extend with end_token at zero cost
            fin_row = jnp.full((V,), float(np.float32(-1e9)), jnp.float32)
            fin_row = fin_row.at[V_end].set(0.0)
            logp = jnp.where(finished[..., None], fin_row[None, None],
                             logp)
            cand = scores[..., None] + logp            # [B, K, V]
            flat = cand.reshape(B, K * V)
            top, idx = jax.lax.top_k(flat, K)          # [B, K]
            parent = (idx // V).astype(jnp.int32)
            tok = (idx % V).astype(jnp.int32)

            def sel(a):
                a = a.reshape((B, K) + a.shape[1:])
                out = jnp.take_along_axis(
                    a, parent.reshape((B, K) + (1,) * (a.ndim - 2)),
                    axis=1)
                return out.reshape((B * K,) + a.shape[2:])

            states = jax.tree.map(sel, new_states)
            fin_parent = jnp.take_along_axis(finished, parent, axis=1)
            lens = jnp.take_along_axis(lens, parent, axis=1)
            lens = jnp.where(fin_parent, lens, lens + 1)
            finished = fin_parent | (tok == V_end)
            ids_h = ids_h.at[t].set(tok)
            par_h = par_h.at[t].set(parent)
            return (t + 1, tok, top, states, finished, ids_h, par_h, lens)

        carry = (jnp.int32(0), tokens, scores, states, finished, ids_hist,
                 par_hist, lengths)
        t, _, scores, _, _, ids_h, par_h, lens = jax.lax.while_loop(
            cond, body, carry)
        seq = _gather_tree_impl(ids_h, par_h)          # [T, B, K]
        return seq.transpose(1, 2, 0), scores, lens, t

    runner = decode_run
    if cache:
        avals_key = tuple((tuple(a.shape), str(jnp.asarray(a).dtype))
                          for a in leaves)
        key = (id(dec), K, T, V_end, treedef, avals_key)
        with _DECODE_LOCK:
            hit = _DECODE_CACHE.get(key)
            if hit is not None:
                _DECODE_CACHE.move_to_end(key)
                runner = hit[1]
        if runner is decode_run:
            runner = jax.jit(decode_run)
            with _DECODE_LOCK:
                _DECODE_CACHE[key] = (dec, runner)
                while len(_DECODE_CACHE) > _DECODE_CACHE_MAX:
                    _DECODE_CACHE.popitem(last=False)

    def decode_fn():
        return runner(leaves, jnp.int32(dec.start_token))

    seq, scores, lens, t = apply(decode_fn, op_name="dynamic_decode",
                                 nondiff=True)
    if return_length:
        return seq, scores, lens
    return seq, scores
