"""Dynamic decoding: BeamSearchDecoder + dynamic_decode.

Reference: python/paddle/nn/decode.py (BeamSearchDecoder:64,
dynamic_decode:997) lowering to the while/beam-search op stack
(operators/controlflow/while_op.cc, beam_search_op, gather_tree).

TPU-native design: the decode loop is ``lax.while_loop`` with
static-shape state — scores [B, K], token history [B, K, T_max] written
by step index — so one compiled program serves any actual decode length;
early exit is the loop predicate (all beams finished), the reference's
is_finished plumbing.  ``gather_tree`` (backtracking predecessors into
final beams) is a reverse ``lax.scan``.
"""
from __future__ import annotations

from typing import Callable, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..core.dispatch import apply
from ..core.tensor import Tensor

__all__ = ["BeamSearchDecoder", "dynamic_decode", "gather_tree"]


def _gather_tree_impl(step_ids, parent_ids):
    """[T, B, K] ids + parent beam indices -> backtracked [T, B, K]."""
    T = step_ids.shape[0]

    def back(carry, xs):
        beams = carry                       # [B, K] current beam index
        ids_t, par_t = xs
        tok = jnp.take_along_axis(ids_t, beams, axis=1)
        beams = jnp.take_along_axis(par_t, beams, axis=1)
        return beams, tok

    B, K = step_ids.shape[1:]
    init = jnp.tile(jnp.arange(K)[None, :], (B, 1))
    _, toks = jax.lax.scan(back, init, (step_ids[::-1], parent_ids[::-1]))
    return toks[::-1]


def gather_tree(step_ids, parent_ids):
    """reference: paddle.nn.functional.gather_tree / gather_tree_op.cc."""
    return apply(_gather_tree_impl, step_ids, parent_ids,
                 op_name="gather_tree", nondiff=True)


class BeamSearchDecoder:
    """reference: nn/decode.py BeamSearchDecoder:64.

    ``cell(inputs, states) -> (logits_or_out, new_states)``;
    ``output_fn`` maps cell output to vocab logits (e.g. the projection
    layer) when the cell itself doesn't."""

    def __init__(self, cell, start_token: int, end_token: int,
                 beam_size: int, embedding_fn: Optional[Callable] = None,
                 output_fn: Optional[Callable] = None):
        self.cell = cell
        self.start_token = int(start_token)
        self.end_token = int(end_token)
        self.beam_size = int(beam_size)
        self.embedding_fn = embedding_fn
        self.output_fn = output_fn



def dynamic_decode(decoder: BeamSearchDecoder, inits=None,
                   max_step_num: int = 64, is_test: bool = True,
                   return_length: bool = False, **kwargs):
    """Beam-search decode loop (reference: nn/decode.py
    dynamic_decode:997).  ``inits``: initial cell state pytree of
    Tensors/arrays with leading batch dim B.  Returns
    ``(token_ids [B, K, max_step_num], beam_scores [B, K])`` (+ lengths
    with ``return_length=True``), beams sorted best-first; positions
    past a beam's end are padded with ``end_token``.
    """
    dec = decoder
    K, V_end = dec.beam_size, dec.end_token

    def _arr(t):
        return t.data if isinstance(t, Tensor) else jnp.asarray(t)

    states0 = jax.tree.map(_arr, inits,
                           is_leaf=lambda x: isinstance(x, Tensor))
    leaves = jax.tree.leaves(states0)
    assert leaves, "dynamic_decode needs initial states with a batch dim"
    B = leaves[0].shape[0]
    T = int(max_step_num)

    def cell_step(tok_flat, states_flat):
        """[B*K] tokens + flat states -> ([B*K, V] logprobs, new states)."""
        inp = Tensor(tok_flat)
        if dec.embedding_fn is not None:
            inp = dec.embedding_fn(inp)
        out, new_states = dec.cell(inp, jax.tree.map(
            Tensor, states_flat,
            is_leaf=lambda x: not isinstance(x, (list, tuple, dict))))
        if dec.output_fn is not None:
            out = dec.output_fn(out)
        logits = _arr(out)
        new_states = jax.tree.map(_arr, new_states,
                                  is_leaf=lambda x: isinstance(x, Tensor))
        return jax.nn.log_softmax(logits.astype(jnp.float32)), new_states

    def decode_fn():
        # tile the initial state across beams: [B, ...] -> [B*K, ...]
        states = jax.tree.map(
            lambda a: jnp.repeat(a, K, axis=0), states0)
        NEG = jnp.float32(-1e9)
        # only beam 0 is live at t=0 so identical start beams don't
        # multiply (reference kInitialBeamScores)
        scores = jnp.tile(jnp.where(jnp.arange(K) == 0, 0.0, NEG)[None],
                          (B, 1))
        tokens = jnp.full((B, K), dec.start_token, jnp.int32)
        finished = jnp.zeros((B, K), bool)
        # unwritten history must be self-describing for an early exit:
        # ids pad with end_token, parents with the identity permutation
        # (so gather_tree backtracks through unwritten steps unchanged)
        ids_hist = jnp.full((T, B, K), V_end, jnp.int32)
        par_hist = jnp.tile(jnp.arange(K, dtype=jnp.int32)[None, None],
                            (T, B, 1))
        lengths = jnp.zeros((B, K), jnp.int32)

        def cond(carry):
            t, _, _, _, finished, _, _, _ = carry
            return jnp.logical_and(t < T, ~jnp.all(finished))

        def body(carry):
            t, tokens, scores, states, finished, ids_h, par_h, lens = carry
            logp, new_states = cell_step(tokens.reshape(-1), states)
            V = logp.shape[-1]
            logp = logp.reshape(B, K, V)
            # finished beams only extend with end_token at zero cost
            fin_row = jnp.full((V,), float(np.float32(-1e9)), jnp.float32)
            fin_row = fin_row.at[V_end].set(0.0)
            logp = jnp.where(finished[..., None], fin_row[None, None],
                             logp)
            cand = scores[..., None] + logp            # [B, K, V]
            flat = cand.reshape(B, K * V)
            top, idx = jax.lax.top_k(flat, K)          # [B, K]
            parent = (idx // V).astype(jnp.int32)
            tok = (idx % V).astype(jnp.int32)

            def sel(a):
                a = a.reshape((B, K) + a.shape[1:])
                out = jnp.take_along_axis(
                    a, parent.reshape((B, K) + (1,) * (a.ndim - 2)),
                    axis=1)
                return out.reshape((B * K,) + a.shape[2:])

            states = jax.tree.map(sel, new_states)
            fin_parent = jnp.take_along_axis(finished, parent, axis=1)
            lens = jnp.take_along_axis(lens, parent, axis=1)
            lens = jnp.where(fin_parent, lens, lens + 1)
            finished = fin_parent | (tok == V_end)
            ids_h = ids_h.at[t].set(tok)
            par_h = par_h.at[t].set(parent)
            return (t + 1, tok, top, states, finished, ids_h, par_h, lens)

        carry = (jnp.int32(0), tokens, scores, states, finished, ids_hist,
                 par_hist, lengths)
        t, _, scores, _, _, ids_h, par_h, lens = jax.lax.while_loop(
            cond, body, carry)
        seq = _gather_tree_impl(ids_h, par_h)          # [T, B, K]
        return seq.transpose(1, 2, 0), scores, lens, t

    seq, scores, lens, t = apply(decode_fn, op_name="dynamic_decode",
                                 nondiff=True)
    if return_length:
        return seq, scores, lens
    return seq, scores
