"""The Layer container system.

TPU-native analog of the reference's ``paddle.nn.Layer``
(reference: python/paddle/fluid/dygraph/layers.py): named parameter /
sublayer / buffer registries with attribute magic, state_dict round-trip,
train/eval flags, and forward hooks.

Two execution paths share these Layers:
- eager: ``layer(x)`` runs ops through the autograd tape
- jit: ``paddle_tpu.jit`` binds the parameter pytree to traced arrays and
  differentiates the whole step with ``jax.grad`` (SURVEY §7 design stance).
"""
from __future__ import annotations

import collections
from typing import Any, Callable, Dict, Iterator, List, Optional, Tuple

import jax.numpy as jnp
import numpy as np

from ..core.dtype import convert_dtype, get_default_dtype
from ..core.tensor import Parameter, Tensor
from . import initializer as I


class ParamAttr:
    """reference: python/paddle/fluid/param_attr.py."""

    def __init__(self, name=None, initializer=None, learning_rate=1.0,
                 regularizer=None, trainable=True, need_clip=True):
        self.name = name
        self.initializer = initializer
        self.learning_rate = learning_rate
        self.regularizer = regularizer
        self.trainable = trainable
        self.need_clip = need_clip

    @staticmethod
    def _to_attr(attr):
        if attr is None:
            return ParamAttr()
        if isinstance(attr, ParamAttr):
            return attr
        if isinstance(attr, str):
            return ParamAttr(name=attr)
        if isinstance(attr, I.Initializer):
            return ParamAttr(initializer=attr)
        if attr is False:
            return False
        raise TypeError(f"Cannot interpret {attr!r} as ParamAttr")


_unique_counters: Dict[str, int] = {}


def unique_name(prefix: str) -> str:
    """paddle.utils.unique_name-style 'prefix_N' generator (reference:
    python/paddle/fluid/unique_name.py)."""
    i = _unique_counters.get(prefix, 0)
    _unique_counters[prefix] = i + 1
    return f"{prefix}_{i}"


class Layer:
    def __init__(self, name_scope=None, dtype="float32"):
        object.__setattr__(self, "_parameters", collections.OrderedDict())
        object.__setattr__(self, "_sub_layers", collections.OrderedDict())
        object.__setattr__(self, "_buffers", collections.OrderedDict())
        object.__setattr__(self, "_non_persistable_buffer_names", set())
        self.training = True
        self._dtype = convert_dtype(dtype)
        self._forward_pre_hooks = collections.OrderedDict()
        self._forward_post_hooks = collections.OrderedDict()
        self._name_scope = name_scope or self.__class__.__name__.lower()
        self._auto_name = None  # lazy 'linear_0'-style unique scope
        self._param_suffix_counts = {}

    # -- attribute magic ---------------------------------------------------
    def __setattr__(self, name, value):
        params = self.__dict__.get("_parameters")
        layers = self.__dict__.get("_sub_layers")
        buffers = self.__dict__.get("_buffers")
        if isinstance(value, Parameter):
            if params is None:
                raise RuntimeError("call Layer.__init__ before assigning params")
            for d in (layers, buffers):
                d.pop(name, None) if d else None
            params[name] = value
        elif isinstance(value, Layer):
            if layers is None:
                raise RuntimeError("call Layer.__init__ before assigning layers")
            params.pop(name, None) if params else None
            layers[name] = value
        elif params is not None and name in params:
            if value is None:
                del params[name]
            else:
                raise TypeError(
                    f"cannot assign non-Parameter to parameter {name!r}")
        elif buffers is not None and name in buffers:
            buffers[name] = value if isinstance(value, Tensor) else Tensor(value)
        else:
            object.__setattr__(self, name, value)

    def __getattr__(self, name):
        for store in ("_parameters", "_sub_layers", "_buffers"):
            d = self.__dict__.get(store)
            if d is not None and name in d:
                return d[name]
        raise AttributeError(
            f"'{type(self).__name__}' object has no attribute '{name}'")

    def __delattr__(self, name):
        for store in ("_parameters", "_sub_layers", "_buffers"):
            d = self.__dict__.get(store)
            if d is not None and name in d:
                del d[name]
                return
        object.__delattr__(self, name)

    def __dir__(self):
        extra = (list(self._parameters) + list(self._sub_layers)
                 + list(self._buffers))
        return sorted(set(super().__dir__() + extra))

    # -- construction helpers ---------------------------------------------
    def create_parameter(self, shape, attr=None, dtype=None, is_bias=False,
                         default_initializer=None) -> Optional[Parameter]:
        attr = ParamAttr._to_attr(attr)
        if attr is False:
            return None
        dtype = convert_dtype(dtype) or self._dtype or get_default_dtype()
        init = attr.initializer or default_initializer or (
            I.Constant(0.0) if is_bias else I.XavierNormal())
        name = attr.name
        if name is None:
            # paddle-convention auto-name 'linear_0.w_0' / 'linear_0.b_0'
            # so apply_decay_param_fun-style predicates work unmodified
            if self._auto_name is None:
                self._auto_name = unique_name(self._name_scope)
            suffix = "b" if is_bias else "w"
            k = self._param_suffix_counts.get(suffix, 0)
            self._param_suffix_counts[suffix] = k + 1
            name = f"{self._auto_name}.{suffix}_{k}"
        p = Parameter(init(tuple(shape), dtype), name=name,
                      trainable=attr.trainable, regularizer=attr.regularizer,
                      need_clip=attr.need_clip)
        p.optimize_attr["learning_rate"] = attr.learning_rate
        return p

    def add_parameter(self, name: str, parameter: Optional[Parameter]):
        if parameter is None:
            self._parameters[name] = None
        else:
            self._parameters[name] = parameter
        return parameter

    def add_sublayer(self, name: str, sublayer: "Layer"):
        self._sub_layers[name] = sublayer
        return sublayer

    def register_buffer(self, name: str, tensor, persistable=True):
        t = tensor if isinstance(tensor, Tensor) or tensor is None else Tensor(tensor)
        self._buffers[name] = t
        if not persistable:
            self._non_persistable_buffer_names.add(name)
        return t

    # -- traversal ---------------------------------------------------------
    def named_parameters(self, prefix="", include_sublayers=True
                         ) -> Iterator[Tuple[str, Parameter]]:
        seen = set()
        for name, p in self._parameters.items():
            if p is not None and id(p) not in seen:
                seen.add(id(p))
                yield (f"{prefix}.{name}" if prefix else name), p
        if include_sublayers:
            for lname, layer in self._sub_layers.items():
                if layer is None:
                    continue
                sub_prefix = f"{prefix}.{lname}" if prefix else lname
                for n, p in layer.named_parameters(sub_prefix):
                    if id(p) not in seen:
                        seen.add(id(p))
                        yield n, p

    def parameters(self, include_sublayers=True) -> List[Parameter]:
        return [p for _, p in self.named_parameters(
            include_sublayers=include_sublayers)]

    def named_buffers(self, prefix="", include_sublayers=True):
        for name, b in self._buffers.items():
            if b is not None:
                yield (f"{prefix}.{name}" if prefix else name), b
        if include_sublayers:
            for lname, layer in self._sub_layers.items():
                if layer is None:
                    continue
                sub_prefix = f"{prefix}.{lname}" if prefix else lname
                yield from layer.named_buffers(sub_prefix)

    def buffers(self, include_sublayers=True):
        return [b for _, b in self.named_buffers(
            include_sublayers=include_sublayers)]

    def _named_persistable_buffers(self, prefix=""):
        """Like named_buffers, but each layer filters its OWN
        non-persistable buffers (so sublayer persistability is honored)."""
        for name, b in self._buffers.items():
            if b is not None and name not in self._non_persistable_buffer_names:
                yield (f"{prefix}.{name}" if prefix else name), b
        for lname, layer in self._sub_layers.items():
            if layer is None:
                continue
            sub_prefix = f"{prefix}.{lname}" if prefix else lname
            yield from layer._named_persistable_buffers(sub_prefix)

    def children(self) -> Iterator["Layer"]:
        for l in self._sub_layers.values():
            if l is not None:
                yield l

    def named_children(self):
        for n, l in self._sub_layers.items():
            if l is not None:
                yield n, l

    def sublayers(self, include_self=False) -> List["Layer"]:
        out = [self] if include_self else []
        for l in self.children():
            out.append(l)
            out.extend(l.sublayers())
        return out

    def named_sublayers(self, prefix="", include_self=False):
        if include_self:
            yield prefix, self
        for n, l in self.named_children():
            p = f"{prefix}.{n}" if prefix else n
            yield p, l
            yield from l.named_sublayers(p)

    def apply(self, fn: Callable[["Layer"], None]) -> "Layer":
        for l in self.children():
            l.apply(fn)
        fn(self)
        return self

    # -- modes -------------------------------------------------------------
    def train(self):
        self.training = True
        for l in self.children():
            l.train()
        return self

    def eval(self):
        self.training = False
        for l in self.children():
            l.eval()
        return self

    # -- state dict --------------------------------------------------------
    def state_dict(self, destination=None, include_sublayers=True,
                   structured_name_prefix="", use_hook=True) -> Dict[str, Tensor]:
        dest = destination if destination is not None else collections.OrderedDict()
        for n, p in self.named_parameters(structured_name_prefix.rstrip(".")):
            # a compiled step may hold the authoritative value elsewhere
            # (ZeRO-3 padded shards, LocalSGD replicas); let it refresh
            # p.data before we hand out a stale mirror
            owner = getattr(p, "_param_owner_step", None)
            owner = owner() if owner is not None else None
            if owner is not None:
                owner.sync_params()
            dest[n] = p
        for n, b in self._named_persistable_buffers(
                structured_name_prefix.rstrip(".")):
            dest[n] = b
        return dest

    def set_state_dict(self, state_dict, use_structured_name=True):
        """Load values into existing parameters/buffers (shape-checked)."""
        own = self.state_dict()
        missing, unexpected = [], []
        for k, v in state_dict.items():
            if k not in own:
                unexpected.append(k)
                continue
            tgt = own[k]
            arr = v.data if isinstance(v, Tensor) else jnp.asarray(v)
            if tuple(arr.shape) != tgt.shape_tuple:
                raise ValueError(
                    f"shape mismatch for {k}: loading {list(arr.shape)} into "
                    f"{tgt.shape}")
            # copy: loaded params must not alias the source (donation-safe)
            tgt.data = jnp.array(arr, dtype=tgt.data.dtype, copy=True)
        for k in own:
            if k not in state_dict:
                missing.append(k)
        return missing, unexpected

    load_dict = set_state_dict

    def to(self, device=None, dtype=None, blocking=None):
        if dtype is not None:
            d = convert_dtype(dtype)
            for p in self.parameters():
                p.data = p.data.astype(d)
            for b in self.buffers():
                if jnp.issubdtype(b.data.dtype, jnp.floating):
                    b.data = b.data.astype(d)
            self._dtype = d
        return self

    def astype(self, dtype):
        return self.to(dtype=dtype)

    def float(self):
        return self.to(dtype="float32")

    def half(self):
        return self.to(dtype="float16")

    def bfloat16(self):
        return self.to(dtype="bfloat16")

    # -- hooks & call ------------------------------------------------------
    def register_forward_pre_hook(self, hook):
        handle = _HookHandle(self._forward_pre_hooks)
        self._forward_pre_hooks[handle.id] = hook
        return handle

    def register_forward_post_hook(self, hook):
        handle = _HookHandle(self._forward_post_hooks)
        self._forward_post_hooks[handle.id] = hook
        return handle

    def forward(self, *inputs, **kwargs):
        raise NotImplementedError(
            f"{type(self).__name__} must implement forward()")

    def __call__(self, *inputs, **kwargs):
        for hook in self._forward_pre_hooks.values():
            result = hook(self, inputs)
            if result is not None:
                inputs = result if isinstance(result, tuple) else (result,)
        out = self.forward(*inputs, **kwargs)
        for hook in self._forward_post_hooks.values():
            result = hook(self, inputs, out)
            if result is not None:
                out = result
        return out

    def extra_repr(self) -> str:
        return ""

    def __repr__(self):
        extra = self.extra_repr()
        lines = []
        for n, l in self._sub_layers.items():
            sub = repr(l).split("\n")
            sub = [sub[0]] + ["  " + s for s in sub[1:]]
            lines.append(f"  ({n}): " + "\n".join(sub))
        main = f"{type(self).__name__}({extra}" + ("" if not lines else "\n")
        if lines:
            main += "\n".join(lines) + "\n"
        return main + ")"

    def full_name(self):
        return self._name_scope

    def clear_gradients(self):
        for p in self.parameters():
            p.clear_grad()


class _HookHandle:
    _next_id = 0

    def __init__(self, store):
        self.store = store
        self.id = _HookHandle._next_id
        _HookHandle._next_id += 1

    def remove(self):
        self.store.pop(self.id, None)
