"""Activation layers (reference: python/paddle/nn/layer/activation.py)."""
from __future__ import annotations

from .. import functional as F
from .. import initializer as I
from ..layer_base import Layer


def _simple(fname, cls_name):
    class _Act(Layer):
        def __init__(self, name=None):
            super().__init__()

        def forward(self, x):
            return getattr(F, fname)(x)
    _Act.__name__ = cls_name
    _Act.__qualname__ = cls_name
    return _Act


ReLU = _simple("relu", "ReLU")
ReLU6 = _simple("relu6", "ReLU6")
Sigmoid = _simple("sigmoid", "Sigmoid")
Tanh = _simple("tanh", "Tanh")
Silu = _simple("silu", "Silu")
Swish = _simple("swish", "Swish")
Mish = _simple("mish", "Mish")
Softsign = _simple("softsign", "Softsign")
Tanhshrink = _simple("tanhshrink", "Tanhshrink")
Hardswish = _simple("hardswish", "Hardswish")
Hardsigmoid = _simple("hardsigmoid", "Hardsigmoid")
GLU = _simple("glu", "GLU")


class GELU(Layer):
    def __init__(self, approximate=False, name=None):
        super().__init__()
        self._approximate = approximate

    def forward(self, x):
        return F.gelu(x, self._approximate)


class LeakyReLU(Layer):
    def __init__(self, negative_slope=0.01, name=None):
        super().__init__()
        self._negative_slope = negative_slope

    def forward(self, x):
        return F.leaky_relu(x, self._negative_slope)


class PReLU(Layer):
    def __init__(self, num_parameters=1, init=0.25, weight_attr=None,
                 data_format="NCHW", name=None):
        super().__init__()
        self._data_format = data_format
        self.weight = self.create_parameter(
            [num_parameters], attr=weight_attr,
            default_initializer=I.Constant(init))

    def forward(self, x):
        return F.prelu(x, self.weight, self._data_format)


class ELU(Layer):
    def __init__(self, alpha=1.0, name=None):
        super().__init__()
        self._alpha = alpha

    def forward(self, x):
        return F.elu(x, self._alpha)


class SELU(Layer):
    def __init__(self, scale=1.0507009873554804934193349852946,
                 alpha=1.6732632423543772848170429916717, name=None):
        super().__init__()
        self._scale, self._alpha = scale, alpha

    def forward(self, x):
        return F.selu(x, self._scale, self._alpha)


class CELU(Layer):
    def __init__(self, alpha=1.0, name=None):
        super().__init__()
        self._alpha = alpha

    def forward(self, x):
        return F.celu(x, self._alpha)


class Hardtanh(Layer):
    def __init__(self, min=-1.0, max=1.0, name=None):
        super().__init__()
        self._min, self._max = min, max

    def forward(self, x):
        return F.hardtanh(x, self._min, self._max)


class Hardshrink(Layer):
    def __init__(self, threshold=0.5, name=None):
        super().__init__()
        self._threshold = threshold

    def forward(self, x):
        return F.hardshrink(x, self._threshold)


class Softshrink(Layer):
    def __init__(self, threshold=0.5, name=None):
        super().__init__()
        self._threshold = threshold

    def forward(self, x):
        return F.softshrink(x, self._threshold)


class Softplus(Layer):
    def __init__(self, beta=1.0, threshold=20.0, name=None):
        super().__init__()
        self._beta, self._threshold = beta, threshold

    def forward(self, x):
        return F.softplus(x, self._beta, self._threshold)


class Softmax(Layer):
    def __init__(self, axis=-1, name=None):
        super().__init__()
        self._axis = axis

    def forward(self, x):
        return F.softmax(x, self._axis)


class LogSoftmax(Layer):
    def __init__(self, axis=-1, name=None):
        super().__init__()
        self._axis = axis

    def forward(self, x):
        return F.log_softmax(x, self._axis)


class Maxout(Layer):
    def __init__(self, groups, axis=1, name=None):
        super().__init__()
        self._groups, self._axis = groups, axis

    def forward(self, x):
        return F.maxout(x, self._groups, self._axis)


class ThresholdedReLU(Layer):
    def __init__(self, threshold=1.0, name=None):
        super().__init__()
        self._threshold = threshold

    def forward(self, x):
        import jax.numpy as jnp
        from ...core.dispatch import apply
        t = self._threshold
        return apply(lambda a: jnp.where(a > t, a, 0.0), x,
                     op_name="thresholded_relu")


class LogSigmoid(Layer):
    def forward(self, x):
        return F.log_sigmoid(x)
