"""Recurrent layers (reference: python/paddle/nn/layer/rnn.py; the
reference executes RNNs as a `recurrent` sub-block op or cudnn kernels).

TPU-first: the time loop is a single ``lax.scan`` inside one traced op, so
XLA compiles the whole unrolled recurrence into one fused loop — no
per-step Python dispatch."""
from __future__ import annotations

import math

import jax
import jax.numpy as jnp

from ...core.dispatch import apply
from ...core.tensor import Tensor
from .. import functional as F
from .. import initializer as I
from ..layer_base import Layer


class RNNCellBase(Layer):
    def get_initial_states(self, batch_ref, shape=None, dtype=None,
                           init_value=0.0, batch_dim_idx=0):
        import paddle_tpu as paddle
        B = batch_ref.shape[batch_dim_idx]
        return paddle.full([B, self.hidden_size], init_value)


class SimpleRNNCell(RNNCellBase):
    def __init__(self, input_size, hidden_size, activation="tanh",
                 weight_ih_attr=None, weight_hh_attr=None, bias_ih_attr=None,
                 bias_hh_attr=None, name=None):
        super().__init__()
        self.input_size = input_size
        self.hidden_size = hidden_size
        self.activation = activation
        std = 1.0 / math.sqrt(hidden_size)
        u = I.Uniform(-std, std)
        self.weight_ih = self.create_parameter(
            [hidden_size, input_size], weight_ih_attr, default_initializer=u)
        self.weight_hh = self.create_parameter(
            [hidden_size, hidden_size], weight_hh_attr, default_initializer=u)
        self.bias_ih = self.create_parameter(
            [hidden_size], bias_ih_attr, is_bias=True, default_initializer=u)
        self.bias_hh = self.create_parameter(
            [hidden_size], bias_hh_attr, is_bias=True, default_initializer=u)

    @property
    def state_shape(self):
        return (self.hidden_size,)

    def forward(self, inputs, states=None):
        if states is None:
            states = self.get_initial_states(inputs)
        act = jnp.tanh if self.activation == "tanh" else jax.nn.relu

        def _cell(x, h, wi, wh, bi, bh):
            out = act(x @ wi.T + bi + h @ wh.T + bh)
            return out
        h = apply(_cell, inputs, states, self.weight_ih, self.weight_hh,
                  self.bias_ih, self.bias_hh, op_name="simple_rnn_cell")
        return h, h


class LSTMCell(RNNCellBase):
    """Gate order i,f,g,o (paddle convention, rnn.py LSTMCell)."""

    def __init__(self, input_size, hidden_size, weight_ih_attr=None,
                 weight_hh_attr=None, bias_ih_attr=None, bias_hh_attr=None,
                 name=None):
        super().__init__()
        self.input_size = input_size
        self.hidden_size = hidden_size
        std = 1.0 / math.sqrt(hidden_size)
        u = I.Uniform(-std, std)
        self.weight_ih = self.create_parameter(
            [4 * hidden_size, input_size], weight_ih_attr,
            default_initializer=u)
        self.weight_hh = self.create_parameter(
            [4 * hidden_size, hidden_size], weight_hh_attr,
            default_initializer=u)
        self.bias_ih = self.create_parameter(
            [4 * hidden_size], bias_ih_attr, is_bias=True,
            default_initializer=u)
        self.bias_hh = self.create_parameter(
            [4 * hidden_size], bias_hh_attr, is_bias=True,
            default_initializer=u)

    @property
    def state_shape(self):
        return ((self.hidden_size,), (self.hidden_size,))

    def forward(self, inputs, states=None):
        if states is None:
            h = self.get_initial_states(inputs)
            c = self.get_initial_states(inputs)
        else:
            h, c = states
        H = self.hidden_size

        def _cell(x, h, c, wi, wh, bi, bh):
            gates = x @ wi.T + bi + h @ wh.T + bh
            i, f, g, o = jnp.split(gates, 4, axis=-1)
            i, f, o = jax.nn.sigmoid(i), jax.nn.sigmoid(f), jax.nn.sigmoid(o)
            g = jnp.tanh(g)
            new_c = f * c + i * g
            new_h = o * jnp.tanh(new_c)
            return new_h, new_c
        new_h, new_c = apply(_cell, inputs, h, c, self.weight_ih,
                             self.weight_hh, self.bias_ih, self.bias_hh,
                             op_name="lstm_cell")
        return new_h, (new_h, new_c)


class GRUCell(RNNCellBase):
    """Gate order r,z,c (paddle convention)."""

    def __init__(self, input_size, hidden_size, weight_ih_attr=None,
                 weight_hh_attr=None, bias_ih_attr=None, bias_hh_attr=None,
                 name=None):
        super().__init__()
        self.input_size = input_size
        self.hidden_size = hidden_size
        std = 1.0 / math.sqrt(hidden_size)
        u = I.Uniform(-std, std)
        self.weight_ih = self.create_parameter(
            [3 * hidden_size, input_size], weight_ih_attr,
            default_initializer=u)
        self.weight_hh = self.create_parameter(
            [3 * hidden_size, hidden_size], weight_hh_attr,
            default_initializer=u)
        self.bias_ih = self.create_parameter(
            [3 * hidden_size], bias_ih_attr, is_bias=True,
            default_initializer=u)
        self.bias_hh = self.create_parameter(
            [3 * hidden_size], bias_hh_attr, is_bias=True,
            default_initializer=u)

    @property
    def state_shape(self):
        return (self.hidden_size,)

    def forward(self, inputs, states=None):
        if states is None:
            states = self.get_initial_states(inputs)

        def _cell(x, h, wi, wh, bi, bh):
            xg = x @ wi.T + bi
            hg = h @ wh.T + bh
            xr, xz, xc = jnp.split(xg, 3, axis=-1)
            hr, hz, hc = jnp.split(hg, 3, axis=-1)
            r = jax.nn.sigmoid(xr + hr)
            z = jax.nn.sigmoid(xz + hz)
            c = jnp.tanh(xc + r * hc)
            return (1 - z) * c + z * h
        h = apply(_cell, inputs, states, self.weight_ih, self.weight_hh,
                  self.bias_ih, self.bias_hh, op_name="gru_cell")
        return h, h


def _scan_layer(mode, x, h0, c0, wi, wh, bi, bh, reverse=False):
    """One direction of one layer as a lax.scan (pure function)."""
    def step(carry, xt):
        if mode == "LSTM":
            h, c = carry
            gates = xt @ wi.T + bi + h @ wh.T + bh
            i, f, g, o = jnp.split(gates, 4, axis=-1)
            i, f, o = jax.nn.sigmoid(i), jax.nn.sigmoid(f), jax.nn.sigmoid(o)
            g = jnp.tanh(g)
            c = f * c + i * g
            h = o * jnp.tanh(c)
            return (h, c), h
        elif mode == "GRU":
            h = carry
            xg = xt @ wi.T + bi
            hg = h @ wh.T + bh
            xr, xz, xc = jnp.split(xg, 3, axis=-1)
            hr, hz, hc = jnp.split(hg, 3, axis=-1)
            r = jax.nn.sigmoid(xr + hr)
            z = jax.nn.sigmoid(xz + hz)
            c = jnp.tanh(xc + r * hc)
            h = (1 - z) * c + z * h
            return h, h
        else:
            h = carry
            h = jnp.tanh(xt @ wi.T + bi + h @ wh.T + bh)
            return h, h

    init = (h0, c0) if mode == "LSTM" else h0
    carry, ys = jax.lax.scan(step, init, x, reverse=reverse)
    return carry, ys


class _RNNBase(Layer):
    def __init__(self, mode, input_size, hidden_size, num_layers=1,
                 direction="forward", time_major=False, dropout=0.0,
                 weight_ih_attr=None, weight_hh_attr=None, bias_ih_attr=None,
                 bias_hh_attr=None, name=None):
        super().__init__()
        self.mode = mode
        self.input_size = input_size
        self.hidden_size = hidden_size
        self.num_layers = num_layers
        self.direction = direction
        self.time_major = time_major
        self.dropout = dropout
        self.num_directions = 2 if direction in ("bidirect",
                                                 "bidirectional") else 1
        g = {"LSTM": 4, "GRU": 3, "RNN_TANH": 1, "RNN_RELU": 1}[mode]
        std = 1.0 / math.sqrt(hidden_size)
        u = I.Uniform(-std, std)
        self._all_weights = []
        for layer in range(num_layers):
            for d in range(self.num_directions):
                in_sz = (input_size if layer == 0
                         else hidden_size * self.num_directions)
                suffix = "_reverse" if d == 1 else ""
                wi = self.create_parameter([g * hidden_size, in_sz],
                                           weight_ih_attr,
                                           default_initializer=u)
                wh = self.create_parameter([g * hidden_size, hidden_size],
                                           weight_hh_attr,
                                           default_initializer=u)
                bi = self.create_parameter([g * hidden_size], bias_ih_attr,
                                           is_bias=True,
                                           default_initializer=u)
                bh = self.create_parameter([g * hidden_size], bias_hh_attr,
                                           is_bias=True,
                                           default_initializer=u)
                self.add_parameter(f"weight_ih_l{layer}{suffix}", wi)
                self.add_parameter(f"weight_hh_l{layer}{suffix}", wh)
                self.add_parameter(f"bias_ih_l{layer}{suffix}", bi)
                self.add_parameter(f"bias_hh_l{layer}{suffix}", bh)
                self._all_weights.append((wi, wh, bi, bh))

    def forward(self, inputs, initial_states=None, sequence_length=None):
        import paddle_tpu as paddle
        x = inputs
        if not self.time_major:
            x = x.transpose([1, 0, 2])  # -> [T, B, F]
        T, B = x.shape[0], x.shape[1]
        L, D, H = self.num_layers, self.num_directions, self.hidden_size
        is_lstm = self.mode == "LSTM"

        if initial_states is None:
            h0 = paddle.zeros([L * D, B, H])
            c0 = paddle.zeros([L * D, B, H]) if is_lstm else None
        else:
            if is_lstm:
                h0, c0 = initial_states
            else:
                h0, c0 = initial_states, None

        mode = self.mode
        n_weights = len(self._all_weights)

        def _run(xa, h0a, *rest):
            if is_lstm:
                c0a = rest[0]
                flat_w = rest[1:]
            else:
                c0a = None
                flat_w = rest
            ws = [flat_w[i * 4:(i + 1) * 4] for i in range(n_weights)]
            out = xa
            final_h, final_c = [], []
            for layer in range(L):
                outs_d = []
                for d in range(D):
                    wi, wh, bi, bh = ws[layer * D + d]
                    hh = h0a[layer * D + d]
                    cc = c0a[layer * D + d] if is_lstm else None
                    carry, ys = _scan_layer(mode, out, hh, cc, wi, wh, bi,
                                            bh, reverse=(d == 1))
                    if is_lstm:
                        final_h.append(carry[0])
                        final_c.append(carry[1])
                    else:
                        final_h.append(carry)
                    outs_d.append(ys)
                out = (outs_d[0] if D == 1
                       else jnp.concatenate(outs_d, axis=-1))
            fh = jnp.stack(final_h, axis=0)
            if is_lstm:
                fc = jnp.stack(final_c, axis=0)
                return out, fh, fc
            return out, fh

        flat_params = [p for tup in self._all_weights for p in tup]
        if is_lstm:
            res = apply(_run, x, h0, c0, *flat_params, op_name="lstm")
            out, fh, fc = res
            states = (fh, fc)
        else:
            out, fh = apply(_run, x, h0, *flat_params,
                            op_name=self.mode.lower())
            states = fh
        if not self.time_major:
            out = out.transpose([1, 0, 2])
        return out, states


class SimpleRNN(_RNNBase):
    def __init__(self, input_size, hidden_size, num_layers=1,
                 direction="forward", time_major=False, dropout=0.0,
                 activation="tanh", **kwargs):
        mode = "RNN_TANH" if activation == "tanh" else "RNN_RELU"
        super().__init__(mode, input_size, hidden_size, num_layers,
                         direction, time_major, dropout, **kwargs)


class LSTM(_RNNBase):
    def __init__(self, input_size, hidden_size, num_layers=1,
                 direction="forward", time_major=False, dropout=0.0,
                 **kwargs):
        super().__init__("LSTM", input_size, hidden_size, num_layers,
                         direction, time_major, dropout, **kwargs)


class GRU(_RNNBase):
    def __init__(self, input_size, hidden_size, num_layers=1,
                 direction="forward", time_major=False, dropout=0.0,
                 **kwargs):
        super().__init__("GRU", input_size, hidden_size, num_layers,
                         direction, time_major, dropout, **kwargs)


class RNN(Layer):
    """Generic cell-driven RNN wrapper (reference: nn/layer/rnn.py RNN).

    Eager: python loop over time.  For compiled execution use the fused
    SimpleRNN/LSTM/GRU classes (lax.scan)."""

    def __init__(self, cell, is_reverse=False, time_major=False):
        super().__init__()
        self.cell = cell
        self.is_reverse = is_reverse
        self.time_major = time_major

    def forward(self, inputs, initial_states=None, sequence_length=None):
        import paddle_tpu as paddle
        x = inputs if self.time_major else inputs.transpose([1, 0, 2])
        T = x.shape[0]
        state = initial_states
        outs = []
        steps = range(T - 1, -1, -1) if self.is_reverse else range(T)
        for t in steps:
            out, state = self.cell(x[t], state)
            outs.append(out)
        if self.is_reverse:
            outs = outs[::-1]
        y = paddle.stack(outs, axis=0)
        if not self.time_major:
            y = y.transpose([1, 0, 2])
        return y, state


class BiRNN(Layer):
    def __init__(self, cell_fw, cell_bw, time_major=False):
        super().__init__()
        self.rnn_fw = RNN(cell_fw, False, time_major)
        self.rnn_bw = RNN(cell_bw, True, time_major)

    def forward(self, inputs, initial_states=None, sequence_length=None):
        import paddle_tpu as paddle
        states_fw, states_bw = (initial_states if initial_states is not None
                                else (None, None))
        y_fw, s_fw = self.rnn_fw(inputs, states_fw)
        y_bw, s_bw = self.rnn_bw(inputs, states_bw)
        return paddle.concat([y_fw, y_bw], axis=-1), (s_fw, s_bw)
