"""Normalisation layers (reference: python/paddle/nn/layer/norm.py;
batch_norm_op.cc, layer_norm_op.cc).

BatchNorm running stats are registered buffers; in eager training mode the
layer updates them in place.  Under jit, the functionalize pass captures
buffer writes and threads them through the compiled step (SURVEY §7
hard-parts: in-place semantics under functional XLA)."""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ...core import autograd
from ...core.tensor import Tensor
from .. import functional as F
from .. import initializer as I
from ..layer_base import Layer


class _BatchNormBase(Layer):
    def __init__(self, num_features, momentum=0.9, epsilon=1e-5,
                 weight_attr=None, bias_attr=None, data_format="NCHW",
                 use_global_stats=None, name=None):
        super().__init__()
        self._num_features = num_features
        self._momentum = momentum
        self._epsilon = epsilon
        self._data_format = data_format
        self._use_global_stats = use_global_stats
        self.weight = (None if weight_attr is False else
                       self.create_parameter(
                           [num_features], attr=weight_attr,
                           default_initializer=I.Constant(1.0)))
        self.bias = (None if bias_attr is False else
                     self.create_parameter([num_features], attr=bias_attr,
                                           is_bias=True))
        self.register_buffer("_mean", Tensor(jnp.zeros([num_features])))
        self.register_buffer("_variance", Tensor(jnp.ones([num_features])))

    def forward(self, x):
        training = self.training and not self._use_global_stats
        if training:
            # update running stats (in eager; functionalized under jit)
            ch_axis = (1 if self._data_format.startswith("NC")
                       and x.ndim > 1 else -1)
            axes = tuple(i for i in range(x.ndim)
                         if i != ch_axis % x.ndim)
            with autograd.no_grad():
                m = jnp.mean(x.data, axis=axes)
                v = jnp.var(x.data, axis=axes)
                mom = self._momentum
                self._mean.data = mom * self._mean.data + (1 - mom) * m
                self._variance.data = (mom * self._variance.data
                                       + (1 - mom) * v)
        return F.batch_norm(x, self._mean, self._variance, self.weight,
                            self.bias, training=training,
                            momentum=self._momentum, epsilon=self._epsilon,
                            data_format=self._data_format,
                            use_global_stats=self._use_global_stats)

    def extra_repr(self):
        return f"num_features={self._num_features}"


class BatchNorm1D(_BatchNormBase):
    pass


class BatchNorm2D(_BatchNormBase):
    pass


class BatchNorm3D(_BatchNormBase):
    def __init__(self, num_features, momentum=0.9, epsilon=1e-5,
                 weight_attr=None, bias_attr=None, data_format="NCDHW",
                 use_global_stats=None, name=None):
        super().__init__(num_features, momentum, epsilon, weight_attr,
                         bias_attr, "NCHW" if data_format == "NCDHW"
                         else data_format, use_global_stats, name)


class BatchNorm(_BatchNormBase):
    """Old-style paddle.nn.BatchNorm (fluid dygraph BatchNorm parity)."""

    def __init__(self, num_channels, act=None, momentum=0.9, epsilon=1e-5,
                 param_attr=None, bias_attr=None, dtype="float32",
                 data_layout="NCHW", in_place=False, moving_mean_name=None,
                 moving_variance_name=None, do_model_average_for_mean_and_var=True,
                 use_global_stats=False, trainable_statistics=False):
        super().__init__(num_channels, momentum, epsilon, param_attr,
                         bias_attr, data_layout,
                         use_global_stats or None)
        self._act = act

    def forward(self, x):
        out = super().forward(x)
        if self._act:
            out = getattr(F, self._act)(out)
        return out


class SyncBatchNorm(_BatchNormBase):
    """On TPU, batch stats sync falls out of SPMD compilation: under pjit
    the mean/var reductions become cross-replica automatically (reference's
    sync_batch_norm_op.cu is NCCL-based; no analog needed)."""

    @classmethod
    def convert_sync_batchnorm(cls, layer):
        """Under the SPMD train step, batch norm statistics are computed
        over the GLOBAL (dp-sharded) batch by GSPMD, so conversion is the
        identity.  Under eager multi-process DataParallel there is no
        cross-process stat sync — warn so the silent-identity isn't
        mistaken for NCCL SyncBatchNorm."""
        import warnings
        from ...distributed.env import get_world_size
        if get_world_size() > 1:
            warnings.warn(
                "convert_sync_batchnorm: running stats are NOT synced "
                "across eager DataParallel processes; use the SPMD train "
                "step (batch sharded over 'dp') for global-batch BN "
                "statistics")
        return layer


class LayerNorm(Layer):
    """reference: nn/layer/norm.py LayerNorm → layer_norm_op.cc."""

    def __init__(self, normalized_shape, epsilon=1e-5, weight_attr=None,
                 bias_attr=None, name=None):
        super().__init__()
        if isinstance(normalized_shape, int):
            normalized_shape = (normalized_shape,)
        self._normalized_shape = tuple(normalized_shape)
        self._epsilon = epsilon
        self.weight = (None if weight_attr is False else
                       self.create_parameter(
                           self._normalized_shape, attr=weight_attr,
                           default_initializer=I.Constant(1.0)))
        self.bias = (None if bias_attr is False else
                     self.create_parameter(self._normalized_shape,
                                           attr=bias_attr, is_bias=True))

    def forward(self, x):
        return F.layer_norm(x, self._normalized_shape, self.weight,
                            self.bias, self._epsilon)

    def extra_repr(self):
        return f"normalized_shape={list(self._normalized_shape)}"


class GroupNorm(Layer):
    def __init__(self, num_groups, num_channels, epsilon=1e-5,
                 weight_attr=None, bias_attr=None, data_format="NCHW",
                 name=None):
        super().__init__()
        self._num_groups = num_groups
        self._epsilon = epsilon
        self._data_format = data_format
        self.weight = (None if weight_attr is False else
                       self.create_parameter(
                           [num_channels], attr=weight_attr,
                           default_initializer=I.Constant(1.0)))
        self.bias = (None if bias_attr is False else
                     self.create_parameter([num_channels], attr=bias_attr,
                                           is_bias=True))

    def forward(self, x):
        return F.group_norm(x, self._num_groups, self._epsilon, self.weight,
                            self.bias, self._data_format)


class InstanceNorm2D(Layer):
    def __init__(self, num_features, epsilon=1e-5, momentum=0.9,
                 weight_attr=None, bias_attr=None, data_format="NCHW",
                 name=None):
        super().__init__()
        self._epsilon = epsilon
        self.weight = (None if weight_attr is False else
                       self.create_parameter(
                           [num_features], attr=weight_attr,
                           default_initializer=I.Constant(1.0)))
        self.bias = (None if bias_attr is False else
                     self.create_parameter([num_features], attr=bias_attr,
                                           is_bias=True))

    def forward(self, x):
        return F.instance_norm(x, weight=self.weight, bias=self.bias,
                               eps=self._epsilon)


InstanceNorm1D = InstanceNorm2D
InstanceNorm3D = InstanceNorm2D


class LocalResponseNorm(Layer):
    def __init__(self, size, alpha=1e-4, beta=0.75, k=1.0,
                 data_format="NCHW", name=None):
        super().__init__()
        self.args = (size, alpha, beta, k, data_format)

    def forward(self, x):
        return F.local_response_norm(x, *self.args)


class SpectralNorm(Layer):
    def __init__(self, weight_shape, dim=0, power_iters=1, eps=1e-12,
                 name=None):
        super().__init__()
        self._dim = dim
        self._power_iters = power_iters
        self._eps = eps
        h = weight_shape[dim]
        w = 1
        for i, s in enumerate(weight_shape):
            if i != dim:
                w *= s
        self.weight_u = self.create_parameter(
            [h], default_initializer=I.Normal(0.0, 1.0))
        self.weight_u.stop_gradient = True
        self.weight_v = self.create_parameter(
            [w], default_initializer=I.Normal(0.0, 1.0))
        self.weight_v.stop_gradient = True

    def forward(self, weight):
        from ...core.dispatch import apply
        dim, iters, eps = self._dim, self._power_iters, self._eps

        def _sn(w, u, v):
            wm = jnp.moveaxis(w, dim, 0).reshape(w.shape[dim], -1)
            for _ in range(iters):
                v = wm.T @ u
                v = v / (jnp.linalg.norm(v) + eps)
                u = wm @ v
                u = u / (jnp.linalg.norm(u) + eps)
            sigma = u @ wm @ v
            return w / sigma
        return apply(_sn, weight, self.weight_u, self.weight_v,
                     op_name="spectral_norm")
