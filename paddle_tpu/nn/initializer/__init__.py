"""Weight initializers (reference: python/paddle/nn/initializer/*,
fluid/initializer.py — lowered there to fill ops; here they are pure
key->array functions drawn from the global Generator)."""
from __future__ import annotations

import math

import jax
import jax.numpy as jnp
import numpy as np

from ...core.rng import next_key


def _fans(shape):
    shape = tuple(shape)
    if len(shape) < 1:
        return 1, 1
    if len(shape) == 1:
        return shape[0], shape[0]
    if len(shape) == 2:
        return shape[0], shape[1]
    # conv kernels [out_c, in_c, *spatial] (paddle layout)
    receptive = int(np.prod(shape[2:]))
    return shape[1] * receptive, shape[0] * receptive


class Initializer:
    def __call__(self, shape, dtype=jnp.float32):
        raise NotImplementedError


class Constant(Initializer):
    def __init__(self, value=0.0):
        self.value = value

    def __call__(self, shape, dtype=jnp.float32):
        return jnp.full(tuple(shape), self.value, dtype)


class Normal(Initializer):
    def __init__(self, mean=0.0, std=1.0):
        self.mean, self.std = mean, std

    def __call__(self, shape, dtype=jnp.float32):
        return (jax.random.normal(next_key(), tuple(shape), dtype) * self.std
                + self.mean)


class TruncatedNormal(Initializer):
    def __init__(self, mean=0.0, std=1.0):
        self.mean, self.std = mean, std

    def __call__(self, shape, dtype=jnp.float32):
        return (jax.random.truncated_normal(next_key(), -2.0, 2.0,
                                            tuple(shape), dtype) * self.std
                + self.mean)


class Uniform(Initializer):
    def __init__(self, low=-1.0, high=1.0):
        self.low, self.high = low, high

    def __call__(self, shape, dtype=jnp.float32):
        return jax.random.uniform(next_key(), tuple(shape), dtype,
                                  minval=self.low, maxval=self.high)


class XavierNormal(Initializer):
    def __init__(self, fan_in=None, fan_out=None, gain=1.0):
        self.fan_in, self.fan_out, self.gain = fan_in, fan_out, gain

    def __call__(self, shape, dtype=jnp.float32):
        fi, fo = _fans(shape)
        fi = self.fan_in or fi
        fo = self.fan_out or fo
        std = self.gain * math.sqrt(2.0 / (fi + fo))
        return jax.random.normal(next_key(), tuple(shape), dtype) * std


class XavierUniform(Initializer):
    def __init__(self, fan_in=None, fan_out=None, gain=1.0):
        self.fan_in, self.fan_out, self.gain = fan_in, fan_out, gain

    def __call__(self, shape, dtype=jnp.float32):
        fi, fo = _fans(shape)
        fi = self.fan_in or fi
        fo = self.fan_out or fo
        limit = self.gain * math.sqrt(6.0 / (fi + fo))
        return jax.random.uniform(next_key(), tuple(shape), dtype,
                                  minval=-limit, maxval=limit)


class KaimingNormal(Initializer):
    """reference: nn/initializer/kaiming.py:21 (MSRA)."""

    def __init__(self, fan_in=None, negative_slope=0.0, nonlinearity="relu"):
        self.fan_in = fan_in
        self.negative_slope = negative_slope

    def __call__(self, shape, dtype=jnp.float32):
        fi, _ = _fans(shape)
        fi = self.fan_in or fi
        gain = math.sqrt(2.0 / (1 + self.negative_slope ** 2))
        std = gain / math.sqrt(fi)
        return jax.random.normal(next_key(), tuple(shape), dtype) * std


class KaimingUniform(Initializer):
    """reference: nn/initializer/kaiming.py:64."""

    def __init__(self, fan_in=None, negative_slope=0.0, nonlinearity="relu"):
        self.fan_in = fan_in
        self.negative_slope = negative_slope

    def __call__(self, shape, dtype=jnp.float32):
        fi, _ = _fans(shape)
        fi = self.fan_in or fi
        gain = math.sqrt(2.0 / (1 + self.negative_slope ** 2))
        limit = gain * math.sqrt(3.0 / fi)
        return jax.random.uniform(next_key(), tuple(shape), dtype,
                                  minval=-limit, maxval=limit)


class Assign(Initializer):
    def __init__(self, value):
        self.value = value

    def __call__(self, shape, dtype=jnp.float32):
        from ...core.tensor import Tensor
        v = self.value.data if isinstance(self.value, Tensor) else self.value
        arr = jnp.asarray(v, dtype)
        assert tuple(arr.shape) == tuple(shape), (
            f"Assign initializer shape {arr.shape} != param shape {shape}")
        return arr


class Orthogonal(Initializer):
    def __init__(self, gain=1.0):
        self.gain = gain

    def __call__(self, shape, dtype=jnp.float32):
        return jax.nn.initializers.orthogonal(self.gain)(
            next_key(), tuple(shape), dtype)


class Dirac(Initializer):
    def __init__(self, groups=1):
        self.groups = groups

    def __call__(self, shape, dtype=jnp.float32):
        return jax.nn.initializers.delta_orthogonal()(
            next_key(), tuple(shape), dtype)


# lowercase aliases used by ParamAttr(initializer=...)
constant = Constant
normal = Normal
uniform = Uniform
