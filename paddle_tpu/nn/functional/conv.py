"""Namespace alias (reference exposes paddle.nn.functional.conv as a
submodule); every function lives in the parent package."""
from paddle_tpu.nn.functional import *  # noqa: F401,F403
