"""nn.functional — functional mirror of the layer API
(reference: python/paddle/nn/functional/*, lowering to
operators/activation_op.*, conv_op.*, pool_op.*, softmax_op.*, etc.).

All functions are thin wrappers over pure jnp/lax implementations dispatched
through the shared tape/trace point; convs and matmuls map directly onto the
MXU via lax.conv_general_dilated / dot_general.
"""
from __future__ import annotations

import math
from typing import Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from ...core.dispatch import apply, as_array
from ...core.rng import next_key, stable_draw
from ...core.tensor import Tensor
from ...ops.manipulation import pad as _pad_op
from ...ops.manipulation import squeeze, unsqueeze  # noqa: F401

# ---------------------------------------------------------------------------
# activations (reference: operators/activation_op.cc kernel zoo)
# ---------------------------------------------------------------------------


def _act(jfn, name):
    def op(x, name=None):
        return apply(jfn, x, op_name=name, cacheable=True)
    op.__name__ = name
    return op


relu = _act(jax.nn.relu, "relu")
relu6 = _act(jax.nn.relu6, "relu6")
sigmoid = _act(jax.nn.sigmoid, "sigmoid")
tanh = _act(jnp.tanh, "tanh")
silu = _act(jax.nn.silu, "silu")
swish = silu
mish = _act(jax.nn.mish, "mish")
softsign = _act(jax.nn.soft_sign, "softsign")
tanhshrink = _act(lambda a: a - jnp.tanh(a), "tanhshrink")
hardswish = _act(jax.nn.hard_swish, "hardswish")


def gelu(x, approximate=False, name=None):
    return apply(lambda a: jax.nn.gelu(a, approximate=approximate), x,
                 op_name="gelu")


def leaky_relu(x, negative_slope=0.01, name=None):
    return apply(lambda a: jax.nn.leaky_relu(a, negative_slope), x,
                 op_name="leaky_relu")


def prelu(x, weight, data_format="NCHW", name=None):
    def _prelu(a, w):
        if w.size == 1:
            return jnp.where(a >= 0, a, w.reshape(()) * a)
        shape = [1] * a.ndim
        ch = 1 if data_format.startswith("NC") else a.ndim - 1
        shape[ch] = w.size
        return jnp.where(a >= 0, a, w.reshape(shape) * a)
    return apply(_prelu, x, weight, op_name="prelu")


def elu(x, alpha=1.0, name=None):
    return apply(lambda a: jax.nn.elu(a, alpha), x, op_name="elu")


def selu(x,
         scale=1.0507009873554804934193349852946,
         alpha=1.6732632423543772848170429916717, name=None):
    return apply(lambda a: scale * jnp.where(a > 0, a, alpha * jnp.expm1(a)),
                 x, op_name="selu")


def celu(x, alpha=1.0, name=None):
    return apply(lambda a: jax.nn.celu(a, alpha), x, op_name="celu")


def hardsigmoid(x, slope=0.1666667, offset=0.5, name=None):
    return apply(lambda a: jnp.clip(a * slope + offset, 0.0, 1.0), x,
                 op_name="hardsigmoid")


def hardtanh(x, min=-1.0, max=1.0, name=None):
    return apply(lambda a: jnp.clip(a, min, max), x, op_name="hardtanh")


def hardshrink(x, threshold=0.5, name=None):
    return apply(lambda a: jnp.where(jnp.abs(a) > threshold, a, 0.0), x,
                 op_name="hardshrink")


def softshrink(x, threshold=0.5, name=None):
    return apply(lambda a: jnp.where(a > threshold, a - threshold,
                                     jnp.where(a < -threshold, a + threshold,
                                               0.0)),
                 x, op_name="softshrink")


def softplus(x, beta=1.0, threshold=20.0, name=None):
    return apply(lambda a: jnp.where(a * beta > threshold, a,
                                     jnp.log1p(jnp.exp(beta * a)) / beta),
                 x, op_name="softplus")


def maxout(x, groups, axis=1, name=None):
    def _maxout(a):
        ax = axis % a.ndim
        c = a.shape[ax]
        new_shape = a.shape[:ax] + (c // groups, groups) + a.shape[ax + 1:]
        return jnp.max(a.reshape(new_shape), axis=ax + 1)
    return apply(_maxout, x, op_name="maxout")


def softmax(x, axis=-1, dtype=None, name=None):
    return apply(lambda a: jax.nn.softmax(a, axis=axis), x, op_name="softmax")


def log_softmax(x, axis=-1, dtype=None, name=None):
    return apply(lambda a: jax.nn.log_softmax(a, axis=axis), x,
                 op_name="log_softmax")


def gumbel_softmax(x, temperature=1.0, hard=False, axis=-1, name=None):
    draw = stable_draw()  # in-trace + replay-stable (see core.rng)
    def _gs(a):
        g = jax.random.gumbel(draw.key(), a.shape, a.dtype)
        y = jax.nn.softmax((a + g) / temperature, axis=axis)
        if hard:
            idx = jnp.argmax(y, axis=axis)
            oh = jax.nn.one_hot(idx, y.shape[axis], axis=axis, dtype=y.dtype)
            y = jax.lax.stop_gradient(oh - y) + y  # straight-through
        return y
    return apply(_gs, x, op_name="gumbel_softmax")


def normalize(x, p=2, axis=1, epsilon=1e-12, name=None):
    def _normalize(a):
        nrm = jnp.sum(jnp.abs(a) ** p, axis=axis, keepdims=True) ** (1.0 / p)
        return a / jnp.maximum(nrm, epsilon)
    return apply(_normalize, x, op_name="normalize")


# ---------------------------------------------------------------------------
# linear / embedding
# ---------------------------------------------------------------------------

def _linear_fn(a, w):
    return jnp.matmul(a, w)


def _linear_bias_fn(a, w, b):
    return jnp.matmul(a, w) + b


def linear(x, weight, bias=None, name=None):
    """y = x @ W + b; W is [in, out] (reference: operators/matmul_v2 + fc)."""
    if bias is None:
        return apply(_linear_fn, x, weight, op_name="linear",
                     cacheable=True)
    return apply(_linear_bias_fn, x, weight, bias, op_name="linear",
                 cacheable=True)


def bilinear(x1, x2, weight, bias=None, name=None):
    def _bilinear(a, b, w):
        out = jnp.einsum("bi,oij,bj->bo", a, w, b)
        return out
    out = apply(_bilinear, x1, x2, weight, op_name="bilinear")
    if bias is not None:
        out = out + bias
    return out


def embedding(x, weight, padding_idx=None, sparse=False, name=None):
    def _embedding(ids, w):
        out = jnp.take(w, ids, axis=0)
        if padding_idx is not None:
            mask = (ids != padding_idx)[..., None].astype(w.dtype)
            out = out * mask
        return out

    from ...core import autograd
    ids_arr = as_array(x)
    w_arr = as_array(weight)
    if (sparse and autograd.grad_enabled()
            and isinstance(weight, Tensor) and not weight.stop_gradient
            and weight._node is None  # leaf only: an upstream dense vjp
            #                           cannot consume SelectedRows
            and not isinstance(ids_arr, jax.core.Tracer)
            and not isinstance(w_arr, jax.core.Tracer)):
        # SelectedRows gradient (reference: lookup_table_op.cc
        # is_sparse branch): the weight cotangent is (rows, values), not
        # a [vocab, dim]-dense scatter — optimizers apply it row-wise
        from ...core.selected_rows import SelectedRows

        with autograd.no_grad():
            out_arr = _embedding(ids_arr, w_arr)
        out = Tensor(out_arr, stop_gradient=False, _produced=True)

        def vjp_fn(ct):
            rows = ids_arr.reshape(-1)
            vals = jnp.asarray(ct).reshape(-1, w_arr.shape[-1])
            if padding_idx is not None:
                keep = (rows != padding_idx)[:, None].astype(vals.dtype)
                vals = vals * keep
            return (SelectedRows(rows, vals, w_arr.shape[0]),)

        node = autograd.Node(
            inputs=[weight], vjp_fn=vjp_fn, out_ids=[out._bw_id],
            out_avals=[(out.shape_tuple, np.dtype(out_arr.dtype))],
            out_is_tuple=False)
        out._node = node
        return out
    return apply(_embedding, x, weight, op_name="embedding")


def one_hot(x, num_classes, name=None):
    return apply(lambda a: jax.nn.one_hot(a, num_classes), x,
                 op_name="one_hot", nondiff=True)


# ---------------------------------------------------------------------------
# convolution (reference: operators/conv_op.*, conv_transpose_op.*)
# ---------------------------------------------------------------------------

def _norm_tuple(v, n):
    if isinstance(v, int):
        return (v,) * n
    return tuple(v)


def _conv_padding(padding, n):
    if isinstance(padding, str):
        return padding.upper()  # SAME / VALID
    if isinstance(padding, int):
        return [(padding, padding)] * n
    padding = list(padding)
    if len(padding) == n:
        return [(p, p) for p in padding]
    if len(padding) == 2 * n:
        return [(padding[2 * i], padding[2 * i + 1]) for i in range(n)]
    raise ValueError(f"bad conv padding: {padding}")


def conv2d(x, weight, bias=None, stride=1, padding=0, dilation=1, groups=1,
           data_format="NCHW", name=None):
    """reference: operators/conv_op.cc; lowers to lax.conv_general_dilated
    which XLA tiles onto the MXU."""
    return _convnd(x, weight, bias, stride, padding, dilation, groups,
                   data_format, 2)


def conv1d(x, weight, bias=None, stride=1, padding=0, dilation=1, groups=1,
           data_format="NCL", name=None):
    return _convnd(x, weight, bias, stride, padding, dilation, groups,
                   data_format, 1)


def conv3d(x, weight, bias=None, stride=1, padding=0, dilation=1, groups=1,
           data_format="NCDHW", name=None):
    return _convnd(x, weight, bias, stride, padding, dilation, groups,
                   data_format, 3)


def _conv_fn(a, w, *maybe_bias, stride, pad_spec, dilation, groups, specs,
             channels_last=False):
    dn = jax.lax.conv_dimension_numbers(a.shape, w.shape, specs)
    out = jax.lax.conv_general_dilated(
        a, w, window_strides=stride,
        padding=(pad_spec if isinstance(pad_spec, str)
                 else [tuple(p) for p in pad_spec]),
        rhs_dilation=dilation, dimension_numbers=dn,
        feature_group_count=groups)
    if maybe_bias:
        # bias fused into the cached op: an eager reshape+add pair costs
        # more host dispatch than the conv itself (r4 profile: 330us vs
        # 69us per call)
        shape = [1] * out.ndim
        shape[-1 if channels_last else 1] = -1
        out = out + maybe_bias[0].reshape(shape)
    return out


def _convnd(x, weight, bias, stride, padding, dilation, groups, data_format,
            n):
    stride = _norm_tuple(stride, n)
    dilation = _norm_tuple(dilation, n)
    pad_spec = _conv_padding(padding, n)
    channels_last = not data_format.startswith("NC")
    sp = "".join("DHW"[3 - n:][i] for i in range(n))
    if channels_last:
        lhs_spec = "N" + sp + "C"
    else:
        lhs_spec = "NC" + sp
    # paddle kernel layout: [out_c, in_c/groups, *spatial]
    rhs_spec = "OI" + sp
    out_spec = lhs_spec
    pad_hashable = (pad_spec if isinstance(pad_spec, str)
                    else tuple(tuple(p) for p in pad_spec))
    args = (x, weight) + ((bias,) if bias is not None else ())
    return apply(_conv_fn, *args, op_name=f"conv{n}d", cacheable=True,
                 stride=stride, pad_spec=pad_hashable, dilation=dilation,
                 groups=groups, specs=(lhs_spec, rhs_spec, out_spec),
                 channels_last=channels_last)


def conv2d_transpose(x, weight, bias=None, stride=1, padding=0,
                     output_padding=0, dilation=1, groups=1,
                     output_size=None, data_format="NCHW", name=None):
    """reference: operators/conv_transpose_op.cc — implemented as the
    gradient of conv2d (lax.conv_transpose with paddle's IOHW kernel)."""
    n = 2
    stride = _norm_tuple(stride, n)
    dilation = _norm_tuple(dilation, n)
    outpad = _norm_tuple(output_padding, n)
    channels_last = not data_format.startswith("NC")
    pad_int = padding if isinstance(padding, int) else None

    def _convt(a, w):
        # paddle kernel layout for transpose conv: [in_c, out_c/groups, H, W]
        if channels_last:
            a_ = jnp.moveaxis(a, -1, 1)
        else:
            a_ = a
        k = _norm_tuple(w.shape[2], 1) + (w.shape[3],)
        pads = _conv_padding(padding, n)
        if isinstance(pads, str):
            raise ValueError("string padding unsupported for conv_transpose")
        # output_size disambiguates the stride>1 output length
        # (conv_transpose_op.cc InferShape): it overrides output_padding
        outpad_eff = list(outpad)
        if output_size is not None:
            os_ = _norm_tuple(tuple(output_size), n)
            for i in range(n):
                kk = (w.shape[2 + i] - 1) * dilation[i] + 1
                lo, hi = pads[i]
                base = (a_.shape[2 + i] - 1) * stride[i] - lo - hi + kk
                op = os_[i] - base
                if not 0 <= op < max(stride[i], 1) + 1:
                    raise ValueError(
                        f"conv_transpose: output_size[{i}]={os_[i]} not "
                        f"reachable (base {base}, stride {stride[i]})")
                outpad_eff[i] = op
        # gradient-of-conv formulation: dilate input by stride, full-pad
        lhs_dilation = stride
        pad_list = []
        for i in range(n):
            kk = (w.shape[2 + i] - 1) * dilation[i] + 1
            lo, hi = pads[i]
            pad_list.append((kk - 1 - lo, kk - 1 - hi + outpad_eff[i]))
        w_flip = jnp.flip(w, axis=(2, 3))
        w_t = jnp.swapaxes(w_flip, 0, 1)  # -> [out_c, in_c, H, W]
        if groups > 1:
            # grouped transpose: w is [in_c, out_c//g, kh, kw]
            ic = a_.shape[1]
            w_g = w_flip.reshape(groups, ic // groups, w.shape[1],
                                 *w.shape[2:])
            w_t = jnp.concatenate(
                [jnp.swapaxes(w_g[g], 0, 1) for g in range(groups)], axis=0)
        dn = jax.lax.conv_dimension_numbers(
            a_.shape, w_t.shape, ("NCHW", "OIHW", "NCHW"))
        out = jax.lax.conv_general_dilated(
            a_, w_t, window_strides=(1, 1), padding=pad_list,
            lhs_dilation=lhs_dilation, rhs_dilation=dilation,
            dimension_numbers=dn, feature_group_count=groups)
        if channels_last:
            out = jnp.moveaxis(out, 1, -1)
        return out

    out = apply(_convt, x, weight, op_name="conv2d_transpose")
    if bias is not None:
        shape = [1, 1, 1, 1]
        shape[-1 if channels_last else 1] = -1
        out = out + bias.reshape(shape)
    return out


# ---------------------------------------------------------------------------
# pooling (reference: operators/pool_op.*)
# ---------------------------------------------------------------------------

def _pool(x, kernel, stride, padding, n, reducer, init, data_format,
          ceil_mode=False, count_include_pad=True, average=False):
    kernel = _norm_tuple(kernel, n)
    stride = _norm_tuple(stride if stride is not None else kernel, n)
    pads = _conv_padding(padding, n)
    channels_last = not data_format.startswith("NC")
    if channels_last:
        window = (1,) + kernel + (1,)
        strides = (1,) + stride + (1,)
        pad_full = ([(0, 0)] + list(pads) + [(0, 0)]
                    if not isinstance(pads, str) else pads)
    else:
        window = (1, 1) + kernel
        strides = (1, 1) + stride
        pad_full = ([(0, 0), (0, 0)] + list(pads)
                    if not isinstance(pads, str) else pads)

    no_pad = isinstance(pads, list) and all(p == (0, 0) for p in pads)
    return apply(
        _pool_fn, x, op_name="pool", cacheable=True, init=init,
        max_pool=(reducer is jax.lax.max), window=window, strides=strides,
        pad_full=(pad_full if isinstance(pad_full, str)
                  else tuple(tuple(p) for p in pad_full)),
        average=average, divisor=(float(np.prod(kernel))
                                  if (count_include_pad or no_pad)
                                  else None))


def _pool_fn(a, *, init, max_pool, window, strides, pad_full, average,
             divisor):
    reducer = jax.lax.max if max_pool else jax.lax.add
    pad = (pad_full if isinstance(pad_full, str)
           else [tuple(p) for p in pad_full])
    out = jax.lax.reduce_window(a, init, reducer, window, strides, pad)
    if average:
        if divisor is not None:
            out = out / divisor
        else:
            ones = jnp.ones_like(a)
            cnt = jax.lax.reduce_window(ones, 0.0, jax.lax.add, window,
                                        strides, pad)
            out = out / cnt
    return out


def max_pool2d(x, kernel_size, stride=None, padding=0, ceil_mode=False,
               return_mask=False, data_format="NCHW", name=None):
    return _pool(x, kernel_size, stride, padding, 2, jax.lax.max,
                 -jnp.inf, data_format, ceil_mode)


def avg_pool2d(x, kernel_size, stride=None, padding=0, ceil_mode=False,
               exclusive=True, divisor_override=None, data_format="NCHW",
               name=None):
    return _pool(x, kernel_size, stride, padding, 2, jax.lax.add, 0.0,
                 data_format, ceil_mode, count_include_pad=not exclusive,
                 average=True)


def max_pool1d(x, kernel_size, stride=None, padding=0, return_mask=False,
               ceil_mode=False, name=None):
    return _pool(x, kernel_size, stride, padding, 1, jax.lax.max,
                 -jnp.inf, "NCL", ceil_mode)


def avg_pool1d(x, kernel_size, stride=None, padding=0, exclusive=True,
               ceil_mode=False, name=None):
    return _pool(x, kernel_size, stride, padding, 1, jax.lax.add, 0.0,
                 "NCL", ceil_mode, count_include_pad=not exclusive,
                 average=True)


def max_pool3d(x, kernel_size, stride=None, padding=0, ceil_mode=False,
               return_mask=False, data_format="NCDHW", name=None):
    return _pool(x, kernel_size, stride, padding, 3, jax.lax.max,
                 -jnp.inf, data_format, ceil_mode)


def avg_pool3d(x, kernel_size, stride=None, padding=0, ceil_mode=False,
               exclusive=True, divisor_override=None, data_format="NCDHW",
               name=None):
    return _pool(x, kernel_size, stride, padding, 3, jax.lax.add, 0.0,
                 data_format, ceil_mode, count_include_pad=not exclusive,
                 average=True)


def adaptive_avg_pool2d(x, output_size, data_format="NCHW", name=None):
    os = _norm_tuple(output_size, 2)

    def _aap(a):
        if data_format.startswith("NC"):
            N, C, H, W = a.shape
            a_ = a
        else:
            N, H, W, C = a.shape
            a_ = jnp.moveaxis(a, -1, 1)
        # XLA-friendly: split into os windows when divisible, else mean over
        # index buckets via reshape fallback
        if H % os[0] == 0 and W % os[1] == 0:
            out = a_.reshape(N, C, os[0], H // os[0], os[1], W // os[1])
            out = out.mean(axis=(3, 5))
        else:
            # bucketed mean (static loop over output cells)
            rows = [a_[:, :, (i * H) // os[0]:-(-(i + 1) * H // os[0]), :]
                    for i in range(os[0])]
            cells = []
            for r in rows:
                cells.append(jnp.stack(
                    [r[:, :, :, (j * W) // os[1]:-(-(j + 1) * W // os[1])]
                     .mean(axis=(2, 3)) for j in range(os[1])], axis=-1))
            out = jnp.stack(cells, axis=2)
        if not data_format.startswith("NC"):
            out = jnp.moveaxis(out, 1, -1)
        return out
    return apply(_aap, x, op_name="adaptive_avg_pool2d")


def adaptive_max_pool2d(x, output_size, return_mask=False, name=None):
    os = _norm_tuple(output_size, 2)

    def _amp(a):
        N, C, H, W = a.shape
        if H % os[0] == 0 and W % os[1] == 0:
            out = a.reshape(N, C, os[0], H // os[0], os[1], W // os[1])
            return out.max(axis=(3, 5))
        rows = [a[:, :, (i * H) // os[0]:-(-(i + 1) * H // os[0]), :]
                for i in range(os[0])]
        cells = []
        for r in rows:
            cells.append(jnp.stack(
                [r[:, :, :, (j * W) // os[1]:-(-(j + 1) * W // os[1])]
                 .max(axis=(2, 3)) for j in range(os[1])], axis=-1))
        return jnp.stack(cells, axis=2)
    return apply(_amp, x, op_name="adaptive_max_pool2d")


def adaptive_avg_pool1d(x, output_size, name=None):
    os = int(output_size)

    def _aap(a):
        N, C, L = a.shape
        if L % os == 0:
            return a.reshape(N, C, os, L // os).mean(axis=3)
        return jnp.stack(
            [a[:, :, (i * L) // os:-(-(i + 1) * L // os)].mean(axis=2)
             for i in range(os)], axis=-1)
    return apply(_aap, x, op_name="adaptive_avg_pool1d")


# ---------------------------------------------------------------------------
# normalisation (reference: operators/batch_norm_op.*, layer_norm_op.*)
# ---------------------------------------------------------------------------

def batch_norm(x, running_mean, running_var, weight=None, bias=None,
               training=False, momentum=0.9, epsilon=1e-5,
               data_format="NCHW", use_global_stats=None, name=None):
    """Functional BN. In training mode returns (out, new_mean, new_var) data
    updates through the Layer wrapper; here it computes with batch stats and
    the Layer handles running-stat updates."""
    ch_axis = 1 if data_format.startswith("NC") and as_array(x).ndim > 1 else -1
    axes = tuple(i for i in range(as_array(x).ndim) if i != ch_axis % as_array(x).ndim)

    use_batch = training and not use_global_stats

    if use_batch:
        def _bn(a, w, b):
            m = jnp.mean(a, axis=axes, keepdims=True)
            v = jnp.var(a, axis=axes, keepdims=True)
            out = (a - m) * jax.lax.rsqrt(v + epsilon)
            if w is not None:
                out = out * _chan(w, a, ch_axis)
            if b is not None:
                out = out + _chan(b, a, ch_axis)
            return out
    else:
        def _bn(a, w, b, rm=as_array(running_mean), rv=as_array(running_var)):
            out = ((a - _chan(rm, a, ch_axis))
                   * jax.lax.rsqrt(_chan(rv, a, ch_axis) + epsilon))
            if w is not None:
                out = out * _chan(w, a, ch_axis)
            if b is not None:
                out = out + _chan(b, a, ch_axis)
            return out
    return apply(_bn, x, weight, bias, op_name="batch_norm")


def _chan(v, a, ch_axis):
    shape = [1] * a.ndim
    shape[ch_axis] = -1
    return v.reshape(shape)


def layer_norm(x, normalized_shape, weight=None, bias=None, epsilon=1e-5,
               name=None):
    if isinstance(normalized_shape, int):
        normalized_shape = (normalized_shape,)
    n = len(tuple(normalized_shape))

    def _ln(a, *wb):
        w = wb[0] if len(wb) > 0 else None
        b = wb[1] if len(wb) > 1 else None
        axes = tuple(range(a.ndim - n, a.ndim))
        m = jnp.mean(a, axis=axes, keepdims=True)
        v = jnp.var(a, axis=axes, keepdims=True)
        out = (a - m) * jax.lax.rsqrt(v + epsilon)
        if w is not None:
            out = out * w
        if b is not None:
            out = out + b
        return out

    args = [x]
    if weight is not None:
        args.append(weight)
        if bias is not None:
            args.append(bias)
    return apply(_ln, *args, op_name="layer_norm")


def group_norm(x, num_groups, epsilon=1e-5, weight=None, bias=None,
               data_format="NCHW", name=None):
    def _gn(a, *wb):
        w = wb[0] if len(wb) > 0 else None
        b = wb[1] if len(wb) > 1 else None
        if not data_format.startswith("NC"):
            a = jnp.moveaxis(a, -1, 1)
        N, C = a.shape[:2]
        spatial = a.shape[2:]
        g = a.reshape(N, num_groups, C // num_groups, *spatial)
        axes = tuple(range(2, g.ndim))
        m = jnp.mean(g, axis=axes, keepdims=True)
        v = jnp.var(g, axis=axes, keepdims=True)
        out = ((g - m) * jax.lax.rsqrt(v + epsilon)).reshape(a.shape)
        shape = [1, C] + [1] * len(spatial)
        if w is not None:
            out = out * w.reshape(shape)
        if b is not None:
            out = out + b.reshape(shape)
        if not data_format.startswith("NC"):
            out = jnp.moveaxis(out, 1, -1)
        return out

    args = [x]
    if weight is not None:
        args.append(weight)
        if bias is not None:
            args.append(bias)
    return apply(_gn, *args, op_name="group_norm")


def instance_norm(x, running_mean=None, running_var=None, weight=None,
                  bias=None, use_input_stats=True, momentum=0.9, eps=1e-5,
                  data_format="NCHW", name=None):
    def _in(a, *wb):
        w = wb[0] if len(wb) > 0 else None
        b = wb[1] if len(wb) > 1 else None
        axes = tuple(range(2, a.ndim))
        m = jnp.mean(a, axis=axes, keepdims=True)
        v = jnp.var(a, axis=axes, keepdims=True)
        out = (a - m) * jax.lax.rsqrt(v + eps)
        if w is not None:
            shape = [1, -1] + [1] * (a.ndim - 2)
            out = out * w.reshape(shape)
        if b is not None:
            shape = [1, -1] + [1] * (a.ndim - 2)
            out = out + b.reshape(shape)
        return out

    args = [x]
    if weight is not None:
        args.append(weight)
        if bias is not None:
            args.append(bias)
    return apply(_in, *args, op_name="instance_norm")


def local_response_norm(x, size, alpha=1e-4, beta=0.75, k=1.0,
                        data_format="NCHW", name=None):
    def _lrn(a):
        sq = jnp.square(a)
        half = size // 2
        c = a.shape[1]
        pads = [(0, 0), (half, size - 1 - half)] + [(0, 0)] * (a.ndim - 2)
        padded = jnp.pad(sq, pads)
        win = sum(padded[:, i:i + c] for i in range(size))
        return a / (k + alpha * win) ** beta
    return apply(_lrn, x, op_name="local_response_norm")


# ---------------------------------------------------------------------------
# dropout (reference: operators/dropout_op.*)
# ---------------------------------------------------------------------------

def _u16_dropout_mask(key, shape, p, dtype, upscale=True):
    """Dropout keep-mask from u16 random bits: half the random bytes and no
    int->float convert vs the f32-uniform path (which cost ~25 ms/step on
    the BERT bench).  p is quantized to 1/65536; the keep scale uses the
    quantized value so E[mask * x] == x exactly.  Returns None for p<=0
    (keep everything) and 0.0 for p>=1 (drop everything)."""
    t = int(round(float(p) * 65536.0))
    if t <= 0:
        return None
    if t >= 65536:
        return 0.0
    bits = jax.random.bits(key, tuple(shape), jnp.uint16)
    keep = (bits >= jnp.uint16(t)).astype(dtype)
    if upscale:
        return keep * jnp.asarray(65536.0 / (65536 - t), dtype)
    return keep


def dropout(x, p=0.5, axis=None, training=True, mode="upscale_in_train",
            name=None):
    if not training or p == 0.0:
        if mode == "downscale_in_infer" and not training:
            # reference semantics: infer-time out = x * (1 - p)
            return apply(lambda a: a * jnp.asarray(1.0 - p, a.dtype), x,
                         op_name="dropout")
        return x if isinstance(x, Tensor) else Tensor(x)

    draw = stable_draw()

    def _dropout(a):
        # key resolved INSIDE the traced fn: under a seed_scope
        # (TrainStep, static Executor runs) it folds the per-run key so
        # static programs reseed per exe.run; the StableDraw identity
        # keeps double-backward tape replays on the SAME mask
        key = draw.key()
        shape = list(a.shape)
        if axis is not None:
            axes = axis if isinstance(axis, (list, tuple)) else [axis]
            shape = [s if i in axes else 1 for i, s in enumerate(shape)]
        mask = _u16_dropout_mask(key, shape, p, a.dtype,
                                 upscale=(mode == "upscale_in_train"))
        if mask is None:
            return a
        return a * mask
    return apply(_dropout, x, op_name="dropout")


def dropout2d(x, p=0.5, training=True, data_format="NCHW", name=None):
    ax = [0, 1] if data_format.startswith("NC") else [0, 3]
    return dropout(x, p, axis=ax, training=training)


def dropout3d(x, p=0.5, training=True, data_format="NCDHW", name=None):
    ax = [0, 1] if data_format.startswith("NC") else [0, 4]
    return dropout(x, p, axis=ax, training=training)


def alpha_dropout(x, p=0.5, training=True, name=None):
    if not training or p == 0.0:
        return x if isinstance(x, Tensor) else Tensor(x)
    draw = stable_draw()  # in-trace + replay-stable (see core.rng)

    def _ad(a):
        alpha = 1.6732632423543772848170429916717
        scale = 1.0507009873554804934193349852946
        neg = -alpha * scale
        keep = jax.random.bernoulli(draw.key(), 1.0 - p, a.shape)
        q = 1.0 - p
        A = (q + neg ** 2 * q * p) ** -0.5
        B = -A * p * neg
        return A * jnp.where(keep, a, neg) + B
    return apply(_ad, x, op_name="alpha_dropout")


# ---------------------------------------------------------------------------
# losses (reference: operators/cross_entropy_op.*, mse, bce, kldiv,
# smooth_l1, margin_rank; python/paddle/nn/functional/loss.py)
# ---------------------------------------------------------------------------

def _reduce(out, reduction):
    if reduction == "mean":
        return jnp.mean(out)
    if reduction == "sum":
        return jnp.sum(out)
    return out


def _ce_fn(logits, lab, *w, use_softmax, axis, soft_label,
       label_smoothing, ignore_index, reduction):
    wgt = w[0] if w else None
    if use_softmax:
        logp = jax.nn.log_softmax(logits, axis=axis)
    else:
        logp = jnp.log(jnp.maximum(logits, 1e-30))
    nclass = logits.shape[axis]
    if soft_label:
        tgt = lab
    else:
        lab_ = lab
        if lab_.ndim == logp.ndim and lab_.shape[axis] == 1:
            lab_ = jnp.squeeze(lab_, axis)
        tgt = jax.nn.one_hot(lab_, nclass, axis=axis, dtype=logp.dtype)
    if label_smoothing > 0.0:
        tgt = tgt * (1.0 - label_smoothing) + label_smoothing / nclass
    loss = -jnp.sum(tgt * logp, axis=axis)
    w_row = None
    if wgt is not None and not soft_label:
        lab_ = lab
        if lab_.ndim == logp.ndim and lab_.shape[axis] == 1:
            lab_ = jnp.squeeze(lab_, axis)
        # ignore_index (e.g. -100) is out of range for the weight
        # table — jnp.take would fill NaN; ignored rows are masked to
        # zero below, so any in-range index works here
        safe = jnp.where(lab_ == ignore_index, 0, lab_)
        w_row = jnp.take(wgt, safe)
        loss = loss * w_row
    if not soft_label:
        lab_ = lab
        if lab_.ndim == logp.ndim and lab_.shape[axis] == 1:
            lab_ = jnp.squeeze(lab_, axis)
        mask = (lab_ != ignore_index).astype(loss.dtype)
        loss = loss * mask
        if reduction == "mean":
            if w_row is not None:
                # weighted mean divides by the sum of selected class
                # weights (reference: nn/functional/loss.py weighted CE)
                denom = jnp.sum(mask * w_row)
            else:
                denom = jnp.sum(mask)
            return jnp.sum(loss) / jnp.maximum(denom, 1e-12)
    return _reduce(loss, reduction)


def cross_entropy(input, label, weight=None, ignore_index=-100,
                  reduction="mean", soft_label=False, axis=-1,
                  use_softmax=True, label_smoothing=0.0, name=None):
    args = [input, label]
    if weight is not None:
        args.append(weight)
    return apply(_ce_fn, *args, op_name="cross_entropy", cacheable=True,
                 use_softmax=use_softmax, axis=axis, soft_label=soft_label,
                 label_smoothing=float(label_smoothing),
                 ignore_index=ignore_index, reduction=reduction)


def _linear_ce_fn(h, w, b, lab, *, chunk, ignore_index):
    """Chunked fused head+CE: logits for one token chunk live only inside
    the rematerialized chunk body, so the [T, vocab] logits (and their
    cotangent) never hit HBM in full.  The matmul is recomputed in the
    chunk's backward — ~6% extra MXU FLOPs for ~4 GB less peak memory on
    the BERT-base bench shape."""
    T = h.shape[0]
    n = max(1, -(-T // chunk))          # ceil: pad the tail chunk
    per = -(-T // n)
    if n * per != T:
        pad = n * per - T
        h = jnp.concatenate(
            [h, jnp.zeros((pad, h.shape[-1]), h.dtype)], axis=0)
        lab = jnp.concatenate(
            [lab, jnp.full((pad,), ignore_index, lab.dtype)], axis=0)
    hs = h.reshape(n, per, h.shape[-1])
    ls = lab.reshape(n, per)

    @jax.checkpoint
    def chunk_nll(hc, lc):
        logits = (jnp.matmul(hc, w) + b).astype(jnp.float32)
        lse = jax.scipy.special.logsumexp(logits, axis=-1)
        safe = jnp.where(lc == ignore_index, 0, lc)
        tgt = jnp.take_along_axis(logits, safe[:, None], axis=-1)[:, 0]
        nll = lse - tgt
        keep = (lc != ignore_index)
        return jnp.sum(nll * keep), jnp.sum(keep)

    def body(carry, xs):
        s, c = carry
        hc, lc = xs
        ds, dc = chunk_nll(hc, lc)
        return (s + ds, c + dc), None

    (total, count), _ = jax.lax.scan(
        body, (jnp.float32(0.0), jnp.int32(0)), (hs, ls))
    return total / jnp.maximum(count, 1).astype(jnp.float32)


def linear_cross_entropy(hidden, weight, bias, label, chunk: int = 1024,
                         ignore_index: int = -100, name=None):
    """Fused ``cross_entropy(hidden @ weight + bias, label)`` with chunked
    logits (mean reduction).  The TPU-native extension of the reference's
    fused softmax_with_cross_entropy op (operators/softmax_with_cross_
    entropy_op.cu) to include the vocab projection: the full-vocab logits
    tensor is never materialized.  ``hidden``: [T, H]; ``weight``:
    [H, vocab]; ``label``: [T] int."""
    return apply(_linear_ce_fn, hidden, weight, bias, label,
                 op_name="linear_cross_entropy", cacheable=True,
                 chunk=int(chunk), ignore_index=int(ignore_index))


def softmax_with_cross_entropy(logits, label, soft_label=False,
                               ignore_index=-100, axis=-1,
                               return_softmax=False):
    loss = cross_entropy(logits, label, reduction="none",
                         soft_label=soft_label, ignore_index=ignore_index,
                         axis=axis)
    loss = loss.unsqueeze(axis)
    if return_softmax:
        return loss, softmax(logits, axis=axis)
    return loss


def nll_loss(input, label, weight=None, ignore_index=-100, reduction="mean",
             name=None):
    def _nll(logp, lab, *w):
        wgt = w[0] if w else None
        loss = -jnp.take_along_axis(logp, lab[..., None], axis=-1)[..., 0]
        if wgt is not None:
            loss = loss * jnp.take(wgt, lab)
        return _reduce(loss, reduction)
    args = [input, label]
    if weight is not None:
        args.append(weight)
    return apply(_nll, *args, op_name="nll_loss")


def _mse_fn(a, b, *, reduction):
    return _reduce(jnp.square(a - b), reduction)


def _l1_fn(a, b, *, reduction):
    return _reduce(jnp.abs(a - b), reduction)


# reduction rides the recorded kw (not a closure) so static analysis —
# shardcheck's sum-classifier in particular — can read it off the node
def mse_loss(input, label, reduction="mean", name=None):
    return apply(_mse_fn, input, label, op_name="mse_loss",
                 reduction=reduction)


def l1_loss(input, label, reduction="mean", name=None):
    return apply(_l1_fn, input, label, op_name="l1_loss",
                 reduction=reduction)


def smooth_l1_loss(input, label, reduction="mean", delta=1.0, name=None):
    def _sl1(a, b):
        d = a - b
        abs_d = jnp.abs(d)
        loss = jnp.where(abs_d < delta, 0.5 * d * d / delta,
                         abs_d - 0.5 * delta)
        return _reduce(loss, reduction)
    return apply(_sl1, input, label, op_name="smooth_l1_loss")


def binary_cross_entropy(input, label, weight=None, reduction="mean",
                         name=None):
    def _bce(p, t, *w):
        eps = 1e-12
        loss = -(t * jnp.log(jnp.maximum(p, eps))
                 + (1 - t) * jnp.log(jnp.maximum(1 - p, eps)))
        if w:
            loss = loss * w[0]
        return _reduce(loss, reduction)
    args = [input, label] + ([weight] if weight is not None else [])
    return apply(_bce, *args, op_name="binary_cross_entropy")


def binary_cross_entropy_with_logits(logit, label, weight=None,
                                     reduction="mean", pos_weight=None,
                                     name=None):
    def _bcewl(z, t, *extra):
        i = 0
        w = extra[i] if weight is not None else None
        i += 1 if weight is not None else 0
        pw = extra[i] if pos_weight is not None else None
        # stable: max(z,0) - z*t + log(1+exp(-|z|))
        loss = jnp.maximum(z, 0) - z * t + jnp.log1p(jnp.exp(-jnp.abs(z)))
        if pw is not None:
            loss = loss * (t * (pw - 1) + 1)
        if w is not None:
            loss = loss * w
        return _reduce(loss, reduction)
    args = [logit, label]
    if weight is not None:
        args.append(weight)
    if pos_weight is not None:
        args.append(pos_weight)
    return apply(_bcewl, *args, op_name="bce_with_logits")


def kl_div(input, label, reduction="mean", name=None):
    def _kl(logp, t):
        loss = t * (jnp.log(jnp.maximum(t, 1e-12)) - logp)
        if reduction == "batchmean":
            return jnp.sum(loss) / logp.shape[0]
        return _reduce(loss, reduction)
    return apply(_kl, input, label, op_name="kl_div")


def margin_ranking_loss(input, other, label, margin=0.0, reduction="mean",
                        name=None):
    return apply(lambda a, b, t: _reduce(
        jnp.maximum(0.0, -t * (a - b) + margin), reduction),
        input, other, label, op_name="margin_ranking_loss")


def hinge_embedding_loss(input, label, margin=1.0, reduction="mean",
                         name=None):
    return apply(lambda a, t: _reduce(
        jnp.where(t == 1, a, jnp.maximum(0.0, margin - a)), reduction),
        input, label, op_name="hinge_embedding_loss")


def cosine_similarity(x1, x2, axis=1, eps=1e-8):
    def _cs(a, b):
        dot = jnp.sum(a * b, axis=axis)
        na = jnp.sqrt(jnp.sum(a * a, axis=axis))
        nb = jnp.sqrt(jnp.sum(b * b, axis=axis))
        return dot / jnp.maximum(na * nb, eps)
    return apply(_cs, x1, x2, op_name="cosine_similarity")


def cosine_embedding_loss(input1, input2, label, margin=0, reduction="mean",
                          name=None):
    def _cel(a, b, t):
        cs = jnp.sum(a * b, axis=-1) / jnp.maximum(
            jnp.linalg.norm(a, axis=-1) * jnp.linalg.norm(b, axis=-1), 1e-12)
        loss = jnp.where(t == 1, 1 - cs, jnp.maximum(0.0, cs - margin))
        return _reduce(loss, reduction)
    return apply(_cel, input1, input2, label, op_name="cosine_embedding_loss")


def sigmoid_focal_loss(logit, label, normalizer=None, alpha=0.25, gamma=2.0,
                       reduction="sum", name=None):
    def _sfl(z, t, *n):
        p = jax.nn.sigmoid(z)
        ce = jnp.maximum(z, 0) - z * t + jnp.log1p(jnp.exp(-jnp.abs(z)))
        p_t = p * t + (1 - p) * (1 - t)
        a_t = alpha * t + (1 - alpha) * (1 - t)
        loss = a_t * ((1 - p_t) ** gamma) * ce
        if n:
            loss = loss / n[0]
        return _reduce(loss, reduction)
    args = [logit, label] + ([normalizer] if normalizer is not None else [])
    return apply(_sfl, *args, op_name="sigmoid_focal_loss")


def ctc_loss(log_probs, labels, input_lengths, label_lengths, blank=0,
             reduction="mean", norm_by_times=False):
    """CTC via optax's implementation (reference: warpctc dynload)."""
    import optax
    def _ctc(lp, lab, il, ll):
        # optax expects [B, T, C] logits and paddings
        lp_btc = jnp.transpose(lp, (1, 0, 2)) if lp.ndim == 3 else lp
        B, T, C = lp_btc.shape
        t_idx = jnp.arange(T)[None, :]
        logitpad = (t_idx >= il[:, None]).astype(lp_btc.dtype)
        L = lab.shape[1]
        l_idx = jnp.arange(L)[None, :]
        labelpad = (l_idx >= ll[:, None]).astype(lp_btc.dtype)
        per_seq = optax.ctc_loss(lp_btc, logitpad, lab, labelpad,
                                 blank_id=blank)
        return _reduce(per_seq, reduction)
    return apply(_ctc, log_probs, labels, input_lengths, label_lengths,
                 op_name="ctc_loss")


# ---------------------------------------------------------------------------
# attention (tier-1 jnp path; the Pallas flash kernel replaces it on TPU)
# ---------------------------------------------------------------------------

def scaled_dot_product_attention(query, key, value, attn_mask=None,
                                 dropout_p=0.0, is_causal=False,
                                 training=True, name=None,
                                 return_weights=False):
    """[B, L, H, D] attention (paddle incubate layout).  The Pallas
    flash-attention kernel (paddle_tpu.ops.pallas) replaces the jnp path
    when FLAGS_use_pallas_kernels is on and shapes allow (reference analog:
    operators/math/bert_encoder_functor.cu fused attention).

    ``return_weights=True`` forces the unfused path and returns
    ``(out, weights [B, H, Lq, Lk])`` — post-softmax probabilities, with
    dropout applied in training mode (matching the reference, which
    returns the dropped weights: nn/layer/transformer.py:412-431)."""
    from ...core.flags import get_flag
    if get_flag("use_pallas_kernels") and not return_weights:
        from ...ops.pallas import flash_attention, flash_attention_supported
        q_shape = tuple(query.shape)
        k_shape = tuple(key.shape)
        dtype = (query.data if hasattr(query, "data") else query).dtype
        eff_dropout = dropout_p if training else 0.0
        if flash_attention_supported(q_shape, k_shape, dtype, attn_mask,
                                     eff_dropout):
            if eff_dropout > 0.0:
                fdraw = stable_draw()  # in-trace + replay-stable seed
                return apply(
                    lambda q, k, v: flash_attention(
                        q, k, v, causal=is_causal, dropout_p=eff_dropout,
                        seed=jax.random.bits(fdraw.key(), (1, 1),
                                             jnp.uint32)
                        .astype(jnp.int32)),
                    query, key, value,
                    op_name="flash_attention")
            return apply(
                lambda q, k, v: flash_attention(q, k, v, causal=is_causal),
                query, key, value, op_name="flash_attention")

    use_dropout = dropout_p > 0.0 and training

    sdpa_draw = stable_draw() if use_dropout else None

    def _sdpa(q, k, v, *m):
        # key resolved in-trace (see dropout): static/jitted programs
        # fold the per-run key instead of a record-time constant
        dkey = sdpa_draw.key() if use_dropout else None
        mask = m[0] if m else None
        B, Lq, H, D = q.shape
        scale = 1.0 / math.sqrt(D)
        qt = jnp.einsum("blhd,bshd->bhls", q, k) * scale
        if is_causal:
            causal = jnp.tril(jnp.ones((Lq, k.shape[1]), bool))
            qt = jnp.where(causal[None, None], qt, -jnp.inf)
        if mask is not None:
            if mask.dtype == jnp.bool_:
                qt = jnp.where(mask, qt, -jnp.inf)
            else:
                qt = qt + mask
        w = jax.nn.softmax(qt, axis=-1)
        w_used = w
        if dkey is not None:
            mask = _u16_dropout_mask(dkey, w.shape, dropout_p, w.dtype)
            if mask is not None:
                w_used = w * mask
        out = jnp.einsum("bhls,bshd->blhd", w_used, v)
        if return_weights:
            # post-DROPOUT weights in training mode: the reference passes
            # weights through F.dropout before returning them
            # (nn/layer/transformer.py:412-431)
            return out, w_used
        return out

    args = [query, key, value] + ([attn_mask] if attn_mask is not None else [])
    return apply(_sdpa, *args, op_name="scaled_dot_product_attention")


# ---------------------------------------------------------------------------
# misc (interpolate, pixel_shuffle, unfold, grid ops, sequence_mask)
# ---------------------------------------------------------------------------

def interpolate(x, size=None, scale_factor=None, mode="nearest",
                align_corners=False, align_mode=0, data_format="NCHW",
                name=None):
    def _interp(a):
        channels_last = not data_format.startswith("NC")
        a_ = a if channels_last else jnp.moveaxis(a, 1, -1)
        spatial = a_.shape[1:-1]
        if size is not None:
            out_sp = _norm_tuple(size, len(spatial))
        else:
            sf = scale_factor if isinstance(scale_factor, (list, tuple)) \
                else [scale_factor] * len(spatial)
            out_sp = tuple(int(s * f) for s, f in zip(spatial, sf))
        m = {"nearest": "nearest", "bilinear": "linear", "linear": "linear",
             "trilinear": "linear", "bicubic": "cubic", "area": "linear"}[mode]
        out = jax.image.resize(a_, (a_.shape[0], *out_sp, a_.shape[-1]),
                               method=m)
        return out if channels_last else jnp.moveaxis(out, -1, 1)
    return apply(_interp, x, op_name="interpolate")


upsample = interpolate


def pixel_shuffle(x, upscale_factor, data_format="NCHW", name=None):
    r = upscale_factor

    def _ps(a):
        N, C, H, W = a.shape
        out = a.reshape(N, C // (r * r), r, r, H, W)
        out = out.transpose(0, 1, 4, 2, 5, 3)
        return out.reshape(N, C // (r * r), H * r, W * r)
    return apply(_ps, x, op_name="pixel_shuffle")


def unfold(x, kernel_sizes, strides=1, paddings=0, dilations=1, name=None):
    k = _norm_tuple(kernel_sizes, 2)
    s = _norm_tuple(strides, 2)
    d = _norm_tuple(dilations, 2)
    p = _conv_padding(paddings, 2)

    def _unfold(a):
        N, C, H, W = a.shape
        patches = jax.lax.conv_general_dilated_patches(
            a, filter_shape=k, window_strides=s, padding=p, rhs_dilation=d,
            dimension_numbers=jax.lax.conv_dimension_numbers(
                a.shape, (1, C, *k), ("NCHW", "OIHW", "NCHW")))
        return patches.reshape(N, C * k[0] * k[1], -1)
    return apply(_unfold, x, op_name="unfold")


def pad(x, pad, mode="constant", value=0.0, data_format="NCHW", name=None):
    return _pad_op(x, pad, mode, value, data_format)


def sequence_mask(lengths, maxlen=None, dtype="int64", name=None):
    from ...core.dtype import convert_dtype
    d = convert_dtype(dtype)
    ml = maxlen or int(np.asarray(as_array(lengths)).max())
    return apply(lambda l: (jnp.arange(ml)[None, :] <
                            l[:, None]).astype(d),
                 lengths, op_name="sequence_mask", nondiff=True)


def label_smooth(label, prior_dist=None, epsilon=0.1, name=None):
    def _ls(t, *p):
        n = t.shape[-1]
        if p:
            return (1 - epsilon) * t + epsilon * p[0]
        return (1 - epsilon) * t + epsilon / n
    args = [label] + ([prior_dist] if prior_dist is not None else [])
    return apply(_ls, *args, op_name="label_smooth")


def temporal_shift(x, seg_num, shift_ratio=0.25, data_format="NCHW",
                   name=None):
    def _ts(a):
        NT, C, H, W = a.shape
        N = NT // seg_num
        v = a.reshape(N, seg_num, C, H, W)
        fold = int(C * shift_ratio)
        left = jnp.concatenate([v[:, 1:, :fold], jnp.zeros_like(
            v[:, :1, :fold])], axis=1)
        right = jnp.concatenate([jnp.zeros_like(v[:, :1, fold:2 * fold]),
                                 v[:, :-1, fold:2 * fold]], axis=1)
        rest = v[:, :, 2 * fold:]
        return jnp.concatenate([left, right, rest], axis=2).reshape(
            NT, C, H, W)
    return apply(_ts, x, op_name="temporal_shift")


def glu(x, axis=-1, name=None):
    return apply(lambda a: jax.nn.glu(a, axis=axis), x, op_name="glu")


def diag_embed(x, offset=0, dim1=-2, dim2=-1):
    def _de(a):
        n = a.shape[-1]
        out = jnp.zeros(a.shape + (n,), a.dtype)
        idx = jnp.arange(n)
        return out.at[..., idx, idx].set(a)
    return apply(_de, x, op_name="diag_embed")


# ---------------------------------------------------------------------------
# round-4 functional parity (reference: nn/functional full surface)
# ---------------------------------------------------------------------------

def log_sigmoid(x, name=None):
    """reference: activation.py log_sigmoid."""
    return apply(jax.nn.log_sigmoid, x, op_name="log_sigmoid",
                 cacheable=True)


def _thresholded_relu_fn(a, *, threshold):
    return jnp.where(a > threshold, a, 0.0)


def thresholded_relu(x, threshold=1.0, name=None):
    return apply(_thresholded_relu_fn, x, op_name="thresholded_relu",
                 threshold=float(threshold), cacheable=True)


def elu_(x, alpha=1.0, name=None):
    out = elu(x, alpha)
    x._rebind(out)
    return x


def relu_(x, name=None):
    out = relu(x)
    x._rebind(out)
    return x


def softmax_(x, axis=-1, dtype=None, name=None):
    out = softmax(x, axis, dtype)
    x._rebind(out)
    return x


def tanh_(x, name=None):
    from ...ops.math import tanh as _tanh
    out = _tanh(x)
    x._rebind(out)
    return x


def square_error_cost(input, label, name=None):
    """reference: loss.py square_error_cost — elementwise (x - y)^2."""
    return apply(lambda a, b: (a - b) ** 2, input, label,
                 op_name="square_error_cost")


def log_loss(input, label, epsilon=1e-4, name=None):
    """reference: loss.py log_loss — binary cross-entropy on
    probabilities."""
    def fn(p, y):
        return (-y * jnp.log(p + epsilon)
                - (1.0 - y) * jnp.log(1.0 - p + epsilon))
    return apply(fn, input, label, op_name="log_loss")


def dice_loss(input, label, epsilon=1e-5, name=None):
    """reference: loss.py dice_loss — 1 - dice coefficient over the
    class probabilities (input [N, ..., C] softmax outputs, label int)."""
    def fn(p, y):
        yf = jax.nn.one_hot(y.squeeze(-1), p.shape[-1], dtype=p.dtype)
        red = tuple(range(1, p.ndim))
        inter = jnp.sum(p * yf, axis=red)
        union = jnp.sum(p, axis=red) + jnp.sum(yf, axis=red)
        return jnp.mean(1.0 - (2.0 * inter + epsilon) / (union + epsilon))
    return apply(fn, input, label, op_name="dice_loss")


def npair_loss(anchor, positive, labels, l2_reg=0.002, name=None):
    """reference: loss.py npair_loss (Sohn 2016): softmax CE over
    anchor·positiveᵀ similarities + L2 on the embeddings."""
    def fn(a, p, y):
        sim = a @ p.T                                 # [B, B]
        lab = (y[:, None] == y[None, :]).astype(a.dtype)
        lab = lab / jnp.maximum(lab.sum(axis=1, keepdims=True), 1.0)
        logp = jax.nn.log_softmax(sim, axis=1)
        ce = -(lab * logp).sum(axis=1).mean()
        reg = l2_reg * ((a ** 2).sum(axis=1) + (p ** 2).sum(axis=1)
                        ).mean() * 0.25
        return ce + reg
    return apply(fn, anchor, positive, labels, op_name="npair_loss")


def hsigmoid_loss(input, label, num_classes, weight, bias=None,
                  path_table=None, path_code=None, is_sparse=False,
                  name=None):
    """Hierarchical sigmoid loss (reference: loss.py hsigmoid_loss /
    operators/hierarchical_sigmoid_op.cc).

    Default tree: complete binary tree over ``num_classes`` leaves (leaf
    of class c = node c + num_classes - 1, parent (i-1)//2, code = is-
    right-child) — the reference's non-custom-tree path.  Custom trees
    ride in ``path_table``/``path_code`` [N, L] (padded with -1)."""
    import numpy as np_

    if path_table is None:
        depth = max(int(np_.ceil(np_.log2(max(num_classes, 2)))), 1)
        tbl = np_.full((num_classes, depth), -1, np_.int64)
        code = np_.zeros((num_classes, depth), np_.float32)
        for c in range(num_classes):
            node = c + num_classes - 1
            path = []
            while node > 0:
                parent = (node - 1) // 2
                path.append((parent, float(node == 2 * parent + 2)))
                node = parent
            for d, (pn, bit) in enumerate(reversed(path)):
                tbl[c, d] = pn
                code[c, d] = bit
        la = as_array(label).reshape(-1)
        path_table = Tensor(jnp.asarray(tbl)[la])
        path_code = Tensor(jnp.asarray(code)[la])
    elif path_code is None:
        raise ValueError(
            "hsigmoid_loss: a custom path_table requires path_code")

    args = [input, label, path_table, path_code, weight] + (
        [bias] if bias is not None else [])

    def fn(x, y, tbl, code, w, *mb):
        valid = (tbl >= 0)
        t = jnp.maximum(tbl, 0)
        wn = w[t]                                 # [N, L, D]
        logits = jnp.einsum("nd,nld->nl", x, wn)
        if mb:
            logits = logits + mb[0][t]
        # BCE with the path code at every valid node
        ls = jax.nn.log_sigmoid(logits)
        lns = jax.nn.log_sigmoid(-logits)
        bce = -(code * ls + (1.0 - code) * lns)
        per_ex = (bce * valid).sum(axis=1)
        return per_ex[:, None]                     # [N, 1] like reference

    return apply(fn, *args, op_name="hsigmoid_loss")


def affine_grid(theta, out_shape, align_corners=True, name=None):
    """reference: vision.py affine_grid — sampling grid [N, H, W, 2] from
    2x3 affine matrices."""
    if hasattr(out_shape, "data"):
        out_shape = [int(v) for v in np_asarray(out_shape)]
    N, C, H, W = [int(v) for v in out_shape]

    def fn(th):
        if align_corners:
            ys = jnp.linspace(-1.0, 1.0, H)
            xs = jnp.linspace(-1.0, 1.0, W)
        else:
            ys = (jnp.arange(H) * 2 + 1) / H - 1.0
            xs = (jnp.arange(W) * 2 + 1) / W - 1.0
        gy, gx = jnp.meshgrid(ys, xs, indexing="ij")
        ones = jnp.ones_like(gx)
        base = jnp.stack([gx, gy, ones], axis=-1)      # [H, W, 3]
        return jnp.einsum("hwk,njk->nhwj", base, th)   # [N, H, W, 2]

    return apply(fn, theta, op_name="affine_grid")


def grid_sample(x, grid, mode="bilinear", padding_mode="zeros",
                align_corners=True, name=None):
    """reference: vision.py grid_sample — sample NCHW input at normalized
    grid locations [N, H', W', 2] (x, y order)."""
    if mode not in ("bilinear", "nearest"):
        raise NotImplementedError(f"grid_sample mode {mode!r}")
    if padding_mode not in ("zeros", "border"):
        raise NotImplementedError(
            f"grid_sample padding_mode {padding_mode!r}")

    def fn(a, g):
        N, C, H, W = a.shape
        gx, gy = g[..., 0], g[..., 1]
        if align_corners:
            fx = (gx + 1.0) * (W - 1) / 2.0
            fy = (gy + 1.0) * (H - 1) / 2.0
        else:
            fx = ((gx + 1.0) * W - 1.0) / 2.0
            fy = ((gy + 1.0) * H - 1.0) / 2.0

        def gather(yi, xi):
            yi = jnp.clip(yi, 0, H - 1)
            xi = jnp.clip(xi, 0, W - 1)
            bidx = jnp.arange(N)[:, None, None]
            return a[bidx, :, yi, xi]              # [N, H', W', C]

        # zeros padding masks PER TAP (the rounded/nearest index for
        # 'nearest', each corner for 'bilinear') so boundary-straddling
        # samples keep their partial in-bounds contribution — reference
        # grid_sampler semantics
        inb_idx = lambda yy, xx: ((yy >= 0) & (yy <= H - 1)
                                  & (xx >= 0) & (xx <= W - 1))
        if mode == "nearest":
            yi = jnp.round(fy).astype(jnp.int32)
            xi = jnp.round(fx).astype(jnp.int32)
            out = gather(yi, xi)
            if padding_mode == "zeros":
                out = out * inb_idx(yi, xi)[..., None]
        else:
            y0 = jnp.floor(fy).astype(jnp.int32)
            x0 = jnp.floor(fx).astype(jnp.int32)
            wy = fy - y0
            wx = fx - x0
            vals = 0.0
            for dy, dx, wgt in (
                    (0, 0, (1 - wy) * (1 - wx)), (0, 1, (1 - wy) * wx),
                    (1, 0, wy * (1 - wx)), (1, 1, wy * wx)):
                yi, xi = y0 + dy, x0 + dx
                v = gather(yi, xi)
                if padding_mode == "zeros":
                    v = v * inb_idx(yi, xi)[..., None]
                vals = vals + v * wgt[..., None]
            out = vals
        return jnp.moveaxis(out, -1, 1)            # -> [N, C, H', W']

    return apply(fn, x, grid, op_name="grid_sample")


def adaptive_avg_pool3d(x, output_size, data_format="NCDHW", name=None):
    os = _norm_tuple(output_size, 3)
    channels_last = not data_format.startswith("NC")

    def fn(a):
        if channels_last:                # NDHWC -> NCDHW
            a = jnp.moveaxis(a, -1, 1)
        N, C, D, H, W = a.shape
        if D % os[0] == 0 and H % os[1] == 0 and W % os[2] == 0:
            out = a.reshape(N, C, os[0], D // os[0], os[1], H // os[1],
                            os[2], W // os[2])
            out = out.mean(axis=(3, 5, 7))
            return jnp.moveaxis(out, 1, -1) if channels_last else out
        cells = jnp.zeros((N, C) + tuple(os), a.dtype)
        for i in range(os[0]):
            for j in range(os[1]):
                for k in range(os[2]):
                    blk = a[:, :,
                            (i * D) // os[0]:-(-(i + 1) * D // os[0]),
                            (j * H) // os[1]:-(-(j + 1) * H // os[1]),
                            (k * W) // os[2]:-(-(k + 1) * W // os[2])]
                    cells = cells.at[:, :, i, j, k].set(
                        blk.mean(axis=(2, 3, 4)))
        return jnp.moveaxis(cells, 1, -1) if channels_last else cells
    return apply(fn, x, op_name="adaptive_avg_pool3d")


def adaptive_max_pool3d(x, output_size, return_mask=False, name=None):
    if return_mask:
        raise NotImplementedError(
            "adaptive_max_pool3d: return_mask (argmax indices) is not "
            "implemented — dropping it silently would break "
            "reference-parity unpacking")
    os = _norm_tuple(output_size, 3)

    def fn(a):
        N, C, D, H, W = a.shape
        if D % os[0] == 0 and H % os[1] == 0 and W % os[2] == 0:
            out = a.reshape(N, C, os[0], D // os[0], os[1], H // os[1],
                            os[2], W // os[2])
            return out.max(axis=(3, 5, 7))
        cells = jnp.zeros((N, C) + tuple(os), a.dtype)
        for i in range(os[0]):
            for j in range(os[1]):
                for k in range(os[2]):
                    blk = a[:, :,
                            (i * D) // os[0]:-(-(i + 1) * D // os[0]),
                            (j * H) // os[1]:-(-(j + 1) * H // os[1]),
                            (k * W) // os[2]:-(-(k + 1) * W // os[2])]
                    cells = cells.at[:, :, i, j, k].set(
                        blk.max(axis=(2, 3, 4)))
        return cells
    return apply(fn, x, op_name="adaptive_max_pool3d")


def adaptive_max_pool1d(x, output_size, return_mask=False, name=None):
    if return_mask:
        raise NotImplementedError(
            "adaptive_max_pool1d: return_mask (argmax indices) is not "
            "implemented — dropping it silently would break "
            "reference-parity unpacking")
    os = int(output_size)

    def fn(a):
        N, C, L = a.shape
        if L % os == 0:
            return a.reshape(N, C, os, L // os).max(axis=3)
        return jnp.stack(
            [a[:, :, (i * L) // os:-(-(i + 1) * L // os)].max(axis=2)
             for i in range(os)], axis=-1)
    return apply(fn, x, op_name="adaptive_max_pool1d")


def conv1d_transpose(x, weight, bias=None, stride=1, padding=0,
                     output_padding=0, groups=1, dilation=1,
                     output_size=None, data_format="NCL", name=None):
    """reference: conv.py conv1d_transpose — via the 2-D kernel with a
    unit width axis."""
    channels_first = data_format.startswith("NC")
    # NCL -> NCLW (unit W after spatial); NLC -> NL1C (unit W axis 2,
    # keeping channels last)
    x4 = unsqueeze(x, -1 if channels_first else 2)
    w4 = unsqueeze(weight, -1)
    fmt = "NCHW" if channels_first else "NHWC"
    if output_size is not None:
        output_size = [_norm_tuple(output_size, 1)[0], 1]
    out = conv2d_transpose(
        x4, w4, bias, stride=(_norm_tuple(stride, 1)[0], 1),
        padding=(_norm_tuple(padding, 1)[0], 0),
        output_padding=(_norm_tuple(output_padding, 1)[0], 0),
        dilation=(_norm_tuple(dilation, 1)[0], 1), groups=groups,
        output_size=output_size, data_format=fmt)
    return squeeze(out, -1 if channels_first else 2)


def conv3d_transpose(x, weight, bias=None, stride=1, padding=0,
                     output_padding=0, groups=1, dilation=1,
                     output_size=None, data_format="NCDHW", name=None):
    """reference: conv.py conv3d_transpose (gradient-of-conv3d)."""
    n = 3
    stride = _norm_tuple(stride, n)
    dilation = _norm_tuple(dilation, n)
    outpad = _norm_tuple(output_padding, n)
    if groups != 1:
        raise NotImplementedError("conv3d_transpose: groups > 1")
    channels_last = not data_format.startswith("NC")

    def fn(a, w):
        a_ = jnp.moveaxis(a, -1, 1) if channels_last else a
        pads = _conv_padding(padding, n)
        if isinstance(pads, str):
            raise ValueError(
                "string padding unsupported for conv_transpose")
        outpad_eff = list(outpad)
        if output_size is not None:
            os_ = _norm_tuple(tuple(output_size), n)
            for i in range(n):
                kk = (w.shape[2 + i] - 1) * dilation[i] + 1
                lo, hi = pads[i]
                base = (a_.shape[2 + i] - 1) * stride[i] - lo - hi + kk
                op = os_[i] - base
                if not 0 <= op < max(stride[i], 1) + 1:
                    raise ValueError(
                        f"conv3d_transpose: output_size[{i}]={os_[i]} "
                        f"not reachable (base {base}, stride {stride[i]})")
                outpad_eff[i] = op
        pad_list = []
        for i in range(n):
            kk = (w.shape[2 + i] - 1) * dilation[i] + 1
            lo, hi = pads[i]
            pad_list.append((kk - 1 - lo, kk - 1 - hi + outpad_eff[i]))
        w_t = jnp.swapaxes(jnp.flip(w, axis=(2, 3, 4)), 0, 1)
        dn = jax.lax.conv_dimension_numbers(
            a_.shape, w_t.shape, ("NCDHW", "OIDHW", "NCDHW"))
        out = jax.lax.conv_general_dilated(
            a_, w_t, window_strides=(1, 1, 1), padding=pad_list,
            lhs_dilation=stride, rhs_dilation=dilation,
            dimension_numbers=dn)
        return jnp.moveaxis(out, 1, -1) if channels_last else out

    out = apply(fn, x, weight, op_name="conv3d_transpose")
    if bias is not None:
        shape = [1] * 5
        shape[-1 if channels_last else 1] = -1
        out = out + bias.reshape(shape)
    return out


def np_asarray(x):
    import numpy as _np
    return _np.asarray(x.data if hasattr(x, "data") else x)


from ..decode import gather_tree  # noqa: F401,E402

from . import activation, common, conv, extension, loss, pooling  # noqa
