"""paddle_tpu.nn — Layer system and neural-net layers
(reference: python/paddle/nn/, ~19k LoC layer+functional; SURVEY §2.4)."""
from . import functional  # noqa: F401
from . import initializer  # noqa: F401
from .layer_base import Layer, ParamAttr  # noqa: F401
from .layer.activation import (CELU, ELU, GELU, GLU, Hardshrink,  # noqa
                               Hardsigmoid, Hardswish, Hardtanh, LeakyReLU,
                               LogSoftmax, Maxout, Mish, PReLU, ReLU, ReLU6,
                               SELU, Sigmoid, Silu, Softmax, Softplus,
                               Softshrink, Softsign, Swish, Tanh, Tanhshrink,
                               ThresholdedReLU)
from .layer.common import (AlphaDropout, Bilinear, CosineSimilarity,  # noqa
                           Dropout, Dropout2D, Embedding, Flatten, Identity,
                           Linear, Pad1D, Pad2D, Pad3D, PixelShuffle,
                           Unfold, Upsample)
from .layer.container import (LayerDict, LayerList, ParameterList,  # noqa
                              Sequential)
from .layer.conv import (Conv1D, Conv2D, Conv2DTranspose, Conv3D)  # noqa
from .layer.loss import (BCELoss, BCEWithLogitsLoss, CosineEmbeddingLoss,  # noqa
                         CrossEntropyLoss, CTCLoss, HingeEmbeddingLoss,
                         KLDivLoss, L1Loss, MarginRankingLoss, MSELoss,
                         NLLLoss, SmoothL1Loss)
from .layer.norm import (BatchNorm, BatchNorm1D, BatchNorm2D, BatchNorm3D,  # noqa
                         GroupNorm, InstanceNorm1D, InstanceNorm2D,
                         InstanceNorm3D, LayerNorm, LocalResponseNorm,
                         SpectralNorm, SyncBatchNorm)
from .layer.pooling import (AdaptiveAvgPool1D, AdaptiveAvgPool2D,  # noqa
                            AdaptiveMaxPool2D, AvgPool1D, AvgPool2D,
                            AvgPool3D, MaxPool1D, MaxPool2D, MaxPool3D)
from .layer.rnn import (BiRNN, GRU, GRUCell, LSTM, LSTMCell, RNN,  # noqa
                        RNNCellBase, SimpleRNN, SimpleRNNCell)
from .layer.transformer import (MultiHeadAttention, Transformer,  # noqa
                                TransformerDecoder, TransformerDecoderLayer,
                                TransformerEncoder, TransformerEncoderLayer)

from .decode import (BeamSearchDecoder, cell_step, dynamic_decode,  # noqa
                     gather_tree)

# -- round-4 parity additions --------------------------------------------
from .layer.activation import LogSigmoid  # noqa: F401,E402
from .layer.common import (Dropout3D, PairwiseDistance,  # noqa: F401,E402
                           UpsamplingBilinear2D, UpsamplingNearest2D)
from .layer.conv import Conv1DTranspose, Conv3DTranspose  # noqa: F401,E402
from .layer.loss import HSigmoidLoss  # noqa: F401,E402
from .layer.pooling import (AdaptiveAvgPool3D,  # noqa: F401,E402
                            AdaptiveMaxPool1D, AdaptiveMaxPool3D)
# gradient-clip classes ride in paddle.nn too (reference nn/__init__.py)
from ..optimizer.clip import (ClipGradByGlobalNorm,  # noqa: F401,E402
                              ClipGradByNorm, ClipGradByValue)
# reference exposes the layer submodules as paddle.nn.<name>
from .layer import (activation, common, conv, loss, norm,  # noqa: F401
                    pooling, rnn)
from .layer import common as extension  # noqa: F401,E402
from .layer import conv as vision  # noqa: F401,E402
from .utils import remove_weight_norm, weight_norm  # noqa: F401,E402
from . import utils as weight_norm_hook  # noqa: F401,E402
