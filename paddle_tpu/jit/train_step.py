"""TrainStep: whole-training-step compilation.

The TPU-native analog of the reference's CompiledProgram/ParallelExecutor
fast path (reference: fluid/compiler.py, parallel_executor.cc:619): forward,
backward, gradient clip, and optimizer update are traced into ONE XLA
executable with donated buffers, so the MXU never waits on Python between
micro-steps.  Under a `Mesh` (paddle_tpu.distributed) the same step is
pjit-sharded for DP/TP/PP hybrid execution.
"""
from __future__ import annotations

from typing import Any, Callable, Dict, List, Optional

import jax
import jax.numpy as jnp

from ..core import autograd, rng
from ..core.tensor import Tensor
from .bind import bind, buffer_arrays, buffer_names, param_list

_as_arr = lambda x: x.data if isinstance(x, Tensor) else jnp.asarray(x)


class TrainStep:
    """Compile `loss = loss_fn(model(*inputs), *labels)` + optimizer update.

    Usage::

        step = TrainStep(model, loss_fn, opt)       # loss_fn(outputs, labels)
        loss = step(x, y)                            # one fused XLA call

    ``loss_fn`` receives (model_output, *labels) as Tensors inside the trace.
    Model parameters / optimizer slots / buffers live as device arrays
    between calls and are donated each step (no copies).
    """

    def __init__(self, model, loss_fn: Callable, optimizer,
                 n_inputs: int = 1, donate: bool = False):
        # donate=False by default: eager user code may alias param arrays
        # (e.g. state_dict sharing); SpmdTrainStep/bench enable donation.
        self.model = model
        self.loss_fn = loss_fn
        self.optimizer = optimizer
        self.n_inputs = n_inputs
        self._params = param_list(model)
        self._bnames = buffer_names(model)
        self._compiled: Dict[Any, Callable] = {}
        self._opt_state = None
        self._donate = donate

    def _build(self, training: bool):
        model, loss_fn, opt = self.model, self.loss_fn, self.optimizer
        params_meta = self._params
        bnames = self._bnames
        n_in = self.n_inputs

        def step_fn(p_arr, b_arr, opt_state, lr, step_i, key_data, inputs,
                    labels):
            key = jax.random.wrap_key_data(key_data)

            def loss_of(p_list):
                with autograd.no_grad(), rng.seed_scope(key):
                    with bind(model, p_list, list(b_arr)) as res:
                        out = model(*[Tensor(a) for a in inputs])
                        lab = [Tensor(a) for a in labels]
                        loss_t = loss_fn(out, *lab)
                    # new_buffers is populated on bind-context exit
                    new_b = tuple(
                        _as_arr(res.new_buffers.get(n, old))
                        for n, old in zip(bnames, b_arr))
                return loss_t.data, new_b

            (loss, new_b), grads = jax.value_and_grad(
                loss_of, has_aux=True)(list(p_arr))
            new_p, new_s = opt.functional_update(
                list(p_arr), grads, opt_state, lr, step_i,
                params_meta=params_meta)
            return loss, tuple(new_p), new_b, new_s

        donate = (0, 1, 2) if self._donate else ()
        return jax.jit(step_fn, donate_argnums=donate)

    def __call__(self, *batch):
        assert len(batch) >= self.n_inputs, (
            f"TrainStep expects at least {self.n_inputs} input(s)")
        inputs = tuple(_as_arr(b) for b in batch[:self.n_inputs])
        labels = tuple(_as_arr(b) for b in batch[self.n_inputs:])
        p_arr = tuple(p.data for p in self._params)
        b_arr = tuple(buffer_arrays(self.model))
        if self._opt_state is None:
            self._opt_state = self.optimizer.functional_init(list(p_arr))
        key = self.optimizer  # noqa: F841 (readability)
        training = self.model.training
        compiled = self._compiled.get(training)
        if compiled is None:
            compiled = self._build(training)
            self._compiled[training] = compiled

        self.optimizer._step_count += 1
        lr = jnp.asarray(self.optimizer.get_lr(), jnp.float32)
        step_i = jnp.asarray(self.optimizer._step_count, jnp.float32)
        key_data = jax.random.key_data(rng.next_key())
        loss, new_p, new_b, new_s = compiled(
            p_arr, b_arr, self._opt_state, lr, step_i, key_data, inputs,
            labels)
        # write back (device-side aliasing, no host copies)
        for p, arr in zip(self._params, new_p):
            p.data = arr
        buffers = dict(self.model.named_buffers())
        for n, arr in zip(self._bnames, new_b):
            buffers[n].data = arr
        self._opt_state = new_s
        return Tensor(loss)

    def eval_step(self, *batch):
        """Forward-only compiled step (no param update)."""
        inputs = tuple(_as_arr(b) for b in batch[:self.n_inputs])
        labels = tuple(_as_arr(b) for b in batch[self.n_inputs:])
        model, loss_fn = self.model, self.loss_fn
        bnames = self._bnames

        key = ("eval", model.training)
        compiled = self._compiled.get(key)
        if compiled is None:
            def eval_fn(p_arr, b_arr, key_data, inputs, labels):
                k = jax.random.wrap_key_data(key_data)
                with autograd.no_grad(), rng.seed_scope(k):
                    with bind(model, list(p_arr), list(b_arr)):
                        out = model(*[Tensor(a) for a in inputs])
                        lab = [Tensor(a) for a in labels]
                        loss_t = loss_fn(out, *lab)
                out_arr = jax.tree.map(
                    lambda t: t.data if isinstance(t, Tensor) else t, out,
                    is_leaf=lambda x: isinstance(x, Tensor))
                return loss_t.data, out_arr
            compiled = jax.jit(eval_fn)
            self._compiled[key] = compiled
        p_arr = tuple(p.data for p in self._params)
        b_arr = tuple(buffer_arrays(self.model))
        key_data = jax.random.key_data(rng.next_key())
        loss, out = compiled(p_arr, b_arr, key_data, inputs, labels)
        return Tensor(loss), jax.tree.map(Tensor, out)
