"""TrainStep: whole-training-step compilation.

The TPU-native analog of the reference's CompiledProgram/ParallelExecutor
fast path (reference: fluid/compiler.py, parallel_executor.cc:619): forward,
backward, gradient clip, and optimizer update are traced into ONE XLA
executable with donated buffers, so the MXU never waits on Python between
micro-steps.  Under a `Mesh` (paddle_tpu.distributed) the same step is
pjit-sharded for DP/TP/PP hybrid execution.

Also compiled in-graph (zero host syncs per step):
- **dynamic loss scaling** (``scaler=``): scale the loss, unscale grads,
  detect non-finite grads, skip the update and adjust the scale — the
  reference's check_finite_and_unscale + update_loss_scaling ops
  (operators/amp/check_finite_and_unscale_op.cu, update_loss_scaling_op.cu)
  as a handful of fused scalar ops.
- **gradient accumulation** (``accumulate_steps=k``): a lax.scan over k
  microbatches accumulating f32 grads, one optimizer update — the
  reference's gradient-merge meta-optimizer
  (fleet/meta_optimizers/gradient_merge_optimizer.py:18,
  grad_merge_all_reduce_op_handle.cc) without the extra memory pass.
"""
from __future__ import annotations

import contextlib
from typing import Any, Callable, Dict, List, Optional

import jax
import jax.numpy as jnp

from ..core import autograd, rng
from ..core.tensor import Tensor
from .bind import bind, buffer_arrays, buffer_names, param_list

_as_arr = lambda x: x.data if isinstance(x, Tensor) else jnp.asarray(x)


def _select(pred, when_true, when_false):
    """Per-leaf scalar select over matching pytrees."""
    return jax.tree.map(lambda a, b: jnp.where(pred, a, b),
                        when_true, when_false)


class TrainStep:
    """Compile `loss = loss_fn(model(*inputs), *labels)` + optimizer update.

    Usage::

        step = TrainStep(model, loss_fn, opt)       # loss_fn(outputs, labels)
        loss = step(x, y)                            # one fused XLA call

    ``loss_fn`` receives (model_output, *labels) as Tensors inside the trace.
    Model parameters / optimizer slots / buffers live as device arrays
    between calls and are donated each step (no copies).

    ``scaler``: a paddle_tpu.amp.GradScaler whose dynamic-loss-scaling state
    is threaded through the compiled step (fp16 path; bf16 needs none).
    ``accumulate_steps``: microbatch gradient accumulation inside the step
    (the global batch you pass is split into this many microbatches).
    """

    def __init__(self, model, loss_fn: Callable, optimizer,
                 n_inputs: int = 1, donate: bool = False, scaler=None,
                 accumulate_steps: int = 1, amp_level: Optional[str] = None,
                 recompute: bool = False):
        # donate=False by default: eager user code may alias param arrays
        # (e.g. state_dict sharing); SpmdTrainStep/bench enable donation.
        self.model = model
        self.loss_fn = loss_fn
        self.optimizer = optimizer
        self.n_inputs = n_inputs
        self._params = param_list(model)
        self._bnames = buffer_names(model)
        self._compiled: Dict[Any, Callable] = {}
        self._opt_state = None
        self._donate = donate
        self.scaler = (scaler if scaler is not None
                       and getattr(scaler, "_enable", True) else None)
        self.accumulate_steps = int(accumulate_steps)
        # amp_level: re-enter auto_cast(level, model's decorated dtype)
        # inside the compiled trace (the reference's train-loop
        # `with amp.auto_cast(...)`); None = trace ops at their natural
        # dtypes (pure-bf16 after amp.decorate O2)
        self.amp_level = amp_level
        # recompute: rematerialise the forward during backward instead of
        # storing activations — the reference's recompute meta-optimizer
        # (fleet/meta_optimizers/recompute_optimizer.py:18) as jax.checkpoint
        # over the whole loss (checkpoints=[] edge: keep only the inputs)
        self._recompute = bool(recompute)
        self._scaler_state = None
        self._lr_value = None
        self._lr_device = None
        self._buffer_objs = None
        if self.scaler is not None:
            # let scaler.state_dict()/load_state_dict() see the in-graph
            # state (checkpoint correctness)
            self.scaler._bound_step = self
        # let optimizer.state_dict()/set_state_dict() see / resync the
        # in-graph step counter (checkpoint correctness)
        optimizer._bound_train_step = self

    # -- hooks for subclasses ---------------------------------------------
    def _grad_transform(self, grads: List[jnp.ndarray]) -> List[jnp.ndarray]:
        """Applied to (unscaled) grads before the optimizer update.
        SpmdTrainStep overrides this for ZeRO-2 grad sharding."""
        return grads

    def _decode_params(self, p_list):
        """Stored form -> model-shaped arrays (inside the trace).
        SpmdTrainStep overrides this to un-pad ZeRO-3 padded shards."""
        return p_list

    def _wrap_loss_and_grad(self, fn):
        """Wrap the per-microbatch (b_cur, inputs, labels, kidx) ->
        (loss, new_buffers, grads) function.  SpmdTrainStep overrides this
        for fp16_allreduce (shard_map with reduced-precision grad psum)."""
        return fn

    def _value_and_grad(self, loss_of, p_list):
        """Differentiate ``loss_of`` (returns (scaled_loss, (loss, new_b)))
        w.r.t. the stored param list, honoring ``recompute``."""
        if self._recompute:
            loss_of = jax.checkpoint(loss_of)
        return jax.value_and_grad(loss_of, has_aux=True)(p_list)

    def _param_arrays(self):
        """Stored param arrays fed to the compiled step (subclasses may
        keep a padded/sharded store distinct from ``p.data``)."""
        return tuple(p.data for p in self._params)

    def _writeback_params(self, new_p):
        for p, arr in zip(self._params, new_p):
            p.data = arr

    def sync_params(self):
        """Materialise any step-held authoritative weights into the model
        (no-op here; ZeRO-3 padded / LocalSGD subclasses override).  Layer
        .state_dict() calls this via the ``_param_owner_step`` hook."""

    # -- the compiled step -------------------------------------------------
    def _make_step_fn(self):
        model, loss_fn, opt = self.model, self.loss_fn, self.optimizer
        params_meta = self._params
        bnames = self._bnames
        K = self.accumulate_steps
        scaler = self.scaler
        grad_transform = self._grad_transform
        if scaler is not None:
            sc = dict(incr_ratio=scaler._incr_ratio,
                      decr_ratio=scaler._decr_ratio,
                      incr_every=scaler._incr_every,
                      decr_every=scaler._decr_every,
                      dynamic=scaler._dynamic)

        def step_fn(p_arr, b_arr, opt_state, aux, lr, inputs, labels):
            # aux carries everything that changes per step but lives on
            # device: the RNG base key, the effective step counter, and the
            # loss-scaling state.  Keeping these in-graph means __call__
            # performs ZERO host->device uploads per step (each tiny
            # upload costs ~10 ms through a remote-device tunnel and
            # serialises the pipeline).
            key = jax.random.wrap_key_data(aux["key"])
            # 'step' counts only applied updates (non-finite-grad steps
            # don't advance Adam bias correction — reference GradScaler
            # semantics where optimizer.step() is skipped); 'draw' advances
            # every call so RNG draws are never reused after a skip
            attempt = aux["step"] + 1
            draw = aux["draw"] + 1
            step_i = attempt.astype(jnp.float32)
            key = jax.random.fold_in(key, draw)
            scale = aux["scale"] if scaler is not None else None

            amp_level = self.amp_level

            def amp_scope():
                if amp_level is None:
                    return contextlib.nullcontext()
                from ..amp import auto_cast
                return auto_cast(level=amp_level,
                                 dtype=getattr(model, "_amp_dtype",
                                               "bfloat16"))

            def loss_and_grad(p_cur, b_cur, mb_inputs, mb_labels, kidx):
                def loss_of(p_list):
                    k_mb = jax.random.fold_in(key, kidx)
                    p_model = self._decode_params(p_list)
                    with autograd.no_grad(), rng.seed_scope(k_mb), \
                            amp_scope():
                        with bind(model, p_model, list(b_cur)) as res:
                            out = model(*[Tensor(a) for a in mb_inputs])
                            lab = [Tensor(a) for a in mb_labels]
                            loss_t = loss_fn(out, *lab)
                        # new_buffers is populated on bind-context exit
                        new_b = tuple(
                            _as_arr(res.new_buffers.get(n, old))
                            for n, old in zip(bnames, b_cur))
                    loss = loss_t.data
                    scaled = loss * scale if scaler is not None else loss
                    return scaled, (loss, new_b)

                (_, (loss, new_b)), grads = self._value_and_grad(
                    loss_of, list(p_cur))
                return loss, new_b, grads

            loss_and_grad = self._wrap_loss_and_grad(loss_and_grad)

            if K <= 1:
                loss, new_b, grads = loss_and_grad(p_arr, b_arr, inputs,
                                                   labels, 0)
            else:
                # gradient merge: scan over K microbatches, f32 accumulators
                mb_in = tuple(a.reshape(K, a.shape[0] // K, *a.shape[1:])
                              for a in inputs)
                mb_lab = tuple(a.reshape(K, a.shape[0] // K, *a.shape[1:])
                               for a in labels)

                def mb_body(carry, xs):
                    b_cur, g_acc, l_acc = carry
                    idx, ins, labs = xs
                    loss, new_b, grads = loss_and_grad(p_arr, b_cur, ins,
                                                       labs, idx)
                    g_acc = [ga + g.astype(jnp.float32)
                             for ga, g in zip(g_acc, grads)]
                    return (new_b, g_acc, l_acc + loss), None

                g0 = [jnp.zeros(p.shape, jnp.float32) for p in p_arr]
                (new_b, g_acc, l_sum), _ = jax.lax.scan(
                    mb_body, (tuple(b_arr), g0, jnp.zeros((), jnp.float32)),
                    (jnp.arange(K), mb_in, mb_lab))
                loss = l_sum / K
                grads = [g / K for g in g_acc]

            if scaler is not None:
                inv = 1.0 / scale
                grads = [g * inv for g in grads]
                finite = jnp.all(jnp.stack(
                    [jnp.all(jnp.isfinite(g)) for g in grads]))
                found_inf = jnp.logical_not(finite)

            grads = grad_transform(grads)
            new_p, new_s = opt.functional_update(
                list(p_arr), grads, opt_state, lr, step_i,
                params_meta=params_meta)

            new_aux = dict(aux)
            new_aux["draw"] = draw
            if scaler is not None:
                # skip the update on non-finite grads (reference:
                # check_finite_and_unscale) ...
                new_p = _select(found_inf, list(p_arr), new_p)
                new_s = _select(found_inf, opt_state, new_s)
                # ... and adjust the scale in-graph (update_loss_scaling)
                good, bad = aux["good"], aux["bad"]
                if sc["dynamic"]:
                    good = jnp.where(found_inf, 0, good + 1)
                    bad = jnp.where(found_inf, bad + 1, 0)
                    dec = bad >= sc["decr_every"]
                    new_scale = jnp.where(
                        dec, jnp.maximum(scale * sc["decr_ratio"], 1.0),
                        scale)
                    bad = jnp.where(dec, 0, bad)
                    inc = good >= sc["incr_every"]
                    new_scale = jnp.where(inc, new_scale * sc["incr_ratio"],
                                          new_scale)
                    good = jnp.where(inc, 0, good)
                else:
                    new_scale = scale
                new_aux.update(scale=new_scale, good=good, bad=bad,
                               found_inf=found_inf,
                               step=jnp.where(found_inf, aux["step"],
                                              attempt))
            else:
                new_aux["step"] = attempt
            return loss, tuple(new_p), new_b, new_s, new_aux

        return step_fn

    def _build(self, training: bool):
        donate = (0, 1, 2, 3) if self._donate else ()
        return jax.jit(self._make_step_fn(), donate_argnums=donate)

    def _aux_keys(self):
        """Static key set of the aux carry (no side effects — used to
        build shardings without consuming RNG state)."""
        keys = ["step", "draw", "key"]
        if self.scaler is not None:
            keys += ["scale", "good", "bad", "found_inf"]
        return keys

    def _init_scaler_state(self):
        """Device-resident per-step carry: step/draw counters, RNG base
        key, and (when a scaler is bound) the dynamic loss-scaling state.
        The applied-step counter seeds from the optimizer's host count so a
        set_state_dict before the first step is honored."""
        aux = {"step": jnp.asarray(self.optimizer._step_count, jnp.int32),
               "draw": jnp.asarray(0, jnp.int32),
               "key": jax.random.key_data(rng.next_key())}
        if self.scaler is not None:
            aux.update(
                scale=jnp.asarray(self.scaler._scale, jnp.float32),
                good=jnp.asarray(self.scaler._good_steps, jnp.int32),
                bad=jnp.asarray(self.scaler._bad_steps, jnp.int32),
                found_inf=jnp.asarray(False))
        return aux

    @property
    def loss_scale(self) -> Optional[float]:
        """Current loss scale (host sync; for logging/checkpoint only)."""
        if self._scaler_state is None or "scale" not in self._scaler_state:
            return None
        return float(self._scaler_state["scale"])

    def __call__(self, *batch):
        assert len(batch) >= self.n_inputs, (
            f"TrainStep expects at least {self.n_inputs} input(s)")
        inputs = tuple(_as_arr(b) for b in batch[:self.n_inputs])
        labels = tuple(_as_arr(b) for b in batch[self.n_inputs:])
        if self.accumulate_steps > 1:
            bs = inputs[0].shape[0]
            if bs % self.accumulate_steps:
                raise ValueError(
                    f"batch size {bs} is not divisible by "
                    f"accumulate_steps={self.accumulate_steps}")
        p_arr = self._param_arrays()
        b_arr = tuple(buffer_arrays(self.model))
        if self._opt_state is None:
            self._opt_state = self.optimizer.functional_init(list(p_arr))
        if self._scaler_state is None:
            self._scaler_state = self._init_scaler_state()
        training = self.model.training
        compiled = self._compiled.get(training)
        if compiled is None:
            compiled = self._build(training)
            self._compiled[training] = compiled

        self.optimizer._step_count += 1
        lr_val = float(self.optimizer.get_lr())
        if lr_val != self._lr_value:
            # upload the lr only when the schedule moves it (a tiny
            # host->device transfer costs ~10 ms over a device tunnel)
            self._lr_value = lr_val
            self._lr_device = jnp.asarray(lr_val, jnp.float32)
        loss, new_p, new_b, new_s, new_sc = compiled(
            p_arr, b_arr, self._opt_state, self._scaler_state,
            self._lr_device, inputs, labels)
        # write back (device-side aliasing, no host copies)
        self._writeback_params(new_p)
        if self._buffer_objs is None:
            buffers = dict(self.model.named_buffers())
            self._buffer_objs = [buffers[n] for n in self._bnames]
        for b, arr in zip(self._buffer_objs, new_b):
            b.data = arr
        self._opt_state = new_s
        self._scaler_state = new_sc
        return Tensor(loss)

    def eval_step(self, *batch):
        """Forward-only compiled step (no param update)."""
        inputs = tuple(_as_arr(b) for b in batch[:self.n_inputs])
        labels = tuple(_as_arr(b) for b in batch[self.n_inputs:])
        model, loss_fn = self.model, self.loss_fn
        bnames = self._bnames

        key = ("eval", model.training)
        compiled = self._compiled.get(key)
        if compiled is None:
            def eval_fn(p_arr, b_arr, key_data, inputs, labels):
                k = jax.random.wrap_key_data(key_data)
                p_model = self._decode_params(list(p_arr))
                with autograd.no_grad(), rng.seed_scope(k):
                    with bind(model, p_model, list(b_arr)):
                        out = model(*[Tensor(a) for a in inputs])
                        lab = [Tensor(a) for a in labels]
                        loss_t = loss_fn(out, *lab)
                out_arr = jax.tree.map(
                    lambda t: t.data if isinstance(t, Tensor) else t, out,
                    is_leaf=lambda x: isinstance(x, Tensor))
                return loss_t.data, out_arr
            compiled = jax.jit(eval_fn)
            self._compiled[key] = compiled
        p_arr = self._param_arrays()
        b_arr = tuple(buffer_arrays(self.model))
        key_data = jax.random.key_data(rng.next_key())
        loss, out = compiled(p_arr, b_arr, key_data, inputs, labels)
        return Tensor(loss), jax.tree.map(Tensor, out)
