"""dy2static AST conversion: Python ``if`` on tensor predicates → cond.

Reference: python/paddle/fluid/dygraph/dygraph_to_static/ — the reference
ships 20+ AST transformers (ifelse_transformer.py,
loop_transformer.py, ...) rewriting user Python into ProgramDesc ops.
TPU-native stance: tracing handles everything EXCEPT genuine
data-dependent Python control flow, so only that needs rewriting.  This
module converts the two ubiquitous patterns:

1. ``if cond: <assignments>  else: <assignments>`` where both branches
   assign the same simple names → both branches become closures returning
   those names, dispatched through :func:`_jst_cond`;
2. ``if cond: return A`` followed by ``return B`` (and the two-armed
   ``if/else`` with lone returns) → ``return _jst_cond(cond, ...)``.

``_jst_cond`` preserves EAGER semantics exactly (a concrete/bool
predicate runs one branch in Python); only traced tensor predicates lower
to ``lax.cond``.  Anything the transformer cannot prove convertible is
left untouched — an unconverted tensor ``if`` still raises the loud
trace-time error pointing at paddle.cond (no silent mistracing).
``while`` loops are not converted (use paddle.while_loop; XLA's While has
no reverse-mode adjoint, so auto-converting could silently break
training).
"""
from __future__ import annotations

import ast
import functools
import inspect
import textwrap
from typing import Callable, List, Optional, Set

__all__ = ["convert_control_flow", "_jst_cond"]


def _jst_cond(pred, true_fn, false_fn):
    """Runtime dispatch for converted ifs: Python branch when the
    predicate is concrete, paddle.cond when traced."""
    from ..core.tensor import Tensor
    import jax

    p = pred.data if isinstance(pred, Tensor) else pred
    if isinstance(p, jax.core.Tracer):
        from ..ops.control_flow import cond
        return cond(pred, true_fn, false_fn)
    return true_fn() if p else false_fn()


def _loads(node) -> Set[str]:
    return {n.id for n in ast.walk(node)
            if isinstance(n, ast.Name) and isinstance(n.ctx, ast.Load)}


def _assigned_names(stmts: List[ast.stmt]) -> Optional[Set[str]]:
    """Simple names assigned by ``stmts``; None if anything non-trivial
    (aug-assign, attribute/subscript targets, nested control flow, or a
    read of a to-be-assigned name before its assignment — which would
    become an UnboundLocalError inside the branch closure)."""
    names: Set[str] = set()
    all_assigned: Set[str] = set()
    for s in stmts:
        if isinstance(s, ast.Assign):
            for t in s.targets:
                if isinstance(t, ast.Name):
                    all_assigned.add(t.id)
                elif isinstance(t, ast.Tuple) and all(
                        isinstance(e, ast.Name) for e in t.elts):
                    all_assigned.update(e.id for e in t.elts)
                else:
                    return None
        elif not isinstance(s, ast.Expr):
            return None
    assigned_so_far: Set[str] = set()
    for s in stmts:
        if isinstance(s, ast.Assign):
            # reading a name this branch assigns LATER (incl. this stmt's
            # own target, `x = x + 1`) would hit the closure-local unbound
            if (_loads(s.value) & all_assigned) - assigned_so_far:
                return None
            for t in s.targets:
                if isinstance(t, ast.Name):
                    assigned_so_far.add(t.id)
                else:
                    assigned_so_far.update(e.id for e in t.elts)
            names = assigned_so_far
        elif isinstance(s, ast.Expr):
            if (_loads(s) & all_assigned) - assigned_so_far:
                return None
    return set(names)


class _IfElseTransformer(ast.NodeTransformer):
    """reference: dygraph_to_static/ifelse_transformer.py."""

    def __init__(self):
        self.count = 0
        self.converted = 0

    # -- pattern 2: early return --------------------------------------------
    def _convert_return_pair(self, test, a_ret, b_ret):
        self.converted += 1
        t = ast.Lambda(
            args=ast.arguments(posonlyargs=[], args=[], kwonlyargs=[],
                               kw_defaults=[], defaults=[]),
            body=a_ret.value or ast.Constant(None))
        f = ast.Lambda(
            args=ast.arguments(posonlyargs=[], args=[], kwonlyargs=[],
                               kw_defaults=[], defaults=[]),
            body=b_ret.value or ast.Constant(None))
        call = ast.Call(func=ast.Name("_jst_cond", ast.Load()),
                        args=[test, t, f], keywords=[])
        return ast.Return(value=call)

    def _rewrite_body(self, body: List[ast.stmt]) -> List[ast.stmt]:
        out: List[ast.stmt] = []
        i = 0
        while i < len(body):
            s = body[i]
            if isinstance(s, ast.If):
                nxt = body[i + 1] if i + 1 < len(body) else None
                # `if c: return A` / `return B`  (tail follows the if)
                if (len(s.body) == 1 and isinstance(s.body[0], ast.Return)
                        and not s.orelse and isinstance(nxt, ast.Return)):
                    out.append(self._convert_return_pair(
                        s.test, s.body[0], nxt))
                    i += 2
                    continue
                # `if c: return A else: return B`
                if (len(s.body) == 1 and isinstance(s.body[0], ast.Return)
                        and len(s.orelse) == 1
                        and isinstance(s.orelse[0], ast.Return)):
                    out.append(self._convert_return_pair(
                        s.test, s.body[0], s.orelse[0]))
                    i += 1
                    continue
                conv = self._convert_assign_if(s)
                if conv is not None:
                    out.extend(conv)
                    i += 1
                    continue
            out.append(s)
            i += 1
        return out

    # -- pattern 1: both-branch assignments ---------------------------------
    def _convert_assign_if(self, node: ast.If) -> Optional[List[ast.stmt]]:
        if not node.orelse:
            return None
        a = _assigned_names(node.body)
        b = _assigned_names(node.orelse)
        if not a or a != b:
            return None
        targets = sorted(a)
        self.count += 1
        n = self.count
        ret = ast.Return(value=ast.Tuple(
            elts=[ast.Name(t, ast.Load()) for t in targets],
            ctx=ast.Load()))

        def mk(name, stmts):
            return ast.FunctionDef(
                name=name,
                args=ast.arguments(posonlyargs=[], args=[], kwonlyargs=[],
                                   kw_defaults=[], defaults=[]),
                body=list(stmts) + [ret], decorator_list=[])

        call = ast.Call(func=ast.Name("_jst_cond", ast.Load()),
                        args=[node.test,
                              ast.Name(f"__jst_true_{n}", ast.Load()),
                              ast.Name(f"__jst_false_{n}", ast.Load())],
                        keywords=[])
        assign = ast.Assign(
            targets=[ast.Tuple(
                elts=[ast.Name(t, ast.Store()) for t in targets],
                ctx=ast.Store())],
            value=call)
        self.converted += 1
        return [mk(f"__jst_true_{n}", node.body),
                mk(f"__jst_false_{n}", node.orelse), assign]

    def visit_FunctionDef(self, node):
        self.generic_visit(node)
        node.body = self._rewrite_body(node.body)
        return node


def convert_control_flow(fn: Callable) -> Callable:
    """Return ``fn`` with convertible tensor-``if`` patterns rewritten to
    paddle.cond dispatch; returns ``fn`` unchanged when no pattern
    converts or the source is unavailable (lambdas, C funcs, REPL)."""
    try:
        src = textwrap.dedent(inspect.getsource(fn))
        tree = ast.parse(src)
    except (OSError, TypeError, SyntaxError):
        return fn
    fdef = tree.body[0]
    if not isinstance(fdef, (ast.FunctionDef, ast.AsyncFunctionDef)):
        return fn
    fdef.decorator_list = []  # run undecorated (to_static wraps us)
    tr = _IfElseTransformer()
    tr.visit(tree)
    if not tr.converted:
        return fn
    ast.fix_missing_locations(tree)
    try:
        code = compile(tree, f"<dy2static {fn.__qualname__}>", "exec")
    except (ValueError, SyntaxError):  # pragma: no cover - defensive
        return fn
    glb = dict(fn.__globals__)
    glb["_jst_cond"] = _jst_cond
    # snapshot closure cells into globals (documented limitation: the
    # converted function sees decoration-time closure values)
    if fn.__closure__:
        try:
            glb.update({k: c.cell_contents
                        for k, c in zip(fn.__code__.co_freevars,
                                        fn.__closure__)})
        except ValueError:  # empty cell (helper defined later): skip
            return fn
    loc: dict = {}
    exec(code, glb, loc)
    new_fn = loc[fdef.name]
    functools.update_wrapper(new_fn, fn)
    return new_fn
