"""dy2static AST conversion: Python ``if`` on tensor predicates → cond.

Reference: python/paddle/fluid/dygraph/dygraph_to_static/ — the reference
ships 20+ AST transformers (ifelse_transformer.py,
loop_transformer.py, ...) rewriting user Python into ProgramDesc ops.
TPU-native stance: tracing handles everything EXCEPT genuine
data-dependent Python control flow, so only that needs rewriting.  This
module converts the two ubiquitous patterns:

1. ``if cond: <assignments>  else: <assignments>`` where both branches
   assign the same simple names → both branches become closures returning
   those names, dispatched through :func:`_jst_cond`;
2. ``if cond: return A`` followed by ``return B`` (and the two-armed
   ``if/else`` with lone returns) → ``return _jst_cond(cond, ...)``.

``_jst_cond`` preserves EAGER semantics exactly (a concrete/bool
predicate runs one branch in Python); only traced tensor predicates lower
to ``lax.cond``.  Anything the transformer cannot prove convertible is
left untouched — an unconverted tensor ``if`` still raises the loud
trace-time error pointing at paddle.cond (no silent mistracing).

Loops (reference: loop_transformer.py + break_continue_transformer.py):

3. ``while <test>: <assign-only body>`` → carried-variable closures
   dispatched through :func:`_jst_while` (Python loop when everything is
   concrete, ``paddle.while_loop``/``lax.while_loop`` when traced);
4. ``for i in range(...): <assign-only body>`` → the same, with a
   synthetic counter carry (``range`` over a traced tensor bound works
   after conversion — it would be a TypeError in plain Python);
5. exit-ifs — ``if c: [assignments;] break|continue|return <expr>`` —
   at ANY position in the loop body, any number of them
   (break_continue_transformer + return_transformer semantics):
   statements after an exit-if become the else-branch of a nested
   ``_jst_cond``, break/return ride a carried done-flag in the loop
   test, and an early ``return`` carries a value slot surfaced as
   ``if flag: return value`` after the loop (fused with the trailing
   return by a second if-pass);
6. calls to USER functions (bare names resolvable at conversion time)
   are routed through ``_jst_call`` (call_transformer parity): the
   callee is converted too, lazily and memoized, so helpers with tensor
   control flow work when invoked from a converted function.

Loop-carried variables follow the reference's rule: every assigned name
that is read by the loop test, read before it is written in the body, or
read after the loop must be BOUND before the loop.  Like the reference's
while_op, a traced loop is forward-only (XLA While has no reverse-mode
adjoint — taking gradients through a converted loop raises jax's loud
error rather than silently mis-differentiating).
"""
from __future__ import annotations

import ast
import functools
import inspect
import textwrap
from typing import Callable, List, Optional, Set

__all__ = ["convert_control_flow", "_jst_cond", "_jst_while"]


def _jst_cond(pred, true_fn, false_fn):
    """Runtime dispatch for converted ifs: Python branch when the
    predicate is concrete, paddle.cond when traced."""
    from ..core.tensor import Tensor
    import jax

    p = pred.data if isinstance(pred, Tensor) else pred
    if isinstance(p, jax.core.Tracer):
        from ..ops.control_flow import cond
        return cond(pred, true_fn, false_fn)
    return true_fn() if p else false_fn()


def _is_traced(v):
    import jax
    from ..core.tensor import Tensor
    d = v.data if isinstance(v, Tensor) else v
    return isinstance(d, jax.core.Tracer)


def _jst_bool(x):
    from ..core.tensor import Tensor
    return x.data if isinstance(x, Tensor) else x


def _jst_not(x):
    if _is_traced(x):
        import jax.numpy as jnp
        return jnp.logical_not(_jst_bool(x))
    return not _jst_bool(x)


def _jst_and(a, b):
    if _is_traced(a) or _is_traced(b):
        import jax.numpy as jnp
        return jnp.logical_and(_jst_bool(a), _jst_bool(b))
    return bool(_jst_bool(a)) and bool(_jst_bool(b))


def _jst_or(a, b):
    if _is_traced(a) or _is_traced(b):
        import jax.numpy as jnp
        return jnp.logical_or(_jst_bool(a), _jst_bool(b))
    return bool(_jst_bool(a)) or bool(_jst_bool(b))


def _jst_land(l_fn, r_fn):
    """reference: convert_operators.convert_logical_and — thunked so the
    right operand only evaluates when Python would evaluate it; traced
    operands lower to jnp.logical_and, concrete ones keep Python's
    `and` (including returning the operand, not a bool)."""
    a = l_fn()
    if _is_traced(a):
        import jax.numpy as jnp
        return jnp.logical_and(_jst_bool(a), _jst_bool(r_fn()))
    if not _jst_bool(a):
        return a
    b = r_fn()
    if _is_traced(b):
        import jax.numpy as jnp
        return jnp.logical_and(True, _jst_bool(b))
    return b


def _jst_lor(l_fn, r_fn):
    """convert_logical_or analog (see _jst_land)."""
    a = l_fn()
    if _is_traced(a):
        import jax.numpy as jnp
        return jnp.logical_or(_jst_bool(a), _jst_bool(r_fn()))
    if _jst_bool(a):
        return a
    b = r_fn()
    if _is_traced(b):
        import jax.numpy as jnp
        return jnp.logical_or(False, _jst_bool(b))
    return b


def _jst_lt(a, b):
    av, bv = _jst_bool(a), _jst_bool(b)
    return av < bv


def _jst_while(cond_fn, body_fn, init):
    """Runtime dispatch for converted loops: Python loop when all carried
    values and the predicate are concrete, paddle.while_loop (lax.While)
    when traced (loop_transformer.py's create_while_nodes)."""
    vals = tuple(init)
    c = cond_fn(*vals)
    if _is_traced(c) or any(_is_traced(v) for v in vals):
        from ..ops.control_flow import while_loop
        out = while_loop(cond_fn, lambda *a: tuple(body_fn(*a)),
                         list(vals))
        return tuple(out)
    while bool(_jst_bool(c)):
        vals = tuple(body_fn(*vals))
        c = cond_fn(*vals)
    return vals


def _loads(node) -> Set[str]:
    return {n.id for n in ast.walk(node)
            if isinstance(n, ast.Name) and isinstance(n.ctx, ast.Load)}


def _assigned_names(stmts: List[ast.stmt]):
    """Analyse a branch body of simple assignments.

    Returns ``(assigned, prebind)`` — the simple names the body assigns,
    and the subset it READS before assigning (incl. ``x = x + 1`` /
    ``x += 1``), which the branch closure receives as default-argument
    snapshots.  Returns ``None`` for anything non-trivial (attribute or
    subscript targets, nested control flow)."""
    all_assigned: Set[str] = set()
    for s in stmts:
        if isinstance(s, ast.Assign):
            for t in s.targets:
                if isinstance(t, ast.Name):
                    all_assigned.add(t.id)
                elif isinstance(t, ast.Tuple) and all(
                        isinstance(e, ast.Name) for e in t.elts):
                    all_assigned.update(e.id for e in t.elts)
                else:
                    return None
        elif isinstance(s, ast.AugAssign):
            if not isinstance(s.target, ast.Name):
                return None
            all_assigned.add(s.target.id)
        elif not isinstance(s, ast.Expr):
            return None
    assigned_so_far: Set[str] = set()
    prebind: Set[str] = set()
    for s in stmts:
        if isinstance(s, ast.Assign):
            prebind |= (_loads(s.value) & all_assigned) - assigned_so_far
            for t in s.targets:
                if isinstance(t, ast.Name):
                    assigned_so_far.add(t.id)
                else:
                    assigned_so_far.update(e.id for e in t.elts)
        elif isinstance(s, ast.AugAssign):
            if s.target.id not in assigned_so_far:
                prebind.add(s.target.id)
            prebind |= (_loads(s.value) & all_assigned) - assigned_so_far
            assigned_so_far.add(s.target.id)
        elif isinstance(s, ast.Expr):
            prebind |= (_loads(s) & all_assigned) - assigned_so_far
    return all_assigned, prebind


class _IfElseTransformer(ast.NodeTransformer):
    """reference: dygraph_to_static/ifelse_transformer.py."""

    def __init__(self):
        self.count = 0
        self.converted = 0

    # -- pattern 2: early return --------------------------------------------
    def _convert_return_pair(self, test, a_ret, b_ret):
        self.converted += 1
        t = ast.Lambda(
            args=ast.arguments(posonlyargs=[], args=[], kwonlyargs=[],
                               kw_defaults=[], defaults=[]),
            body=a_ret.value or ast.Constant(None))
        f = ast.Lambda(
            args=ast.arguments(posonlyargs=[], args=[], kwonlyargs=[],
                               kw_defaults=[], defaults=[]),
            body=b_ret.value or ast.Constant(None))
        call = ast.Call(func=ast.Name("_jst_cond", ast.Load()),
                        args=[test, t, f], keywords=[])
        return ast.Return(value=call)

    def _rewrite_body(self, body: List[ast.stmt],
                      bound: Set[str]) -> List[ast.stmt]:
        """Rewrite one statement list, tracking ``bound`` — names
        DEFINITELY bound at each point (needed to know whether a branch's
        read-before-write names can be prebound as argument defaults)."""
        out: List[ast.stmt] = []
        i = 0
        while i < len(body):
            s = body[i]
            if isinstance(s, ast.If):
                nxt = body[i + 1] if i + 1 < len(body) else None
                # `if c: return A` / `return B`  (tail follows the if)
                if (len(s.body) == 1 and isinstance(s.body[0], ast.Return)
                        and not s.orelse and isinstance(nxt, ast.Return)):
                    out.append(self._convert_return_pair(
                        s.test, s.body[0], nxt))
                    i += 2
                    continue
                # `if c: return A else: return B`
                if (len(s.body) == 1 and isinstance(s.body[0], ast.Return)
                        and len(s.orelse) == 1
                        and isinstance(s.orelse[0], ast.Return)):
                    out.append(self._convert_return_pair(
                        s.test, s.body[0], s.orelse[0]))
                    i += 1
                    continue
                conv = self._convert_assign_if(s, bound)
                if conv is not None:
                    out.extend(conv)
                    for t in conv:
                        if isinstance(t, ast.Assign):
                            bound |= _stores(t)
                    i += 1
                    continue
                # unconverted if: recurse; only names assigned in BOTH
                # arms are definitely bound after it
                s.body = self._rewrite_body(s.body, set(bound))
                s.orelse = self._rewrite_body(s.orelse, set(bound))
                bs = set()
                for t in s.body:
                    bs |= _stores(t)
                os_ = set()
                for t in s.orelse:
                    os_ |= _stores(t)
                bound |= (bs & os_) if s.orelse else set()
                out.append(s)
                i += 1
                continue
            if isinstance(s, (ast.While, ast.For)):
                # loop bodies: rewrite with a copy (their stores are only
                # conditionally bound afterwards)
                s.body = self._rewrite_body(s.body, set(bound))
                s.orelse = self._rewrite_body(s.orelse, set(bound))
                out.append(s)
                i += 1
                continue
            out.append(s)
            if isinstance(s, (ast.FunctionDef, ast.AsyncFunctionDef)):
                bound.add(s.name)     # not the names stored INSIDE it
            else:
                bound |= _stores(s)
            i += 1
        return out

    # -- pattern 1: both-branch assignments ---------------------------------
    def _convert_assign_if(self, node: ast.If,
                           bound: Set[str]) -> Optional[List[ast.stmt]]:
        ra = _assigned_names(node.body)
        if ra is None:
            return None
        if node.orelse:
            rb = _assigned_names(node.orelse)
            if rb is None:
                return None
        else:
            # single-arm if: synthesize an identity else — legal only
            # when every assigned name is provably bound before the if
            # (the else branch "assigns" each name to itself)
            rb = (ra[0], set(ra[0]))
        (a, pre_a), (b, pre_b) = ra, rb
        if not a or a != b:
            return None
        prebind = sorted(pre_a | pre_b)
        if any(p not in bound for p in prebind):
            # a read-before-write name not provably bound before the if:
            # the default-argument snapshot would evaluate eagerly and
            # raise where plain Python (branch not taken) would not
            return None
        targets = sorted(a)
        self.count += 1
        n = self.count
        ret = ast.Return(value=ast.Tuple(
            elts=[ast.Name(t, ast.Load()) for t in targets],
            ctx=ast.Load()))

        def mk(name, stmts):
            # names read before assignment arrive as default-argument
            # snapshots (`def t(s=s): s = s + x; ...`), sidestepping the
            # closure-local UnboundLocalError
            return ast.FunctionDef(
                name=name,
                args=ast.arguments(
                    posonlyargs=[],
                    args=[ast.arg(arg=p) for p in prebind],
                    kwonlyargs=[], kw_defaults=[],
                    defaults=[ast.Name(p, ast.Load()) for p in prebind]),
                body=list(stmts) + [ret], decorator_list=[])

        call = ast.Call(func=ast.Name("_jst_cond", ast.Load()),
                        args=[node.test,
                              ast.Name(f"__jst_true_{n}", ast.Load()),
                              ast.Name(f"__jst_false_{n}", ast.Load())],
                        keywords=[])
        assign = ast.Assign(
            targets=[ast.Tuple(
                elts=[ast.Name(t, ast.Store()) for t in targets],
                ctx=ast.Store())],
            value=call)
        self.converted += 1
        return [mk(f"__jst_true_{n}", node.body),
                mk(f"__jst_false_{n}", node.orelse), assign]

    def visit_FunctionDef(self, node):
        self.generic_visit(node)   # nested defs rewrite themselves
        args = node.args
        bound = {a.arg for a in (args.posonlyargs + args.args
                                 + args.kwonlyargs)}
        for extra in (args.vararg, args.kwarg):
            if extra is not None:
                bound.add(extra.arg)
        node.body = self._rewrite_body(node.body, bound)
        return node


def _stores(node) -> Set[str]:
    return {n.id for n in ast.walk(node)
            if isinstance(n, ast.Name) and isinstance(n.ctx, ast.Store)}


class _LoopTransformer(ast.NodeTransformer):
    """reference: loop_transformer.py + break_continue_transformer.py.

    Converts ``while``/``for-range`` whose bodies are assignment-only
    (after the if-transformer has run) into carried-closure ``_jst_while``
    dispatch, with a single leading ``if c: break/continue`` or trailing
    ``if c: break`` lowered to a carried done-flag + predicated updates.
    """

    _OK_STMTS = (ast.Assign, ast.AugAssign, ast.Expr, ast.FunctionDef)

    def __init__(self):
        self.count = 0
        self.converted = 0
        self._prior_stores: Set[str] = set()

    # -- analysis ---------------------------------------------------------
    def _body_ok(self, stmts) -> bool:
        for s in stmts:
            if self._exit_kind(s):
                # exit-ifs are handled by _emit's branch nesting; their
                # payloads are assignment-only by construction
                continue
            if not isinstance(s, self._OK_STMTS):
                return False
            if isinstance(s, ast.Expr) and not isinstance(
                    s.value, ast.Constant):
                # converted print/assert statements are trace-safe
                # (jax.debug.print / debug.callback work under lax.while)
                if (isinstance(s.value, ast.Call)
                        and isinstance(s.value.func, ast.Name)
                        and s.value.func.id in ("_jst_print",
                                                "_jst_assert")):
                    continue
                # any other bare expression is almost always a
                # side-effecting call (list.append, dict update):
                # running it inside a traced closure would leak tracers
                # into Python state — leave such loops to plain Python
                return False
            if isinstance(s, ast.Assign):
                for t in s.targets:
                    if isinstance(t, ast.Name):
                        continue
                    if isinstance(t, ast.Tuple) and all(
                            isinstance(e, ast.Name) for e in t.elts):
                        continue
                    return False
            if isinstance(s, ast.AugAssign) and not isinstance(
                    s.target, ast.Name):
                return False
            # no hidden control flow inside expressions — but do NOT
            # descend into nested FunctionDefs: the if-transformer's
            # generated branch closures legitimately contain Return
            stack = list(ast.iter_child_nodes(s)) if not isinstance(
                s, ast.FunctionDef) else []
            while stack:
                n = stack.pop()
                if isinstance(n, (ast.Break, ast.Continue, ast.Return,
                                  ast.While, ast.For, ast.If, ast.Yield,
                                  ast.YieldFrom, ast.Await)):
                    return False
                if not isinstance(n, (ast.FunctionDef,
                                      ast.AsyncFunctionDef, ast.Lambda)):
                    stack.extend(ast.iter_child_nodes(n))
        return True

    @staticmethod
    def _exit_kind(s):
        """'break' / 'continue' / 'return' when ``s`` is an exit-if —
        ``if pred: [assignments...;] break|continue|return <expr>`` with
        no else — otherwise None (reference:
        break_continue_transformer.py, return_transformer.py)."""
        if not (isinstance(s, ast.If) and not s.orelse and s.body):
            return None
        *payload, last = s.body
        if not all(isinstance(q, (ast.Assign, ast.AugAssign))
                   for q in payload):
            return None
        if isinstance(last, ast.Break):
            return "break"
        if isinstance(last, ast.Continue):
            return "continue"
        if isinstance(last, ast.Return) and last.value is not None:
            return "return"
        return None

    def _carried(self, test, body_stmts, after_loads):
        """Loop-carried names: assigned in body AND (read by the test,
        read before written in the body — exit-if predicates and
        payloads included — or read after the loop)."""
        assigned: Set[str] = set()
        for s in body_stmts:
            assigned |= _stores(s)
        live: Set[str] = set()
        written: Set[str] = set()
        for s in body_stmts:
            if isinstance(s, ast.Assign):
                live |= (_loads(s.value) & assigned) - written
                for t in s.targets:
                    written |= _stores(t)
            elif isinstance(s, ast.AugAssign):
                live.add(s.target.id)
                live |= (_loads(s.value) & assigned) - written
                written.add(s.target.id)
            else:
                # exit-ifs land here: their predicate and payload reads
                # count as live (they re-evaluate every iteration), and
                # their conditional stores never count as written
                live |= (_loads(s) & assigned) - written
        if test is not None:
            live |= _loads(test) & assigned
        live |= after_loads & assigned
        # only live names ride in the carry (they must be bound before the
        # loop, the reference's loop-var rule); write-before-read temps
        # stay body-local
        return sorted(live)

    # -- codegen ----------------------------------------------------------
    def _emit(self, stmts, state, k, ind, uid):
        """Emit loop-body source for ``stmts`` with exit-ifs at ANY
        position (reference: break_continue_transformer.py /
        return_transformer.py generality).  Statements after an exit-if
        become the ELSE branch of a ``_jst_cond`` over the exit
        predicate — nesting reproduces Python's 'skip the rest of this
        iteration' semantics exactly, for eager (short-circuit) and
        traced (lax.cond) alike.  ``state`` names are threaded through
        branch closures via default-arg snapshots; plain temps flow by
        lexical capture."""
        lines = []
        j = next((i for i, s in enumerate(stmts)
                  if self._exit_kind(s)), None)
        for s in stmts[:len(stmts) if j is None else j]:
            for ln in ast.unparse(ast.fix_missing_locations(s)).splitlines():
                lines.append(ind + ln)
        if j is None:
            return lines
        ex = stmts[j]
        kind = self._exit_kind(ex)
        d = uid[0]
        uid[0] += 1
        p = f"__jst_p_{k}_{d}"
        names = ", ".join(state)
        tup = f"({names},)" if len(state) == 1 else f"({names})"
        defaults = ", ".join(f"{n}={n}" for n in state)
        lines.append(f"{ind}{p} = ({ast.unparse(ex.test)})")
        lines.append(f"{ind}def __jst_then_{k}_{d}({defaults}):")
        for s in ex.body[:-1]:
            for ln in ast.unparse(s).splitlines():
                lines.append(f"{ind}    {ln}")
        if kind in ("break", "return"):
            lines.append(f"{ind}    __jst_done_{k} = True")
        if kind == "return":
            lines.append(f"{ind}    __jst_rf_{k} = True")
            rv = ast.unparse(ex.body[-1].value)
            lines.append(f"{ind}    __jst_rv_{k} = ({rv})")
        lines.append(f"{ind}    return {tup}")
        lines.append(f"{ind}def __jst_else_{k}_{d}({defaults}):")
        rest = self._emit(stmts[j + 1:], state, k, ind + "    ", uid)
        lines.extend(rest)
        lines.append(f"{ind}    return {tup}")
        lines.append(f"{ind}{tup} = _jst_cond({p}, __jst_then_{k}_{d}, "
                     f"__jst_else_{k}_{d})")
        return lines

    # -- conversion -------------------------------------------------------
    def _convert(self, node, after_loads, tail_is_return=False):
        is_for = isinstance(node, ast.For)
        if node.orelse:
            return None
        body = list(node.body)
        if not self._body_ok(body):
            return None
        kinds = [self._exit_kind(s) for s in body]
        has_break = "break" in kinds
        has_return = "return" in kinds
        ret_exprs = [s.body[-1].value for s, kd in zip(body, kinds)
                     if kd == "return"]
        if has_return and not tail_is_return:
            # the surfaced `if flag: return value` is only fusable when
            # the loop is immediately followed by the function's
            # trailing return — otherwise a traced flag would hit a
            # plain Python if; leave the loop to eager/loud handling
            return None

        if is_for:
            # for <name> in range(...)
            if not (isinstance(node.target, ast.Name)
                    and isinstance(node.iter, ast.Call)
                    and isinstance(node.iter.func, ast.Name)
                    and node.iter.func.id == "range"
                    and 1 <= len(node.iter.args) <= 3
                    and not node.iter.keywords):
                return None
            ivar = node.target.id
            if ivar in after_loads:
                # python leaves i at the LAST value; our carry leaves it
                # one step past — bail rather than deviate
                return None
            ra = node.iter.args
            start = ast.unparse(ra[0]) if len(ra) >= 2 else "0"
            stop = ast.unparse(ra[1] if len(ra) >= 2 else ra[0])
            if len(ra) == 3:
                if not (isinstance(ra[2], ast.Constant)
                        and isinstance(ra[2].value, int)
                        and ra[2].value > 0):
                    return None
                step = str(ra[2].value)
            else:
                step = "1"
            test_src = None
        else:
            test_src = ast.unparse(node.test)

        carried = self._carried(node.test if not is_for else None, body,
                                after_loads)
        if is_for and ivar in carried:
            carried.remove(ivar)
        if not carried:
            return None

        assigned: Set[str] = set()
        for s in body:
            assigned |= _stores(s)
        # names whose ONLY body assignment sits inside an exit-if payload
        # but that ride the carry (read after the loop) need a PRE-loop
        # binding for the carry init — without a visible one the init
        # tuple would raise UnboundLocalError where eager code worked;
        # bail (prior_stores: names assigned earlier in the enclosing
        # block, plus the function's parameters)
        non_exit_stores: Set[str] = set()
        for s, kd in zip(body, kinds):
            if kd is None:
                non_exit_stores |= _stores(s)
        payload_only = (assigned - non_exit_stores) & set(carried)
        if payload_only - self._prior_stores:
            return None
        for e in ret_exprs:
            # the rv carry init evaluates the return expr PRE-loop: only
            # carried body names (pre-bound by the loop-var rule) and the
            # enclosing scope are available there — a body-local temp or
            # the loop index would NameError
            loads = _loads(e)
            if loads & (assigned - set(carried)):
                return None
            if is_for and ivar in loads:
                return None

        self.count += 1
        k = self.count
        done = f"__jst_done_{k}"
        ctr = f"__jst_i_{k}"
        needs_done = has_break or has_return

        state = list(carried)
        if needs_done:
            state.append(done)
        if has_return:
            state += [f"__jst_rf_{k}", f"__jst_rv_{k}"]
        args = ([ctr] if is_for else []) + state
        argl = ", ".join(args)
        atup = f"({argl},)" if len(args) == 1 else f"({argl})"

        lines = []
        if is_for:
            lines.append(f"{ctr} = {start}")
            lines.append(f"__jst_n_{k} = {stop}")
        if needs_done:
            lines.append(f"{done} = False")
        if has_return:
            # the rv carry needs a shape/dtype-compatible init: the
            # return expr evaluated with PRE-loop values (verified above
            # to read only carried — hence pre-bound — or outer names);
            # never observed unless the flag is set
            lines.append(f"__jst_rf_{k} = False")
            lines.append(f"__jst_rv_{k} = ({ast.unparse(ret_exprs[0])})")
        # cond
        base_test = (f"_jst_lt({ctr}, __jst_n_{k})" if is_for
                     else f"({test_src})")
        cond_ret = (f"_jst_and({base_test}, _jst_not({done}))"
                    if needs_done else base_test)
        lines.append(f"def __jst_cond_{k}({argl}):")
        lines.append(f"    return {cond_ret}")
        # body: exit-ifs anywhere via _jst_cond nesting (_emit)
        lines.append(f"def __jst_body_{k}({argl}):")
        if is_for:
            lines.append(f"    {node.target.id} = {ctr}")
        lines.extend(self._emit(body, state, k, "    ", [0]))
        if is_for:
            lines.append(f"    {ctr} = {ctr} + {step}")
        lines.append(f"    return {atup}")
        # dispatch
        lines.append(f"{atup} = _jst_while(__jst_cond_{k}, "
                     f"__jst_body_{k}, {atup})")
        if has_return:
            # early return surfaces after the loop; the second if-pass
            # (convert_control_flow) fuses this with the function's
            # trailing return for traced predicates
            lines.append(f"if __jst_rf_{k}:")
            lines.append(f"    return __jst_rv_{k}")
        src = "\n".join(lines)
        try:
            new_stmts = ast.parse(src).body
        except SyntaxError:  # pragma: no cover - defensive
            return None
        self.converted += 1
        return new_stmts

    def _rewrite(self, stmts, extra_after: Optional[Set[str]] = None,
                 prior: Optional[Set[str]] = None):
        out = []
        prior_stores: Set[str] = set(prior or ())
        for i, s in enumerate(stmts):
            if isinstance(s, (ast.While, ast.For)):
                after_loads: Set[str] = set(extra_after or ())
                for t in stmts[i + 1:]:
                    after_loads |= _loads(t)
                rest = stmts[i + 1:]
                tail_is_return = (len(rest) == 1
                                  and isinstance(rest[0], ast.Return)
                                  and rest[0].value is not None)
                self._prior_stores = prior_stores
                conv = self._convert(s, after_loads,
                                     tail_is_return=tail_is_return)
                if conv is not None:
                    out.extend(conv)
                    prior_stores |= _stores(s)
                    continue
            prior_stores |= _stores(s)
            out.append(s)
        return out

    def visit_FunctionDef(self, node):
        self.generic_visit(node)
        params = {a.arg for a in (node.args.args
                                  + node.args.posonlyargs
                                  + node.args.kwonlyargs)}
        node.body = self._rewrite(node.body, prior=params)
        return node

    def visit_While(self, node):
        # convert inner loops first; a converted inner loop inside an
        # unconverted (Python) outer loop is still a win
        self.generic_visit(node)
        node.body = self._rewrite(node.body,
                                  extra_after=_loads(node))
        return node

    def visit_For(self, node):
        self.generic_visit(node)
        node.body = self._rewrite(node.body,
                                  extra_after=_loads(node))
        return node


_CALLBACKS_OK = None


def _callbacks_supported() -> bool:
    """Host callbacks (jax.debug.print/callback) are UNIMPLEMENTED on
    the axon tunnel backend (its PJRT reports platform 'tpu' but
    platform_version names axon); real TPUs and CPU support them."""
    global _CALLBACKS_OK
    if _CALLBACKS_OK is None:
        import jax
        try:
            ver = getattr(jax.devices()[0].client, "platform_version", "")
        except Exception:  # pragma: no cover - uninitialised backend
            ver = ""
        _CALLBACKS_OK = "axon" not in ver
    return _CALLBACKS_OK


def _jst_print(*args, **kw):
    """reference: print_transformer.py → Print op.  Traced tensors print
    their RUNTIME value via jax.debug.print (a trace-time builtin print
    would show tracer objects once); concrete values use builtin print.
    ``sep`` is honored under trace; ``end``/``file`` (and backends
    without host callbacks, e.g. the axon tunnel) fall back to the
    trace-time builtin print."""
    traced = any(_is_traced(a) for a in args)
    if (traced and _callbacks_supported()
            and not (set(kw) - {"sep"})):
        import jax
        sep = kw.get("sep", " ")
        fmt = sep.join("{}" for _ in args)
        jax.debug.print(fmt, *[_jst_bool(a) if _is_traced(a) else a
                               for a in args])
        return None
    return print(*args, **kw)


def _jst_cast(x, ty):
    """reference: cast_transformer.py → convert_var_dtype.  Traced
    tensors lower to astype (int→int64, float→float32, bool→bool);
    concrete values keep exact Python builtin semantics."""
    if _is_traced(x):
        from ..core.tensor import Tensor
        t = x if isinstance(x, Tensor) else Tensor(x)
        return t.astype({"bool": "bool", "int": "int64",
                         "float": "float32"}[ty])
    v = _jst_bool(x)  # unwrap Tensor -> array for the builtin
    return {"bool": bool, "int": int, "float": float}[ty](v)


def _jst_assert(test, msg_fn=None):
    """reference: assert_transformer.py → layers.Assert.  Concrete
    predicates keep Python assert semantics (``msg_fn`` is a thunk,
    evaluated ONLY on failure, like Python's lazy assert message);
    traced predicates check at RUNTIME through jax.debug.callback.  On
    backends without host callbacks (axon tunnel) the traced path falls
    back to ``bool(test)`` — the loud guided trace error, exactly the
    pre-conversion behavior."""
    def _msg():
        return (msg_fn() if callable(msg_fn) else msg_fn) \
            if msg_fn is not None else "dy2static assert failed"

    if not _is_traced(test):
        if not _jst_bool(test):
            raise AssertionError(_msg())
        return None
    if not _callbacks_supported():
        if not bool(test):  # raises the guided tensor-bool error
            raise AssertionError(_msg())  # pragma: no cover
        return None
    import jax

    def _check(ok):
        if not ok:
            raise AssertionError(_msg())

    jax.debug.callback(_check, _jst_bool(test))
    return None


class _LogicalTransformer(ast.NodeTransformer):
    """reference: logical_transformer.py — `a and b` / `a or b` / `not a`
    on tensors would hit the loud bool() trace error; rewrite them to
    thunked converters that keep exact Python short-circuit semantics
    for concrete values and lower to jnp logical ops when traced."""

    def __init__(self):
        self.converted = 0

    @staticmethod
    def _thunk(expr):
        return ast.Lambda(
            args=ast.arguments(posonlyargs=[], args=[], kwonlyargs=[],
                               kw_defaults=[], defaults=[]),
            body=expr)

    def visit_BoolOp(self, node):
        self.generic_visit(node)
        name = "_jst_land" if isinstance(node.op, ast.And) else "_jst_lor"
        out = node.values[0]
        for rhs in node.values[1:]:
            out = ast.Call(func=ast.Name(id=name, ctx=ast.Load()),
                           args=[self._thunk(out), self._thunk(rhs)],
                           keywords=[])
        self.converted += 1
        return out

    def visit_UnaryOp(self, node):
        self.generic_visit(node)
        if isinstance(node.op, ast.Not):
            self.converted += 1
            return ast.Call(func=ast.Name(id="_jst_not", ctx=ast.Load()),
                            args=[node.operand], keywords=[])
        return node


class _BuiltinTransformer(ast.NodeTransformer):
    """reference: print_transformer.py + cast_transformer.py +
    assert_transformer.py — `print(...)`, `int/float/bool(x)`, and
    `assert` route through runtime converters that preserve eager
    semantics and lower tensors under trace.

    Names the function SHADOWS (params, local assignments, or module
    globals/closure bindings) are left untouched — rewriting them would
    silently hijack user callables."""

    _CASTS = {"int", "float", "bool"}

    def __init__(self, shadowed=frozenset()):
        self.converted = 0
        self._shadowed = shadowed

    def visit_Call(self, node):
        self.generic_visit(node)
        if not isinstance(node.func, ast.Name):
            return node
        name = node.func.id
        if name in self._shadowed:
            return node
        if name == "print":
            node.func = ast.Name(id="_jst_print", ctx=ast.Load())
            self.converted += 1
        elif (name in self._CASTS and len(node.args) == 1
                and not node.keywords):
            node = ast.Call(
                func=ast.Name(id="_jst_cast", ctx=ast.Load()),
                args=[node.args[0], ast.Constant(value=name)],
                keywords=[])
            self.converted += 1
        return node

    def visit_Assert(self, node):
        self.generic_visit(node)
        args = [node.test]
        if node.msg is not None:
            # lazy message thunk: Python evaluates the msg expression
            # only when the assert FAILS
            args.append(ast.Lambda(
                args=ast.arguments(posonlyargs=[], args=[],
                                   kwonlyargs=[], kw_defaults=[],
                                   defaults=[]),
                body=node.msg))
        self.converted += 1
        return ast.Expr(value=ast.Call(
            func=ast.Name(id="_jst_assert", ctx=ast.Load()),
            args=args, keywords=[]))


import weakref

# weak keys: dynamically created helpers (per-step closures, factory
# products) must stay collectable — a strong cache would pin every
# function object (and its closed-over arrays) for the process lifetime
_CALL_CACHE: "weakref.WeakKeyDictionary" = weakref.WeakKeyDictionary()
_SKIP_ROOTS = {"paddle_tpu", "jax", "jaxlib", "numpy", "np", "builtins",
               "math", "functools", "itertools", "flax", "optax", "torch"}


def _convertible_user_fn(f) -> bool:
    import types
    if not isinstance(f, types.FunctionType):
        return False
    mod = (getattr(f, "__module__", "") or "").split(".")[0]
    return mod not in _SKIP_ROOTS


def _jst_call(f):
    """Runtime hook for converted call sites (reference:
    call_transformer.py convert_call): user helper functions get
    control-flow conversion too, lazily and memoized; anything else
    (builtins, library fns, shadowed names) passes through untouched."""
    if not _convertible_user_fn(f):
        return f
    conv = _CALL_CACHE.get(f)
    if conv is None:
        conv = convert_control_flow(f)
        _CALL_CACHE[f] = conv
    return conv


class _CallTransformer(ast.NodeTransformer):
    """reference: call_transformer.py — wrap bare-name calls that resolve
    (at conversion time) to plain user functions in ``_jst_call`` so
    tensor control flow inside helpers converts as well."""

    def __init__(self, resolver):
        self.converted = 0
        self._resolve = resolver

    def visit_Call(self, node):
        self.generic_visit(node)
        if (isinstance(node.func, ast.Name)
                and not node.func.id.startswith(("_jst", "__jst"))
                and self._resolve(node.func.id)):
            node.func = ast.Call(
                func=ast.Name(id="_jst_call", ctx=ast.Load()),
                args=[node.func], keywords=[])
            self.converted += 1
        return node


def _shadowed_builtins(fdef, env0) -> Set[str]:
    """Names the function shadows (params, local stores, module/closure
    bindings of print/int/float/bool) — the builtin transformer must not
    rewrite calls through them."""
    shadowed = {a.arg for a in (fdef.args.args + fdef.args.posonlyargs
                                + fdef.args.kwonlyargs)}
    shadowed |= {n.id for n in ast.walk(fdef)
                 if isinstance(n, ast.Name)
                 and isinstance(n.ctx, ast.Store)}
    shadowed |= {n for n in ("print", "int", "float", "bool")
                 if env0.get(n) is not None}
    return shadowed


def _decoration_env(fn) -> dict:
    """Globals + snapshot of closure cells — the name environment both
    the builtin-shadow scan and the call transformer resolve against."""
    env0 = dict(fn.__globals__)
    if fn.__closure__:
        try:
            env0.update({k: c.cell_contents
                         for k, c in zip(fn.__code__.co_freevars,
                                         fn.__closure__)})
        except ValueError:
            pass
    return env0


def _transform_tree(fn):
    """Parse ``fn``'s source and run the full transformer pipeline
    WITHOUT compiling or executing anything.

    Returns ``(tree, fdef, counters)`` — the mutated module tree, its
    FunctionDef, and per-transformer conversion counts — or ``None``
    when the source is unavailable / not a plain function def.  Shared
    by :func:`convert_control_flow` (which compiles the result) and
    jit/lint.py (which diffs the tree against the original to find what
    stayed unconverted)."""
    try:
        src = textwrap.dedent(inspect.getsource(fn))
        tree = ast.parse(src)
    except (OSError, TypeError, SyntaxError):
        return None
    fdef = tree.body[0]
    if not isinstance(fdef, (ast.FunctionDef, ast.AsyncFunctionDef)):
        return None
    fdef.decorator_list = []  # run undecorated (to_static wraps us)
    tr = _IfElseTransformer()
    tr.visit(tree)
    # print/cast/assert rewrite BEFORE loops so their statement forms
    # (whitelisted in _body_ok) don't block loop conversion.  Shadowed
    # builtin names (params, local stores, module/closure bindings)
    # stay untouched.
    env0 = _decoration_env(fn)
    bt = _BuiltinTransformer(
        shadowed=frozenset(_shadowed_builtins(fdef, env0)))
    bt.visit(tree)
    lg = _LogicalTransformer()
    lg.visit(tree)
    lt = _LoopTransformer()
    lt.visit(tree)
    tr2 = _IfElseTransformer()
    if lt.converted:
        # second if-pass: fuses loop-generated `if __jst_rf: return rv`
        # early-return surfacing with the function's trailing return
        tr2.visit(tree)

    # nested calls (resolved against the same decoration-time env the
    # builtin-shadow scan used)
    ct = _CallTransformer(
        lambda name: _convertible_user_fn(env0.get(name)))
    ct.visit(tree)
    counters = {"ifelse": tr.converted + tr2.converted,
                "loops": lt.converted, "builtins": bt.converted,
                "logical": lg.converted, "calls": ct.converted}
    return tree, fdef, counters


def convert_control_flow(fn: Callable) -> Callable:
    """Return ``fn`` with convertible tensor-``if`` patterns rewritten to
    paddle.cond dispatch; returns ``fn`` unchanged when no pattern
    converts or the source is unavailable (lambdas, C funcs, REPL)."""
    res = _transform_tree(fn)
    if res is None:
        return fn
    tree, fdef, counters = res
    # builtin/logical-only conversions recompile ONLY closure-free
    # functions: the recompile snapshots closure cells, and freezing
    # live closures just to route a print or an `and` is a bad trade
    # (review-confirmed regression)
    soft = ((counters["builtins"] + counters["logical"])
            if not fn.__closure__ else 0)
    if not (counters["ifelse"] or counters["loops"]
            or counters["calls"] or soft):
        return fn
    ast.fix_missing_locations(tree)
    try:
        code = compile(tree, f"<dy2static {fn.__qualname__}>", "exec")
    except (ValueError, SyntaxError):  # pragma: no cover - defensive
        return fn
    glb = dict(fn.__globals__)
    glb.update(_jst_cond=_jst_cond, _jst_while=_jst_while,
               _jst_and=_jst_and,
               _jst_or=_jst_or, _jst_not=_jst_not, _jst_lt=_jst_lt,
               _jst_call=_jst_call, _jst_print=_jst_print,
               _jst_cast=_jst_cast, _jst_assert=_jst_assert,
               _jst_land=_jst_land, _jst_lor=_jst_lor)
    # snapshot closure cells into globals (documented limitation: the
    # converted function sees decoration-time closure values)
    if fn.__closure__:
        try:
            glb.update({k: c.cell_contents
                        for k, c in zip(fn.__code__.co_freevars,
                                        fn.__closure__)})
        except ValueError:  # empty cell (helper defined later): skip
            return fn
    loc: dict = {}
    exec(code, glb, loc)
    new_fn = loc[fdef.name]
    functools.update_wrapper(new_fn, fn)
    return new_fn
