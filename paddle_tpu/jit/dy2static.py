"""dy2static AST conversion: Python ``if`` on tensor predicates → cond.

Reference: python/paddle/fluid/dygraph/dygraph_to_static/ — the reference
ships 20+ AST transformers (ifelse_transformer.py,
loop_transformer.py, ...) rewriting user Python into ProgramDesc ops.
TPU-native stance: tracing handles everything EXCEPT genuine
data-dependent Python control flow, so only that needs rewriting.  This
module converts the two ubiquitous patterns:

1. ``if cond: <assignments>  else: <assignments>`` where both branches
   assign the same simple names → both branches become closures returning
   those names, dispatched through :func:`_jst_cond`;
2. ``if cond: return A`` followed by ``return B`` (and the two-armed
   ``if/else`` with lone returns) → ``return _jst_cond(cond, ...)``.

``_jst_cond`` preserves EAGER semantics exactly (a concrete/bool
predicate runs one branch in Python); only traced tensor predicates lower
to ``lax.cond``.  Anything the transformer cannot prove convertible is
left untouched — an unconverted tensor ``if`` still raises the loud
trace-time error pointing at paddle.cond (no silent mistracing).

Loops (reference: loop_transformer.py + break_continue_transformer.py):

3. ``while <test>: <assign-only body>`` → carried-variable closures
   dispatched through :func:`_jst_while` (Python loop when everything is
   concrete, ``paddle.while_loop``/``lax.while_loop`` when traced);
4. ``for i in range(...): <assign-only body>`` → the same, with a
   synthetic counter carry (``range`` over a traced tensor bound works
   after conversion — it would be a TypeError in plain Python);
5. a single ``if c: break`` / ``if c: continue`` as the first statement,
   or ``if c: break`` as the last statement of the loop body → a carried
   done-flag and predicated (select) state updates, the
   break_continue_transformer's early-exit semantics.

Loop-carried variables follow the reference's rule: every assigned name
that is read by the loop test, read before it is written in the body, or
read after the loop must be BOUND before the loop.  Like the reference's
while_op, a traced loop is forward-only (XLA While has no reverse-mode
adjoint — taking gradients through a converted loop raises jax's loud
error rather than silently mis-differentiating).
"""
from __future__ import annotations

import ast
import functools
import inspect
import textwrap
from typing import Callable, List, Optional, Set

__all__ = ["convert_control_flow", "_jst_cond", "_jst_while"]


def _jst_cond(pred, true_fn, false_fn):
    """Runtime dispatch for converted ifs: Python branch when the
    predicate is concrete, paddle.cond when traced."""
    from ..core.tensor import Tensor
    import jax

    p = pred.data if isinstance(pred, Tensor) else pred
    if isinstance(p, jax.core.Tracer):
        from ..ops.control_flow import cond
        return cond(pred, true_fn, false_fn)
    return true_fn() if p else false_fn()


def _is_traced(v):
    import jax
    from ..core.tensor import Tensor
    d = v.data if isinstance(v, Tensor) else v
    return isinstance(d, jax.core.Tracer)


def _jst_bool(x):
    from ..core.tensor import Tensor
    return x.data if isinstance(x, Tensor) else x


def _jst_not(x):
    if _is_traced(x):
        import jax.numpy as jnp
        return jnp.logical_not(_jst_bool(x))
    return not _jst_bool(x)


def _jst_and(a, b):
    if _is_traced(a) or _is_traced(b):
        import jax.numpy as jnp
        return jnp.logical_and(_jst_bool(a), _jst_bool(b))
    return bool(_jst_bool(a)) and bool(_jst_bool(b))


def _jst_or(a, b):
    if _is_traced(a) or _is_traced(b):
        import jax.numpy as jnp
        return jnp.logical_or(_jst_bool(a), _jst_bool(b))
    return bool(_jst_bool(a)) or bool(_jst_bool(b))


def _jst_lt(a, b):
    av, bv = _jst_bool(a), _jst_bool(b)
    return av < bv


def _jst_select(pred, old_vals, new_fn):
    """Predicated state update for converted break/continue: keep
    ``old_vals`` where ``pred`` holds, else the values ``new_fn``
    computes.  Eager concrete predicate short-circuits in Python."""
    if not _is_traced(pred):
        return tuple(old_vals) if bool(_jst_bool(pred)) else tuple(
            new_fn())
    import jax.numpy as jnp

    from ..core.tensor import Tensor
    p = _jst_bool(pred)
    new_vals = tuple(new_fn())
    out = []
    for o, n in zip(old_vals, new_vals):
        od = o.data if isinstance(o, Tensor) else o
        nd = n.data if isinstance(n, Tensor) else n
        sel = jnp.where(p, od, nd)
        out.append(Tensor(sel) if isinstance(o, Tensor) or
                   isinstance(n, Tensor) else sel)
    return tuple(out)


def _jst_while(cond_fn, body_fn, init):
    """Runtime dispatch for converted loops: Python loop when all carried
    values and the predicate are concrete, paddle.while_loop (lax.While)
    when traced (loop_transformer.py's create_while_nodes)."""
    vals = tuple(init)
    c = cond_fn(*vals)
    if _is_traced(c) or any(_is_traced(v) for v in vals):
        from ..ops.control_flow import while_loop
        out = while_loop(cond_fn, lambda *a: tuple(body_fn(*a)),
                         list(vals))
        return tuple(out)
    while bool(_jst_bool(c)):
        vals = tuple(body_fn(*vals))
        c = cond_fn(*vals)
    return vals


def _loads(node) -> Set[str]:
    return {n.id for n in ast.walk(node)
            if isinstance(n, ast.Name) and isinstance(n.ctx, ast.Load)}


def _assigned_names(stmts: List[ast.stmt]):
    """Analyse a branch body of simple assignments.

    Returns ``(assigned, prebind)`` — the simple names the body assigns,
    and the subset it READS before assigning (incl. ``x = x + 1`` /
    ``x += 1``), which the branch closure receives as default-argument
    snapshots.  Returns ``None`` for anything non-trivial (attribute or
    subscript targets, nested control flow)."""
    all_assigned: Set[str] = set()
    for s in stmts:
        if isinstance(s, ast.Assign):
            for t in s.targets:
                if isinstance(t, ast.Name):
                    all_assigned.add(t.id)
                elif isinstance(t, ast.Tuple) and all(
                        isinstance(e, ast.Name) for e in t.elts):
                    all_assigned.update(e.id for e in t.elts)
                else:
                    return None
        elif isinstance(s, ast.AugAssign):
            if not isinstance(s.target, ast.Name):
                return None
            all_assigned.add(s.target.id)
        elif not isinstance(s, ast.Expr):
            return None
    assigned_so_far: Set[str] = set()
    prebind: Set[str] = set()
    for s in stmts:
        if isinstance(s, ast.Assign):
            prebind |= (_loads(s.value) & all_assigned) - assigned_so_far
            for t in s.targets:
                if isinstance(t, ast.Name):
                    assigned_so_far.add(t.id)
                else:
                    assigned_so_far.update(e.id for e in t.elts)
        elif isinstance(s, ast.AugAssign):
            if s.target.id not in assigned_so_far:
                prebind.add(s.target.id)
            prebind |= (_loads(s.value) & all_assigned) - assigned_so_far
            assigned_so_far.add(s.target.id)
        elif isinstance(s, ast.Expr):
            prebind |= (_loads(s) & all_assigned) - assigned_so_far
    return all_assigned, prebind


class _IfElseTransformer(ast.NodeTransformer):
    """reference: dygraph_to_static/ifelse_transformer.py."""

    def __init__(self):
        self.count = 0
        self.converted = 0

    # -- pattern 2: early return --------------------------------------------
    def _convert_return_pair(self, test, a_ret, b_ret):
        self.converted += 1
        t = ast.Lambda(
            args=ast.arguments(posonlyargs=[], args=[], kwonlyargs=[],
                               kw_defaults=[], defaults=[]),
            body=a_ret.value or ast.Constant(None))
        f = ast.Lambda(
            args=ast.arguments(posonlyargs=[], args=[], kwonlyargs=[],
                               kw_defaults=[], defaults=[]),
            body=b_ret.value or ast.Constant(None))
        call = ast.Call(func=ast.Name("_jst_cond", ast.Load()),
                        args=[test, t, f], keywords=[])
        return ast.Return(value=call)

    def _rewrite_body(self, body: List[ast.stmt],
                      bound: Set[str]) -> List[ast.stmt]:
        """Rewrite one statement list, tracking ``bound`` — names
        DEFINITELY bound at each point (needed to know whether a branch's
        read-before-write names can be prebound as argument defaults)."""
        out: List[ast.stmt] = []
        i = 0
        while i < len(body):
            s = body[i]
            if isinstance(s, ast.If):
                nxt = body[i + 1] if i + 1 < len(body) else None
                # `if c: return A` / `return B`  (tail follows the if)
                if (len(s.body) == 1 and isinstance(s.body[0], ast.Return)
                        and not s.orelse and isinstance(nxt, ast.Return)):
                    out.append(self._convert_return_pair(
                        s.test, s.body[0], nxt))
                    i += 2
                    continue
                # `if c: return A else: return B`
                if (len(s.body) == 1 and isinstance(s.body[0], ast.Return)
                        and len(s.orelse) == 1
                        and isinstance(s.orelse[0], ast.Return)):
                    out.append(self._convert_return_pair(
                        s.test, s.body[0], s.orelse[0]))
                    i += 1
                    continue
                conv = self._convert_assign_if(s, bound)
                if conv is not None:
                    out.extend(conv)
                    for t in conv:
                        if isinstance(t, ast.Assign):
                            bound |= _stores(t)
                    i += 1
                    continue
                # unconverted if: recurse; only names assigned in BOTH
                # arms are definitely bound after it
                s.body = self._rewrite_body(s.body, set(bound))
                s.orelse = self._rewrite_body(s.orelse, set(bound))
                bs = set()
                for t in s.body:
                    bs |= _stores(t)
                os_ = set()
                for t in s.orelse:
                    os_ |= _stores(t)
                bound |= (bs & os_) if s.orelse else set()
                out.append(s)
                i += 1
                continue
            if isinstance(s, (ast.While, ast.For)):
                # loop bodies: rewrite with a copy (their stores are only
                # conditionally bound afterwards)
                s.body = self._rewrite_body(s.body, set(bound))
                s.orelse = self._rewrite_body(s.orelse, set(bound))
                out.append(s)
                i += 1
                continue
            out.append(s)
            if isinstance(s, (ast.FunctionDef, ast.AsyncFunctionDef)):
                bound.add(s.name)     # not the names stored INSIDE it
            else:
                bound |= _stores(s)
            i += 1
        return out

    # -- pattern 1: both-branch assignments ---------------------------------
    def _convert_assign_if(self, node: ast.If,
                           bound: Set[str]) -> Optional[List[ast.stmt]]:
        if not node.orelse:
            return None
        ra = _assigned_names(node.body)
        rb = _assigned_names(node.orelse)
        if ra is None or rb is None:
            return None
        (a, pre_a), (b, pre_b) = ra, rb
        if not a or a != b:
            return None
        prebind = sorted(pre_a | pre_b)
        if any(p not in bound for p in prebind):
            # a read-before-write name not provably bound before the if:
            # the default-argument snapshot would evaluate eagerly and
            # raise where plain Python (branch not taken) would not
            return None
        targets = sorted(a)
        self.count += 1
        n = self.count
        ret = ast.Return(value=ast.Tuple(
            elts=[ast.Name(t, ast.Load()) for t in targets],
            ctx=ast.Load()))

        def mk(name, stmts):
            # names read before assignment arrive as default-argument
            # snapshots (`def t(s=s): s = s + x; ...`), sidestepping the
            # closure-local UnboundLocalError
            return ast.FunctionDef(
                name=name,
                args=ast.arguments(
                    posonlyargs=[],
                    args=[ast.arg(arg=p) for p in prebind],
                    kwonlyargs=[], kw_defaults=[],
                    defaults=[ast.Name(p, ast.Load()) for p in prebind]),
                body=list(stmts) + [ret], decorator_list=[])

        call = ast.Call(func=ast.Name("_jst_cond", ast.Load()),
                        args=[node.test,
                              ast.Name(f"__jst_true_{n}", ast.Load()),
                              ast.Name(f"__jst_false_{n}", ast.Load())],
                        keywords=[])
        assign = ast.Assign(
            targets=[ast.Tuple(
                elts=[ast.Name(t, ast.Store()) for t in targets],
                ctx=ast.Store())],
            value=call)
        self.converted += 1
        return [mk(f"__jst_true_{n}", node.body),
                mk(f"__jst_false_{n}", node.orelse), assign]

    def visit_FunctionDef(self, node):
        self.generic_visit(node)   # nested defs rewrite themselves
        args = node.args
        bound = {a.arg for a in (args.posonlyargs + args.args
                                 + args.kwonlyargs)}
        for extra in (args.vararg, args.kwarg):
            if extra is not None:
                bound.add(extra.arg)
        node.body = self._rewrite_body(node.body, bound)
        return node


def _stores(node) -> Set[str]:
    return {n.id for n in ast.walk(node)
            if isinstance(n, ast.Name) and isinstance(n.ctx, ast.Store)}


class _LoopTransformer(ast.NodeTransformer):
    """reference: loop_transformer.py + break_continue_transformer.py.

    Converts ``while``/``for-range`` whose bodies are assignment-only
    (after the if-transformer has run) into carried-closure ``_jst_while``
    dispatch, with a single leading ``if c: break/continue`` or trailing
    ``if c: break`` lowered to a carried done-flag + predicated updates.
    """

    _OK_STMTS = (ast.Assign, ast.AugAssign, ast.Expr, ast.FunctionDef)

    def __init__(self):
        self.count = 0
        self.converted = 0

    # -- analysis ---------------------------------------------------------
    def _body_ok(self, stmts) -> bool:
        for s in stmts:
            if not isinstance(s, self._OK_STMTS):
                return False
            if isinstance(s, ast.Expr) and not isinstance(
                    s.value, ast.Constant):
                # a bare expression in a loop body is almost always a
                # side-effecting call (list.append, dict update, print):
                # running it inside a traced closure would leak tracers
                # into Python state — leave such loops to plain Python
                return False
            if isinstance(s, ast.Assign):
                for t in s.targets:
                    if isinstance(t, ast.Name):
                        continue
                    if isinstance(t, ast.Tuple) and all(
                            isinstance(e, ast.Name) for e in t.elts):
                        continue
                    return False
            if isinstance(s, ast.AugAssign) and not isinstance(
                    s.target, ast.Name):
                return False
            # no hidden control flow inside expressions — but do NOT
            # descend into nested FunctionDefs: the if-transformer's
            # generated branch closures legitimately contain Return
            stack = list(ast.iter_child_nodes(s)) if not isinstance(
                s, ast.FunctionDef) else []
            while stack:
                n = stack.pop()
                if isinstance(n, (ast.Break, ast.Continue, ast.Return,
                                  ast.While, ast.For, ast.If, ast.Yield,
                                  ast.YieldFrom, ast.Await)):
                    return False
                if not isinstance(n, (ast.FunctionDef,
                                      ast.AsyncFunctionDef, ast.Lambda)):
                    stack.extend(ast.iter_child_nodes(n))
        return True

    def _split_break(self, body):
        """Return (mode, pred, rest) where mode in {None, 'lead_break',
        'lead_continue', 'tail_break'}."""
        def is_exit_if(s, kind):
            return (isinstance(s, ast.If) and not s.orelse
                    and len(s.body) == 1 and isinstance(s.body[0], kind))

        if body and is_exit_if(body[0], ast.Break):
            return "lead_break", body[0].test, body[1:]
        if body and is_exit_if(body[0], ast.Continue):
            return "lead_continue", body[0].test, body[1:]
        if body and is_exit_if(body[-1], ast.Break):
            return "tail_break", body[-1].test, body[:-1]
        return None, None, body

    def _carried(self, test, body_stmts, after_loads, brk_pred=None):
        """Loop-carried names: assigned in body AND (read by the test or
        the break/continue predicate, read before written in the body, or
        read after the loop)."""
        assigned: Set[str] = set()
        for s in body_stmts:
            assigned |= _stores(s)
        live: Set[str] = set()
        written: Set[str] = set()
        for s in body_stmts:
            if isinstance(s, ast.Assign):
                live |= (_loads(s.value) & assigned) - written
                for t in s.targets:
                    written |= _stores(t)
            elif isinstance(s, ast.AugAssign):
                live.add(s.target.id)
                live |= (_loads(s.value) & assigned) - written
                written.add(s.target.id)
            else:
                live |= (_loads(s) & assigned) - written
        if test is not None:
            live |= _loads(test) & assigned
        if brk_pred is not None:
            # the break predicate is re-evaluated every iteration: any
            # body-assigned name it reads must ride in the carry or it
            # would see a stale pre-loop snapshot forever
            live |= _loads(brk_pred) & assigned
        live |= after_loads & assigned
        # only live names ride in the carry (they must be bound before the
        # loop, the reference's loop-var rule); write-before-read temps
        # stay body-local
        return sorted(live)

    # -- conversion -------------------------------------------------------
    def _convert(self, node, after_loads):
        is_for = isinstance(node, ast.For)
        if node.orelse:
            return None
        mode, brk_pred, body = self._split_break(list(node.body))
        if not self._body_ok(body):
            return None
        if mode is not None and brk_pred is None:
            return None
        if is_for:
            # for <name> in range(...)
            if not (isinstance(node.target, ast.Name)
                    and isinstance(node.iter, ast.Call)
                    and isinstance(node.iter.func, ast.Name)
                    and node.iter.func.id == "range"
                    and 1 <= len(node.iter.args) <= 3
                    and not node.iter.keywords):
                return None
            ivar = node.target.id
            if ivar in after_loads:
                # python leaves i at the LAST value; our carry leaves it
                # one step past — bail rather than deviate
                return None
            ra = node.iter.args
            start = ast.unparse(ra[0]) if len(ra) >= 2 else "0"
            stop = ast.unparse(ra[1] if len(ra) >= 2 else ra[0])
            if len(ra) == 3:
                if not (isinstance(ra[2], ast.Constant)
                        and isinstance(ra[2].value, int)
                        and ra[2].value > 0):
                    return None
                step = str(ra[2].value)
            else:
                step = "1"
            test_src = None
        else:
            test_src = ast.unparse(node.test)

        carried = self._carried(node.test if not is_for else None, body,
                                after_loads, brk_pred=brk_pred)
        if is_for and ivar in carried:
            carried.remove(ivar)
        if not carried:
            return None
        self.count += 1
        k = self.count
        names = ", ".join(carried)
        done = f"__jst_done_{k}"
        ctr = f"__jst_i_{k}"
        body_src = "\n".join(
            ast.unparse(ast.fix_missing_locations(s)) for s in body
        ) or "pass"

        args = ([ctr] if is_for else []) + carried + (
            [done] if mode in ("lead_break", "tail_break") else [])
        argl = ", ".join(args)
        lines = []
        if is_for:
            lines.append(f"{ctr} = {start}")
            lines.append(f"__jst_n_{k} = {stop}")
        if mode in ("lead_break", "tail_break"):
            lines.append(f"{done} = False")
        # cond
        base_test = (f"_jst_lt({ctr}, __jst_n_{k})" if is_for
                     else f"({test_src})")
        if mode in ("lead_break", "tail_break"):
            cond_ret = f"_jst_and({base_test}, _jst_not({done}))"
        else:
            cond_ret = base_test
        lines.append(f"def __jst_cond_{k}({argl}):")
        lines.append(f"    return {cond_ret}")
        # body
        lines.append(f"def __jst_body_{k}({argl}):")
        if is_for:
            lines.append(f"    {node.target.id} = {ctr}")
        if mode in ("lead_break", "lead_continue"):
            pred = ast.unparse(brk_pred)
            defaults = ", ".join(f"{c}={c}" for c in carried)
            lines.append(f"    __jst_p_{k} = {pred}")
            lines.append(f"    def __jst_rest_{k}({defaults}):")
            for ln in body_src.splitlines():
                lines.append(f"        {ln}")
            lines.append(f"        return ({names},)")
            lines.append(f"    ({names},) = _jst_select(__jst_p_{k}, "
                         f"({names},), __jst_rest_{k})")
            if mode == "lead_break":
                lines.append(f"    {done} = _jst_or({done}, __jst_p_{k})")
        else:
            for ln in body_src.splitlines():
                lines.append(f"    {ln}")
            if mode == "tail_break":
                lines.append(f"    {done} = {ast.unparse(brk_pred)}")
        if is_for:
            lines.append(f"    {ctr} = {ctr} + {step}")
        lines.append(f"    return ({argl},)" if len(args) == 1
                     else f"    return ({argl})")
        # dispatch
        lines.append(f"({argl},) = _jst_while(__jst_cond_{k}, "
                     f"__jst_body_{k}, ({argl},))"
                     if len(args) == 1 else
                     f"({argl}) = _jst_while(__jst_cond_{k}, "
                     f"__jst_body_{k}, ({argl}))")
        src = "\n".join(lines)
        try:
            new_stmts = ast.parse(src).body
        except SyntaxError:  # pragma: no cover - defensive
            return None
        self.converted += 1
        return new_stmts

    def _rewrite(self, stmts, extra_after: Optional[Set[str]] = None):
        out = []
        for i, s in enumerate(stmts):
            if isinstance(s, (ast.While, ast.For)):
                after_loads: Set[str] = set(extra_after or ())
                for t in stmts[i + 1:]:
                    after_loads |= _loads(t)
                conv = self._convert(s, after_loads)
                if conv is not None:
                    out.extend(conv)
                    continue
            out.append(s)
        return out

    def visit_FunctionDef(self, node):
        self.generic_visit(node)
        node.body = self._rewrite(node.body)
        return node

    def visit_While(self, node):
        # convert inner loops first; a converted inner loop inside an
        # unconverted (Python) outer loop is still a win
        self.generic_visit(node)
        node.body = self._rewrite(node.body,
                                  extra_after=_loads(node))
        return node

    def visit_For(self, node):
        self.generic_visit(node)
        node.body = self._rewrite(node.body,
                                  extra_after=_loads(node))
        return node


def convert_control_flow(fn: Callable) -> Callable:
    """Return ``fn`` with convertible tensor-``if`` patterns rewritten to
    paddle.cond dispatch; returns ``fn`` unchanged when no pattern
    converts or the source is unavailable (lambdas, C funcs, REPL)."""
    try:
        src = textwrap.dedent(inspect.getsource(fn))
        tree = ast.parse(src)
    except (OSError, TypeError, SyntaxError):
        return fn
    fdef = tree.body[0]
    if not isinstance(fdef, (ast.FunctionDef, ast.AsyncFunctionDef)):
        return fn
    fdef.decorator_list = []  # run undecorated (to_static wraps us)
    tr = _IfElseTransformer()
    tr.visit(tree)
    lt = _LoopTransformer()
    lt.visit(tree)
    if not (tr.converted or lt.converted):
        return fn
    ast.fix_missing_locations(tree)
    try:
        code = compile(tree, f"<dy2static {fn.__qualname__}>", "exec")
    except (ValueError, SyntaxError):  # pragma: no cover - defensive
        return fn
    glb = dict(fn.__globals__)
    glb.update(_jst_cond=_jst_cond, _jst_while=_jst_while,
               _jst_select=_jst_select, _jst_and=_jst_and,
               _jst_or=_jst_or, _jst_not=_jst_not, _jst_lt=_jst_lt)
    # snapshot closure cells into globals (documented limitation: the
    # converted function sees decoration-time closure values)
    if fn.__closure__:
        try:
            glb.update({k: c.cell_contents
                        for k, c in zip(fn.__code__.co_freevars,
                                        fn.__closure__)})
        except ValueError:  # empty cell (helper defined later): skip
            return fn
    loc: dict = {}
    exec(code, glb, loc)
    new_fn = loc[fdef.name]
    functools.update_wrapper(new_fn, fn)
    return new_fn
