"""paddle_tpu.jit — trace/compile execution mode
(reference: python/paddle/fluid/dygraph/jit.py + dygraph_to_static/;
SURVEY §7 step 2 'dual-mode dispatch')."""
from .bind import bind, buffer_arrays, param_arrays, param_list  # noqa
from .lint import LintDiagnostic, lint  # noqa: F401
from .save_load import TranslatedLayer, load, save  # noqa: F401
from .static_function import InputSpec, StaticFunction, to_static  # noqa
from .train_step import TrainStep  # noqa: F401

not_to_static = lambda fn: fn  # parity no-op


def enable_to_static(flag: bool = True):
    StaticFunction._enabled = flag
