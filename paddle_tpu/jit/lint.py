"""dy2static lint: static diagnosis of what to_static will NOT convert.

Reference: the error-reporting tier of dygraph_to_static
(error.py + origin_info.py map transformed code back to user
file:line).  The converter (dy2static.py) is deliberately conservative
— anything it cannot prove convertible is left untouched and only fails
LOUDLY at trace time, deep inside jit.  This lint runs the SAME
transformer pipeline purely statically (the function is never executed)
and reports, with file:line anchors:

- ``D2S101`` tensor-dependent ``if``/``while``/``for`` the converter
  leaves unconverted (these raise the tensor-bool TypeError the first
  time a traced tensor hits the test);
- ``D2S102`` side-effecting bare-call statements inside tensor-dependent
  loop bodies (``list.append`` etc. — exactly what blocks loop
  conversion, per ``_LoopTransformer._body_ok``);
- ``D2S103`` shadowed builtins (``print``/``int``/``float``/``bool``
  rebound by a param, local store, or module/closure binding), which the
  builtin transformer therefore skips rewriting;
- ``D2S104`` host-sync calls on traced tensors — the same hazard the
  Program analyzer's host-transfer pass reports on recorded graphs,
  caught here earlier at the AST level.  ``.numpy()`` / ``.item()`` /
  ``.tolist()`` are errors: nothing rewrites them, so under
  ``to_static`` they concretize a tracer (a TypeError deep in jit).
  ``float()``/``int()``/``bool()`` are warnings: the cast transformer
  silently lowers them to a tensor ``astype`` — the code runs, but it
  never yields the Python scalar it reads as (and in eager TPU code
  the same call is a device→host sync point).

"Tensor-dependent" is a static taint over the AST: function parameters
are assumed tensors; taint flows through assignments, attributes,
calls-on-tainted, and arithmetic.  Tests that cannot be a traced-truth
value (``is None``, ``isinstance``, ``len``) are excluded — they stay
concrete at trace time and are safe in plain Python form.
"""
from __future__ import annotations

import ast
import inspect
import textwrap
from typing import Callable, List, Optional, Set

from .dy2static import (_decoration_env, _shadowed_builtins,
                        _transform_tree)

__all__ = ["LintDiagnostic", "lint"]

# calls that produce concrete (non-traced) values even on tensor args
_CONCRETE_FNS = {"isinstance", "issubclass", "hasattr", "getattr",
                 "callable", "len", "type", "id", "repr", "str"}
# methods that force a device->host sync (and concretize a tracer)
_HOST_SYNC_METHODS = {"numpy", "item", "tolist"}
# builtin conversions that concretize a traced truth/scalar value
_HOST_SYNC_BUILTINS = {"float", "int", "bool"}
# attributes that are concrete Python metadata at trace time — control
# flow over them (`if x.shape[0] > 1`, `for i in range(x.ndim)`) is safe
_CONCRETE_ATTRS = {"shape", "ndim", "dtype", "name"}
_CONCRETE_CMP = (ast.Is, ast.IsNot, ast.In, ast.NotIn)


class LintDiagnostic:
    """One finding, anchored to the user's source."""

    __slots__ = ("file", "line", "col", "code", "severity", "message",
                 "function")

    def __init__(self, file: str, line: int, col: int, code: str,
                 severity: str, message: str, function: str = ""):
        self.file = file
        self.line = line
        self.col = col
        self.code = code
        self.severity = severity
        self.message = message
        self.function = function

    def __str__(self):
        return (f"{self.file}:{self.line}:{self.col}: {self.code} "
                f"[{self.severity}] {self.message}")

    def __repr__(self):
        return f"LintDiagnostic({self!s})"

    def to_dict(self) -> dict:
        """JSON-able record (tools/lint_program.py --format json)."""
        return {s: getattr(self, s) for s in self.__slots__}


# -- taint ------------------------------------------------------------------

def _names_read(node) -> Set[str]:
    return {n.id for n in ast.walk(node)
            if isinstance(n, ast.Name) and isinstance(n.ctx, ast.Load)}


def _tensor_taint(fdef: ast.FunctionDef) -> Set[str]:
    """Names that may hold tensors: parameters seed the set; assignments
    whose value reads a tainted name propagate it.  Iterated to fixpoint
    (loops assign before the reader appears textually earlier)."""
    tainted = {a.arg for a in (fdef.args.args + fdef.args.posonlyargs
                               + fdef.args.kwonlyargs)}
    for extra in (fdef.args.vararg, fdef.args.kwarg):
        if extra is not None:
            tainted.add(extra.arg)
    changed = True
    while changed:
        changed = False
        for n in ast.walk(fdef):
            if isinstance(n, ast.Assign):
                if _names_read(n.value) & tainted:
                    for t in n.targets:
                        for nm in ast.walk(t):
                            if (isinstance(nm, ast.Name)
                                    and isinstance(nm.ctx, ast.Store)
                                    and nm.id not in tainted):
                                tainted.add(nm.id)
                                changed = True
            elif isinstance(n, ast.AugAssign):
                if (isinstance(n.target, ast.Name)
                        and _names_read(n.value) & tainted
                        and n.target.id not in tainted):
                    tainted.add(n.target.id)
                    changed = True
    return tainted


def _tensorish(expr, tainted: Set[str]) -> bool:
    """Could ``expr`` evaluate to a traced tensor (so that truth-testing
    it raises)?  Conservative on structure, but excludes expressions
    whose VALUE is always concrete (`is None`, isinstance, len)."""
    if isinstance(expr, ast.Name):
        return expr.id in tainted
    if isinstance(expr, ast.Attribute):
        if expr.attr in _CONCRETE_ATTRS:
            return False
        return _tensorish(expr.value, tainted)
    if isinstance(expr, ast.Subscript):
        return _tensorish(expr.value, tainted)
    if isinstance(expr, ast.Call):
        f = expr.func
        if isinstance(f, ast.Name) and f.id in _CONCRETE_FNS:
            return False
        if isinstance(f, ast.Attribute):      # x.sum(), x.mean()...
            return _tensorish(f.value, tainted)
        return any(_tensorish(a, tainted) for a in expr.args)
    if isinstance(expr, ast.Compare):
        if all(isinstance(op, _CONCRETE_CMP) for op in expr.ops):
            return False
        return (_tensorish(expr.left, tainted)
                or any(_tensorish(c, tainted) for c in expr.comparators))
    if isinstance(expr, ast.BoolOp):
        return any(_tensorish(v, tainted) for v in expr.values)
    if isinstance(expr, ast.UnaryOp):
        return _tensorish(expr.operand, tainted)
    if isinstance(expr, ast.BinOp):
        return (_tensorish(expr.left, tainted)
                or _tensorish(expr.right, tainted))
    if isinstance(expr, ast.IfExp):
        return (_tensorish(expr.body, tainted)
                or _tensorish(expr.orelse, tainted))
    return False


def _is_generated(node) -> bool:
    """Transformer-emitted control flow (`if __jst_rf_k: return ...`)
    must not be reported as the user's.  Only ``if`` is ever emitted —
    loops lower to ``_jst_while`` calls — so For/While are always user
    code; only the TEST is inspected (a converted print/cast in the
    body must not mask the user's construct), and only the generated
    ``__jst*`` names count (``_jst_land``/``_jst_lor`` appear in USER
    tests after the logical transformer ran)."""
    if not isinstance(node, ast.If):
        return False
    for n in ast.walk(node.test):
        if isinstance(n, ast.Name) and n.id.startswith("__jst"):
            return True
    return False


# -- lint core --------------------------------------------------------------

def _surviving_control_flow(tree) -> List[ast.stmt]:
    """If/While/For statements still present AFTER the transformer
    pipeline ran — i.e. what to_static will execute as plain Python."""
    out = []
    for n in ast.walk(tree):
        if isinstance(n, (ast.If, ast.While, ast.For)) and \
                not _is_generated(n):
            out.append(n)
    return out


def _unwrap(fn) -> Optional[Callable]:
    from .static_function import StaticFunction
    if isinstance(fn, StaticFunction):
        fn = fn._fn
    seen = set()
    while hasattr(fn, "__wrapped__") and id(fn) not in seen:
        seen.add(id(fn))
        fn = fn.__wrapped__
    if inspect.ismethod(fn):
        fn = fn.__func__
    return fn if callable(fn) else None


def lint(fn) -> List[LintDiagnostic]:
    """Statically lint ``fn`` (a plain function, method, or
    ``to_static``-wrapped StaticFunction) for dy2static hazards.  The
    function is parsed and analysed, never called."""
    fn = _unwrap(fn)
    if fn is None:
        return []
    try:
        file = inspect.getsourcefile(fn) or "<unknown>"
        src_lines, start = inspect.getsourcelines(fn)
    except (OSError, TypeError):
        return []
    name = getattr(fn, "__qualname__", getattr(fn, "__name__", "<fn>"))

    # original tree: line anchors, taint, side-effect + shadow scans
    try:
        orig = ast.parse(textwrap.dedent("".join(src_lines)))
    except SyntaxError:
        return []
    if not orig.body or not isinstance(
            orig.body[0], (ast.FunctionDef, ast.AsyncFunctionDef)):
        return []
    fdef0 = orig.body[0]

    # transformed tree: what the converter actually leaves behind
    res = _transform_tree(fn)
    if res is None:
        converted_tree = orig  # nothing converts; everything survives
    else:
        converted_tree = res[0]

    def anchor(node) -> tuple:
        return (start + node.lineno - 1, node.col_offset)

    diags: List[LintDiagnostic] = []
    tainted = _tensor_taint(fdef0)

    # -- D2S101: surviving tensor-dependent control flow ------------------
    survivors = _surviving_control_flow(converted_tree)
    surviving_lines = {s.lineno for s in survivors}
    for node in ast.walk(fdef0):
        if isinstance(node, ast.If) and node.lineno in surviving_lines \
                and _tensorish(node.test, tainted):
            line, col = anchor(node)
            diags.append(LintDiagnostic(
                file, line, col, "D2S101", "error",
                f"tensor-dependent `if` is not convertible and will "
                f"raise at trace time "
                f"(test: `{ast.unparse(node.test)}`); restructure both "
                f"branches to assign the same names, or use "
                f"paddle.static.nn.cond", function=name))
        elif isinstance(node, ast.While) \
                and node.lineno in surviving_lines \
                and _tensorish(node.test, tainted):
            line, col = anchor(node)
            diags.append(LintDiagnostic(
                file, line, col, "D2S101", "error",
                f"tensor-dependent `while` is not convertible and will "
                f"raise at trace time "
                f"(test: `{ast.unparse(node.test)}`); make the body "
                f"assignment-only, or use paddle.static.nn.while_loop",
                function=name))
        elif isinstance(node, ast.For) and node.lineno in surviving_lines:
            it = node.iter
            over_range = (isinstance(it, ast.Call)
                          and isinstance(it.func, ast.Name)
                          and it.func.id == "range")
            if over_range and any(_tensorish(a, tainted)
                                  for a in it.args):
                line, col = anchor(node)
                diags.append(LintDiagnostic(
                    file, line, col, "D2S101", "error",
                    f"`for` over a tensor-valued `range` bound is not "
                    f"convertible (`{ast.unparse(it)}`); make the body "
                    f"assignment-only so the loop converter can carry "
                    f"it, or use paddle.static.nn.while_loop",
                    function=name))
            elif not over_range and _tensorish(it, tainted):
                line, col = anchor(node)
                diags.append(LintDiagnostic(
                    file, line, col, "D2S101", "error",
                    f"`for` iterating a tensor "
                    f"(`{ast.unparse(it)}`) is never converted; index "
                    f"with a converted range loop or vectorise",
                    function=name))

    # -- D2S102: side effects in tensor-dependent loop bodies -------------
    for loop in ast.walk(fdef0):
        if not isinstance(loop, (ast.While, ast.For)):
            continue
        loop_tainted = (
            _tensorish(loop.test, tainted) if isinstance(loop, ast.While)
            else _tensorish(loop.iter, tainted)
            or (isinstance(loop.iter, ast.Call)
                and isinstance(loop.iter.func, ast.Name)
                and loop.iter.func.id == "range"
                and any(_tensorish(a, tainted) for a in loop.iter.args)))
        if not loop_tainted:
            continue
        for s in loop.body:
            if (isinstance(s, ast.Expr)
                    and isinstance(s.value, ast.Call)
                    and not (isinstance(s.value.func, ast.Name)
                             and s.value.func.id in ("print",))):
                line, col = anchor(s)
                diags.append(LintDiagnostic(
                    file, line, col, "D2S102", "warning",
                    f"side-effecting statement "
                    f"`{ast.unparse(s.value)}` in a tensor-dependent "
                    f"loop body blocks conversion (mutating Python "
                    f"state from a traced loop leaks tracers); carry "
                    f"values through loop variables instead",
                    function=name))

    # -- D2S104: host-sync calls on traced tensors ------------------------
    env0 = _decoration_env(fn)
    shadowed_all = _shadowed_builtins(fdef0, env0)
    for n in ast.walk(fdef0):
        if not isinstance(n, ast.Call):
            continue
        f = n.func
        if isinstance(f, ast.Attribute) and f.attr in _HOST_SYNC_METHODS \
                and _tensorish(f.value, tainted):
            line, col = anchor(n)
            diags.append(LintDiagnostic(
                file, line, col, "D2S104", "error",
                f"host-sync call `{ast.unparse(n)}` on a traced tensor: "
                f"under to_static this concretizes the tracer (TypeError "
                f"at trace time), and in eager TPU code it stalls the "
                f"async dispatch pipeline with a device->host sync; "
                f"return the tensor and convert OUTSIDE the compiled "
                f"function", function=name))
        elif (isinstance(f, ast.Name) and f.id in _HOST_SYNC_BUILTINS
                and f.id not in shadowed_all and n.args
                and _tensorish(n.args[0], tainted)):
            line, col = anchor(n)
            diags.append(LintDiagnostic(
                file, line, col, "D2S104", "warning",
                f"`{f.id}(...)` on a traced tensor "
                f"(`{ast.unparse(n)}`) does not produce a Python "
                f"{f.id} under to_static: the cast transformer lowers "
                f"it to a tensor astype, so code expecting a host "
                f"scalar (formatting, dict keys, plain-Python math) "
                f"misbehaves — and in eager TPU code the same call "
                f"stalls the pipeline with a device->host sync; keep "
                f"the value a tensor, or convert outside the compiled "
                f"function", function=name))

    # -- D2S103: shadowed builtins ----------------------------------------
    shadowed = shadowed_all & {"print", "int", "float", "bool"}
    if shadowed:
        for n in ast.walk(fdef0):
            if (isinstance(n, ast.Call) and isinstance(n.func, ast.Name)
                    and n.func.id in shadowed):
                line, col = anchor(n)
                diags.append(LintDiagnostic(
                    file, line, col, "D2S103", "warning",
                    f"`{n.func.id}(...)` calls a SHADOWED builtin "
                    f"(rebound by a param, local assignment, or "
                    f"module/closure binding), so dy2static will not "
                    f"lower it for traced tensors; rename the "
                    f"shadowing binding", function=name))
    diags.sort(key=lambda d: (d.line, d.col, d.code))
    return diags
