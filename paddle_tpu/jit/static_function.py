"""to_static: trace-and-compile execution mode.

TPU-native replacement for the reference's dy2static AST transpiler +
ProgramTranslator + partial_program run_program_op (reference:
python/paddle/fluid/dygraph/dygraph_to_static/, jit.py:160 `declarative`).

Where the reference rewrites Python AST into a ProgramDesc and replays it
with an Executor, we simply trace the SAME op functions with jax tracers and
let XLA compile — "static mode" is a jit cache, and the whole compiled
program participates in outer eager autograd as ONE fused op on the tape
(its vjp is the compiled backward), mirroring how run_program_op embeds a
traced program into dygraph autograd.
"""
from __future__ import annotations

import functools
import itertools
from typing import Any, Callable, Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp

from ..core import autograd, dispatch, rng
from ..core.tensor import Tensor
from .bind import bind, buffer_names, param_list


class InputSpec:
    """reference: paddle.static.InputSpec."""

    def __init__(self, shape, dtype="float32", name=None):
        self.shape = tuple(shape)
        self.dtype = dtype
        self.name = name

    def __repr__(self):
        return f"InputSpec(shape={self.shape}, dtype={self.dtype})"


def _find_layer(fn) -> Optional[object]:
    from ..nn.layer_base import Layer
    if isinstance(fn, Layer):
        return fn
    self = getattr(fn, "__self__", None)
    if isinstance(self, Layer):
        return self
    return None


class StaticFunction:
    """Callable wrapping ``fn`` with jit compilation.

    The compiled pure function takes (rng_key, *param_arrays,
    *buffer_arrays, *tensor_args) and returns (outputs, new_buffer_values);
    it is pushed through ``dispatch.apply`` so eager autograd sees it as a
    single differentiable op.
    """

    _SERIALS = itertools.count(1)

    def __init__(self, fn: Callable, input_spec=None, layer=None):
        self._fn = fn.forward if layer is not None and fn is layer else fn
        self._layer = layer if layer is not None else _find_layer(fn)
        self._input_spec = input_spec
        self._cache: Dict[Any, Callable] = {}
        self._fn = self._convert_control_flow(self._fn)
        # recompile-attribution identity (id() could be recycled)
        self._serial = (f"{getattr(self._fn, '__name__', 'to_static')}"
                        f"#{next(StaticFunction._SERIALS)}")
        functools.update_wrapper(self, self._fn)

    @staticmethod
    def _convert_control_flow(fn):
        """AST-convert data-dependent Python `if` patterns to paddle.cond
        (dy2static.py); unconvertible code is left untouched and still
        fails loudly at trace time rather than mistracing."""
        import inspect

        from .dy2static import convert_control_flow
        raw = fn.__func__ if inspect.ismethod(fn) else fn
        conv = convert_control_flow(raw)
        if conv is raw:
            return fn
        if inspect.ismethod(fn):
            return conv.__get__(fn.__self__)
        return conv

    @property
    def layer(self):
        return self._layer

    _NONCE = itertools.count(1)

    def _build(self, treedef, n_tensors, static_leaves, training,
               recompile_field=None):
        layer = self._layer
        fn = self._fn
        n_p = len(param_list(layer)) if layer else 0
        bnames = buffer_names(layer) if layer else []
        n_b = len(bnames)

        def pure_fn(key_data, *arrays):
            key_data = jax.random.wrap_key_data(key_data)
            p_arr = list(arrays[:n_p])
            b_arr = list(arrays[n_p:n_p + n_b])
            in_arr = arrays[n_p + n_b:]
            # rebuild the (args, kwargs) structure with traced Tensors
            leaves = []
            it = iter(in_arr)
            for leaf in static_leaves:
                if leaf is _TENSOR_SENTINEL:
                    leaves.append(Tensor(next(it), stop_gradient=True))
                else:
                    leaves.append(leaf)
            args, kwargs = jax.tree.unflatten(treedef, leaves)
            with autograd.no_grad(), rng.seed_scope(key_data):
                if layer is not None:
                    with bind(layer, p_arr, b_arr) as res:
                        out = fn(*args, **kwargs)
                    # new_buffers is populated on bind-context exit
                    new_b = [res.new_buffers.get(n, old)
                             for n, old in zip(bnames, b_arr)]
                else:
                    out = fn(*args, **kwargs)
                    new_b = []
            out_arrays = jax.tree.map(
                lambda t: t.data if isinstance(t, Tensor) else t, out,
                is_leaf=lambda x: isinstance(x, Tensor))
            return out_arrays, tuple(new_b)

        # recompile attribution once the build assembled (a cache miss
        # at this layer = a fresh trace+compile at first call): new
        # input structure / static-arg values, or a training flip.
        # recompile_field marks builds that bypass the cache by design
        # (unhashable static leaves, AOT export) so they read as a
        # named cause instead of "unexplained".
        from ..observability import record_compile
        sig = {}
        if recompile_field is not None:
            sig[recompile_field] = next(StaticFunction._NONCE)
        sig["input_structure"] = (str(treedef), repr(static_leaves))
        sig["training"] = training
        record_compile("jit", self._serial, sig)
        return jax.jit(pure_fn)

    def __call__(self, *args, **kwargs):
        layer = self._layer
        params = param_list(layer) if layer else []
        from .bind import buffer_arrays
        b_arrs = buffer_arrays(layer) if layer else []
        bnames = buffer_names(layer) if layer else []

        leaves, treedef = jax.tree.flatten(
            (args, kwargs), is_leaf=lambda x: isinstance(x, Tensor))
        tensor_args = [l for l in leaves if isinstance(l, Tensor)]
        static_leaves = tuple(
            _TENSOR_SENTINEL if isinstance(l, Tensor) else l for l in leaves)
        training = bool(layer.training) if layer is not None else False
        key = (treedef, static_leaves, training)
        try:
            compiled = self._cache.get(key)
        except TypeError:  # unhashable static leaf
            key = None
            compiled = None
        if compiled is None:
            compiled = self._build(
                treedef, len(tensor_args), static_leaves, training,
                recompile_field=(None if key is not None
                                 else "uncacheable_call"))
            if key is not None:
                self._cache[key] = compiled

        key_t = Tensor(jax.random.key_data(rng.next_key()))
        inputs = [key_t] + list(params) + \
            [Tensor(a) for a in b_arrs] + tensor_args

        adapter = _MultiOut(compiled)
        adapter.__name__ = getattr(self._fn, "__name__", "to_static")
        out_flat = dispatch.apply(adapter, *inputs, op_name=adapter.__name__)
        if not isinstance(out_flat, tuple):
            out_flat = (out_flat,)
        out, new_b = _renest(adapter, out_flat)
        # write back mutated buffers (eager side effect)
        if layer is not None and len(bnames):
            buffers = dict(layer.named_buffers())
            for n, t in zip(bnames, new_b):
                buffers[n].data = t.data if isinstance(t, Tensor) else t
        return out

    def __get__(self, instance, owner=None):
        """Descriptor protocol: @to_static on a method binds per instance
        (the reference's declarative decorator does the analogous binding
        via StaticFunction.__get__, dygraph/jit.py)."""
        if instance is None:
            return self
        from ..nn.layer_base import Layer
        key = "_static_fn_" + self._fn.__name__
        cached = instance.__dict__.get(key) if hasattr(
            instance, "__dict__") else None
        if cached is not None:
            return cached
        layer = instance if isinstance(instance, Layer) else None
        bound = StaticFunction(self._fn.__get__(instance, owner),
                               self._input_spec, layer=layer)
        try:
            object.__setattr__(instance, key, bound)
        except Exception:
            pass
        return bound

    # concretisation for export/inference
    def concrete(self, *example_args, **example_kwargs):
        """Return (jitted_pure_fn, init_arrays) for AOT export."""
        leaves, treedef = jax.tree.flatten(
            (example_args, example_kwargs),
            is_leaf=lambda x: isinstance(x, Tensor))
        tensor_args = [l for l in leaves if isinstance(l, Tensor)]
        static_leaves = tuple(
            _TENSOR_SENTINEL if isinstance(l, Tensor) else l for l in leaves)
        training = bool(self._layer.training) if self._layer else False
        compiled = self._build(treedef, len(tensor_args), static_leaves,
                               training, recompile_field="export_call")
        return compiled, tensor_args


class _TensorSentinel:
    def __repr__(self):
        return "<TensorArg>"


_TENSOR_SENTINEL = _TensorSentinel()


class _MultiOut:
    """Adapter: dispatch.apply expects fn(*arrays); compiled returns
    (out_tree, new_buffers).  Flatten outputs so the tape's vjp covers the
    whole structure, then re-nest."""

    def __init__(self, compiled):
        self._compiled = compiled
        self._out_treedef = None
        self.__name__ = "to_static"

    def __call__(self, key_data, *arrays):
        out, new_b = self._compiled(key_data, *arrays)
        flat, treedef = jax.tree.flatten(out)
        self._out_treedef = (treedef, len(flat), len(new_b))
        return tuple(flat) + tuple(new_b)


def _renest(adapter, out_tensors):
    treedef, n_out, n_b = adapter._out_treedef
    outs = jax.tree.unflatten(treedef, list(out_tensors[:n_out]))
    return outs, list(out_tensors[n_out:])


def to_static(function=None, input_spec=None, build_strategy=None, **kwargs):
    """``@paddle.jit.to_static`` parity (reference: dygraph/jit.py:160)."""
    def decorate(fn):
        from ..nn.layer_base import Layer
        if isinstance(fn, Layer):
            sf = StaticFunction(fn.forward, input_spec, layer=fn)
            fn.forward = sf
            return fn
        return StaticFunction(fn, input_spec)
    if function is not None:
        return decorate(function)
    return decorate
