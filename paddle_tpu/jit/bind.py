"""Parameter/buffer binding for functional execution.

The bridge between the stateful Layer world and pure-functional XLA: swap
every Parameter/buffer ``.data`` with (possibly traced) arrays for the
duration of a trace, and collect buffer mutations (BatchNorm running stats)
on exit so the compiled step can thread them as explicit outputs — the
TPU answer to the reference's in-place Scope mutation (SURVEY §7
hard-parts)."""
from __future__ import annotations

import contextlib
from typing import Dict, List, Optional, Sequence


class BindResult:
    """Filled on context exit with mutated buffer values."""

    def __init__(self):
        self.new_buffers: Dict[str, object] = {}


@contextlib.contextmanager
def bind(layer, param_arrays: Optional[Sequence] = None,
         buffer_arrays: Optional[Sequence] = None,
         param_names: Optional[List[str]] = None):
    """Bind ``param_arrays``/``buffer_arrays`` (aligned with
    ``layer.named_parameters()`` / ``named_buffers()`` order) into the layer.

    Yields a :class:`BindResult`; after the with-block, ``new_buffers`` maps
    buffer names whose ``.data`` changed during the trace to the new value.
    All original arrays are restored on exit.
    """
    params = list(layer.named_parameters())
    buffers = list(layer.named_buffers())
    old_p = [p.data for _, p in params]
    old_b = [b.data for _, b in buffers]
    res = BindResult()
    try:
        if param_arrays is not None:
            assert len(param_arrays) == len(params), (
                f"bind: {len(param_arrays)} arrays for {len(params)} params")
            for (name, p), arr in zip(params, param_arrays):
                p.data = arr
        if buffer_arrays is not None:
            assert len(buffer_arrays) == len(buffers)
            for (name, b), arr in zip(buffers, buffer_arrays):
                b.data = arr
        yield res
        # collect mutations: any buffer whose data is not the bound-in array
        if buffer_arrays is not None:
            for (name, b), arr in zip(buffers, buffer_arrays):
                if b.data is not arr:
                    res.new_buffers[name] = b.data
        else:
            for (name, b), old in zip(buffers, old_b):
                if b.data is not old:
                    res.new_buffers[name] = b.data
    finally:
        for (_, p), old in zip(params, old_p):
            p.data = old
        for (_, b), old in zip(buffers, old_b):
            b.data = old


def param_arrays(layer):
    return [p.data for _, p in layer.named_parameters()]


def buffer_arrays(layer):
    return [b.data for _, b in layer.named_buffers()]


def param_list(layer):
    return [p for _, p in layer.named_parameters()]


def buffer_names(layer):
    return [n for n, _ in layer.named_buffers()]
