"""jit.save / jit.load — deployable model serialization.

Reference analog: ``paddle.jit.save`` writes a ProgramDesc + params and
``paddle.jit.load`` returns a TranslatedLayer (reference: dygraph/jit.py:269,
io.py TranslatedLayer).  TPU-native: we export the traced forward as
serialized StableHLO via ``jax.export`` (portable, version-stable) alongside
the state_dict; ``load`` returns a :class:`TranslatedLayer` that executes
the compiled artifact — the inference path needs no Python model code.
"""
from __future__ import annotations

import os
import pickle

import jax
import jax.numpy as jnp
import numpy as np
from jax import export as jax_export

from ..core import autograd, rng
from ..core.dtype import convert_dtype
from ..core.tensor import Tensor
from ..framework_io import load as _load_obj
from ..framework_io import save as _save_obj
from .bind import bind, buffer_arrays, param_arrays
from .static_function import InputSpec, StaticFunction

SUFFIX_MODEL = ".pdmodel"
SUFFIX_PARAMS = ".pdiparams"


def _example_arrays(input_spec):
    """InputSpecs with None/-1 dims become jax symbolic dimensions so the
    exported artifact is shape-polymorphic (batch-size agnostic)."""
    out = []
    sym_count = [0]

    def _sym():
        sym_count[0] += 1
        return f"b{sym_count[0]}"

    for spec in input_spec:
        if isinstance(spec, InputSpec):
            if any(s is None or (isinstance(s, int) and s < 0)
                   for s in spec.shape):
                dims = ", ".join(
                    _sym() if (s is None or s < 0) else str(s)
                    for s in spec.shape)
                shape = jax_export.symbolic_shape(dims)
                out.append(jax.ShapeDtypeStruct(
                    shape, convert_dtype(spec.dtype)))
            else:
                out.append(jnp.zeros(tuple(spec.shape),
                                     convert_dtype(spec.dtype)))
        elif isinstance(spec, Tensor):
            out.append(spec.data)
        else:
            out.append(jnp.asarray(spec))
    return out


def save(layer, path, input_spec=None, **configs):
    """Serialize ``layer`` for inference (StableHLO) + its state_dict."""
    from ..nn.layer_base import Layer
    fwd = layer.forward
    if isinstance(fwd, StaticFunction):
        spec = input_spec or fwd._input_spec
        fwd_fn = fwd._fn
    else:
        spec = input_spec
        fwd_fn = fwd
    if spec is None:
        raise ValueError(
            "jit.save needs input_spec (list of InputSpec/example Tensors) "
            "unless the layer was decorated with to_static(input_spec=...)")
    examples = _example_arrays(spec)

    was_training = layer.training
    layer.eval()
    p_arr = param_arrays(layer)
    b_arr = buffer_arrays(layer)
    fixed_key = jax.random.key(0)

    def infer_fn(*in_arrays):
        with autograd.no_grad(), rng.seed_scope(fixed_key):
            with bind(layer):  # params bound to their concrete values
                out = fwd_fn(*[Tensor(a) for a in in_arrays])
        return jax.tree.map(
            lambda t: t.data if isinstance(t, Tensor) else t, out,
            is_leaf=lambda x: isinstance(x, Tensor))

    exported = jax_export.export(jax.jit(infer_fn))(*examples)
    blob = exported.serialize()
    if was_training:
        layer.train()

    os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
    with open(path + SUFFIX_MODEL, "wb") as f:
        meta = {
            "format": "paddle_tpu.stablehlo.v1",
            "in_shapes": [tuple(str(d) for d in e.shape) for e in examples],
            "in_dtypes": [str(e.dtype) for e in examples],
        }
        head = pickle.dumps(meta)
        f.write(len(head).to_bytes(8, "little"))
        f.write(head)
        f.write(blob)
    _save_obj(layer.state_dict(), path + SUFFIX_PARAMS)


class TranslatedLayer:
    """Executable loaded model (reference: TranslatedLayer, io.py)."""

    def __init__(self, exported, meta, state_dict):
        self._exported = exported
        self._meta = meta
        self._state = state_dict
        self.training = False

    def __call__(self, *args):
        arrays = [a.data if isinstance(a, Tensor) else jnp.asarray(a)
                  for a in args]
        out = self._exported.call(*arrays)
        return jax.tree.map(Tensor, out)

    forward = __call__

    def eval(self):
        self.training = False
        return self

    def state_dict(self):
        return self._state

    def parameters(self):
        return list(self._state.values())


def load(path, **configs):
    with open(path + SUFFIX_MODEL, "rb") as f:
        n = int.from_bytes(f.read(8), "little")
        meta = pickle.loads(f.read(n))
        blob = f.read()
    exported = jax_export.deserialize(blob)
    state = (_load_obj(path + SUFFIX_PARAMS)
             if os.path.exists(path + SUFFIX_PARAMS) else {})
    return TranslatedLayer(exported, meta, state)
