/* C ABI for the paddle_tpu inference Predictor.
 *
 * Reference analog: paddle_inference_c API (paddle/fluid/inference/capi)
 * consumed by go/paddle.  Implemented by predictor_capi.cpp (embeds
 * CPython; link against libpaddle_tpu_capi.so and the Python runtime).
 *
 * Threading: every entry point acquires the GIL internally, so calls
 * from any host thread are individually safe — but outputs are stashed
 * per predictor, so a Run -> GetOutput SEQUENCE must be serialized per
 * predictor by the caller (concurrent Runs on one predictor would
 * interleave each other's outputs).  Distinct predictors are
 * independent.  All arrays are float32; shapes are int64.
 */
#ifndef PADDLE_TPU_CAPI_H_
#define PADDLE_TPU_CAPI_H_

#include <stdint.h>

#ifdef __cplusplus
extern "C" {
#endif

typedef struct PT_Predictor PT_Predictor;

typedef struct PT_Output {
  float* data;
  int64_t* shape;
  int32_t ndim;
  int64_t numel;
} PT_Output;

/* Load a jit.save'd model (path prefix, no extension).  NULL on
 * failure (error text on stderr). */
PT_Predictor* PT_NewPredictor(const char* model_path_prefix);

/* Run with n_inputs float32 buffers; shapes[i] has ndims[i] dims.
 * Returns the number of outputs, < 0 on error. */
int32_t PT_PredictorRun(PT_Predictor* p, const float* const* inputs,
                        const int64_t* const* shapes,
                        const int32_t* ndims, int32_t n_inputs);

/* Copy output idx of the last successful run into *out (free with
 * PT_FreeOutput).  0 on success. */
int32_t PT_GetOutput(PT_Predictor* p, int32_t idx, PT_Output* out);

void PT_FreeOutput(PT_Output* out);

void PT_DeletePredictor(PT_Predictor* p);

#ifdef __cplusplus
}  /* extern "C" */
#endif

#endif  /* PADDLE_TPU_CAPI_H_ */
