// C ABI over the paddle_tpu inference Predictor.
//
// Reference: fluid/inference/capi/paddle_c_api.h (PD_NewAnalysisConfig,
// PD_NewPredictor :279, PD_PredictorRun :124, PD_DeletePredictor :282) —
// the surface go/paddle/predictor.go binds to.  There the C API fronts
// the C++ AnalysisPredictor; here the serving engine is the XLA AOT
// executable driven by the Python Predictor, so the C ABI EMBEDS CPython
// (Py_InitializeEx when standalone; GIL-acquire when the host process
// already runs an interpreter, which is how the test suite exercises it).
// Float32 tensors only in v1 — the dominant serving dtype; extend the
// dtype switch as needed.
//
// Build:  g++ -shared -fPIC predictor_capi.cpp -o libpaddle_tpu_capi.so \
//             -I$(python -c "import sysconfig;print(sysconfig.get_path('include'))") \
//             -lpython3.12
#include <Python.h>

#include <cstdint>
#include <cstring>
#include <string>
#include <vector>

// the public contract lives in the header (consumed by go/paddle and C
// clients); this TU provides PT_Predictor's definition
#include "paddle_tpu_capi.h"

extern "C" {

struct PT_Predictor {
  PyObject* predictor;  // paddle_tpu.inference.Predictor
};

static int g_we_initialized = 0;

static int ensure_python() {
  if (!Py_IsInitialized()) {
    Py_InitializeEx(0);
    g_we_initialized = 1;
    // release the GIL the init thread holds: every entry point uses
    // PyGILState_Ensure/Release, and a second host thread would
    // otherwise deadlock in Ensure while this thread never re-enters
    PyEval_SaveThread();
  }
  return 1;
}

// Returns NULL on failure; error text (if any) is printed to stderr.
PT_Predictor* PT_NewPredictor(const char* model_path_prefix) {
  ensure_python();
  PyGILState_STATE g = PyGILState_Ensure();
  PT_Predictor* out = nullptr;
  PyObject *mod = nullptr, *cfg_cls = nullptr, *cfg = nullptr,
           *create = nullptr, *pred = nullptr;
  // honor JAX_PLATFORMS even when a sitecustomize pre-imported jax with
  // its own platform choice (config.update wins post-import)
  PyRun_SimpleString(
      "import os\n"
      "_p = os.environ.get('JAX_PLATFORMS')\n"
      "if _p:\n"
      "    import jax\n"
      "    try:\n"
      "        jax.config.update('jax_platforms', _p)\n"
      "    except Exception:\n"
      "        pass\n");
  mod = PyImport_ImportModule("paddle_tpu.inference");
  if (!mod) goto fail;
  cfg_cls = PyObject_GetAttrString(mod, "Config");
  if (!cfg_cls) goto fail;
  cfg = PyObject_CallFunction(cfg_cls, "s", model_path_prefix);
  if (!cfg) goto fail;
  create = PyObject_GetAttrString(mod, "create_predictor");
  if (!create) goto fail;
  pred = PyObject_CallFunctionObjArgs(create, cfg, nullptr);
  if (!pred) goto fail;
  out = new PT_Predictor{pred};
  goto done;
fail:
  PyErr_Print();
done:
  Py_XDECREF(create);
  Py_XDECREF(cfg);
  Py_XDECREF(cfg_cls);
  Py_XDECREF(mod);
  PyGILState_Release(g);
  return out;
}

// inputs: n_inputs float32 buffers with shapes[i] of ndims[i] dims.
// Returns number of outputs (<0 on error); outputs returned via
// PT_GetOutput after a successful run.
int32_t PT_PredictorRun(PT_Predictor* p, const float* const* inputs,
                        const int64_t* const* shapes,
                        const int32_t* ndims, int32_t n_inputs) {
  if (!p || !p->predictor) return -1;
  PyGILState_STATE g = PyGILState_Ensure();
  int32_t rc = -1;
  PyObject *np = nullptr, *feed = nullptr, *outs = nullptr,
           *run = nullptr, *frombuf = nullptr;
  np = PyImport_ImportModule("numpy");
  if (!np) goto fail;
  feed = PyList_New(n_inputs);
  if (!feed) goto fail;
  for (int32_t i = 0; i < n_inputs; ++i) {
    int64_t numel = 1;
    for (int32_t d = 0; d < ndims[i]; ++d) numel *= shapes[i][d];
    // numpy.frombuffer(bytes, float32).reshape(shape).copy()
    PyObject* bytes = PyBytes_FromStringAndSize(
        reinterpret_cast<const char*>(inputs[i]),
        static_cast<Py_ssize_t>(numel * sizeof(float)));
    if (!bytes) goto fail;
    PyObject* arr = PyObject_CallMethod(np, "frombuffer", "Os", bytes,
                                        "float32");
    Py_DECREF(bytes);
    if (!arr) goto fail;
    PyObject* shape = PyTuple_New(ndims[i]);
    for (int32_t d = 0; d < ndims[i]; ++d)
      PyTuple_SET_ITEM(shape, d, PyLong_FromLongLong(shapes[i][d]));
    PyObject* reshaped = PyObject_CallMethod(arr, "reshape", "O", shape);
    Py_DECREF(shape);
    Py_DECREF(arr);
    if (!reshaped) goto fail;
    PyList_SET_ITEM(feed, i, reshaped);  // steals
  }
  outs = PyObject_CallMethod(p->predictor, "run", "O", feed);
  if (!outs) goto fail;
  // stash outputs on the predictor wrapper for PT_GetOutput
  if (PyObject_SetAttrString(p->predictor, "_capi_outputs", outs) < 0)
    goto fail;
  rc = static_cast<int32_t>(PySequence_Size(outs));
  goto done;
fail:
  PyErr_Print();
done:
  Py_XDECREF(outs);
  Py_XDECREF(feed);
  Py_XDECREF(np);
  Py_XDECREF(run);
  Py_XDECREF(frombuf);
  PyGILState_Release(g);
  return rc;
}

// Copy output idx into caller-managed PT_Output (free with PT_FreeOutput).
int32_t PT_GetOutput(PT_Predictor* p, int32_t idx, PT_Output* out) {
  if (!p || !p->predictor || !out) return -1;
  PyGILState_STATE g = PyGILState_Ensure();
  int32_t rc = -1;
  PyObject *outs = nullptr, *np = nullptr, *item = nullptr,
           *arr = nullptr, *ravel = nullptr, *bytes = nullptr;
  outs = PyObject_GetAttrString(p->predictor, "_capi_outputs");
  if (!outs) goto fail;
  item = PySequence_GetItem(outs, idx);
  if (!item) goto fail;
  np = PyImport_ImportModule("numpy");
  if (!np) goto fail;
  arr = PyObject_CallMethod(np, "ascontiguousarray", "O", item);
  if (!arr) goto fail;
  {
    PyObject* f32 = PyObject_CallMethod(arr, "astype", "s", "float32");
    if (!f32) goto fail;
    Py_DECREF(arr);
    arr = f32;
  }
  {
    PyObject* shape = PyObject_GetAttrString(arr, "shape");
    if (!shape) goto fail;
    Py_ssize_t nd = PyTuple_Size(shape);
    out->ndim = static_cast<int32_t>(nd);
    out->shape = new int64_t[nd > 0 ? nd : 1];
    out->numel = 1;
    for (Py_ssize_t d = 0; d < nd; ++d) {
      out->shape[d] = PyLong_AsLongLong(PyTuple_GET_ITEM(shape, d));
      out->numel *= out->shape[d];
    }
    Py_DECREF(shape);
  }
  bytes = PyObject_CallMethod(arr, "tobytes", nullptr);
  if (!bytes) goto fail;
  {
    char* src = nullptr;
    Py_ssize_t len = 0;
    PyBytes_AsStringAndSize(bytes, &src, &len);
    out->data = new float[len / sizeof(float)];
    std::memcpy(out->data, src, static_cast<size_t>(len));
  }
  rc = 0;
  goto done;
fail:
  PyErr_Print();
done:
  Py_XDECREF(bytes);
  Py_XDECREF(arr);
  Py_XDECREF(item);
  Py_XDECREF(np);
  Py_XDECREF(outs);
  PyGILState_Release(g);
  return rc;
}

void PT_FreeOutput(PT_Output* out) {
  if (!out) return;
  delete[] out->data;
  delete[] out->shape;
  out->data = nullptr;
  out->shape = nullptr;
}

void PT_DeletePredictor(PT_Predictor* p) {
  if (!p) return;
  PyGILState_STATE g = PyGILState_Ensure();
  Py_XDECREF(p->predictor);
  PyGILState_Release(g);
  delete p;
}

}  // extern "C"
