"""paddle.inference — the deployment/serving API.

Reference: paddle/fluid/inference/api/analysis_predictor.h:82 (ctor, Run
:120, ZeroCopyTensor handles :143-151) and paddle_infer::Config
(analysis_config.h).  TPU-native design: the artifact is the serialized
StableHLO written by ``paddle.jit.save`` / ``paddle.static.
save_inference_model`` (one deployable format for both sources); the
Predictor deserializes it once, AOT-compiles at load for the declared
input shapes, and serves each shape bucket from a compile cache with
donated input buffers — zero recompiles and zero host copies on the hot
path (the analog of the reference's ZeroCopyTensor path).
"""
from __future__ import annotations

import hashlib
import itertools
import pickle
from typing import Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..core import flags
from ..jit.save_load import SUFFIX_MODEL, SUFFIX_PARAMS
from ..utils import monitor

__all__ = ["Config", "Predictor", "Tensor", "create_predictor"]

_PAD_POLICIES = ("bucket", "none")


class Config:
    """reference: inference/api/paddle_analysis_config.h.

    ``Config(prog_file)`` or ``Config(prog_file, params_file)`` — pass the
    path prefix used at save time (the ``.pdmodel`` suffix is appended if
    missing).  GPU/IR-pass toggles are accepted for parity; XLA owns
    optimization on TPU."""

    def __init__(self, prog_file: Optional[str] = None,
                 params_file: Optional[str] = None):
        if prog_file is not None and prog_file.endswith(SUFFIX_MODEL):
            prog_file = prog_file[: -len(SUFFIX_MODEL)]
        self.prog_file = prog_file
        self.params_file = params_file
        self._shape_buckets: List[Tuple[Tuple[int, ...], ...]] = []
        self._aot_on_load = True
        self._pad_policy: Optional[str] = None  # None -> FLAGS default
        # parity no-ops (XLA owns these decisions on TPU)
        self._flags: Dict[str, object] = {}

    def set_prog_file(self, path: str):
        self.prog_file = path

    def model_dir(self):
        return self.prog_file

    def add_shape_bucket(self, *input_shapes: Sequence[int]):
        """Declare an input-shape combination to AOT-compile at load time
        (the reference's tuned TensorRT shape ranges, analysis_config.h
        EnableTunedTensorRtDynamicShape)."""
        self._shape_buckets.append(tuple(tuple(s) for s in input_shapes))

    def disable_aot_compile(self):
        self._aot_on_load = False

    def set_batch_pad_policy(self, policy: str):
        """What ``Predictor.run`` does with a batch size that has no
        compiled variant:

        - ``"bucket"`` (default, ``FLAGS_inference_pad_policy``): pad the
          leading dim up to the smallest compiled/declared bucket that
          fits — or the next power of two when none fits — run the padded
          batch, and slice the outputs back.  After warmup the hot path
          never recompiles; padded runs count ``inference.pad_hits``.
          Assumes row-independent models (standard for inference nets;
          a cross-batch reduction would see the zero pad rows).
        - ``"none"``: the legacy behavior — compile a fresh variant per
          distinct batch size.
        """
        if policy not in _PAD_POLICIES:
            raise ValueError(f"batch pad policy must be one of "
                             f"{_PAD_POLICIES}, got {policy!r}")
        self._pad_policy = policy

    def batch_pad_policy(self) -> str:
        return self._pad_policy or flags.get_flag("inference_pad_policy")

    # -- accepted-for-parity switches -------------------------------------
    def enable_use_gpu(self, *a, **k):
        self._flags["use_gpu"] = True

    def disable_gpu(self):
        self._flags["use_gpu"] = False

    def enable_memory_optim(self, *a, **k):
        self._flags["memory_optim"] = True

    def switch_ir_optim(self, x=True):
        self._flags["ir_optim"] = x

    def enable_mkldnn(self, *a, **k):
        pass

    def set_cpu_math_library_num_threads(self, n):
        self._flags["cpu_threads"] = n

    def summary(self) -> str:
        return (f"Config(prog_file={self.prog_file}, "
                f"buckets={len(self._shape_buckets)}, "
                f"pad_policy={self.batch_pad_policy()}, "
                f"flags={self._flags})")


class Tensor:
    """IO handle (reference: ZeroCopyTensor, analysis_predictor.h:143-151).
    ``copy_from_cpu`` stages the next input; ``copy_to_cpu`` fetches an
    output."""

    def __init__(self, name: str, predictor: "Predictor", is_input: bool):
        self.name = name
        self._p = predictor
        self._is_input = is_input

    def copy_from_cpu(self, arr):
        assert self._is_input, f"{self.name} is an output handle"
        self._p._inputs[self.name] = np.asarray(arr)
        self._p._external.discard(self.name)

    def share_external_data(self, arr):
        # zero-copy: caller keeps ownership, so this input is NOT donated
        self._p._inputs[self.name] = arr
        self._p._external.add(self.name)

    def reshape(self, shape):
        pass  # shape follows the staged array

    def copy_to_cpu(self):
        assert not self._is_input, f"{self.name} is an input handle"
        out = self._p._outputs[self.name]
        return np.asarray(out)

    def shape(self):
        src = (self._p._inputs if self._is_input else self._p._outputs)
        a = src.get(self.name)
        return list(a.shape) if a is not None else None


class Predictor:
    """reference: inference/api/analysis_predictor.h:82."""

    _SERIALS = itertools.count(1)

    def __init__(self, config: Config):
        self.config = config
        self._serial = f"predictor#{next(Predictor._SERIALS)}"
        if config.params_file:
            # weights are baked into the StableHLO artifact at save time;
            # a swapped .pdiparams cannot be injected — fail loudly rather
            # than silently serving stale weights
            import os
            sibling = config.prog_file + SUFFIX_PARAMS
            same = os.path.abspath(config.params_file) == os.path.abspath(
                sibling)
            if not same and os.path.exists(sibling):
                with open(config.params_file, "rb") as a, \
                        open(sibling, "rb") as b:
                    same = a.read() == b.read()
            if not same:
                raise ValueError(
                    "params_file differs from the weights captured in "
                    f"{config.prog_file}{SUFFIX_MODEL}; re-run jit.save/"
                    "save_inference_model with the new weights (the "
                    "artifact bakes them at export)")
        with open(config.prog_file + SUFFIX_MODEL, "rb") as f:
            raw = f.read()
        # content digest of the whole artifact (meta + StableHLO +
        # baked weights): the persistent compile cache keys on it, so
        # two processes serving the same artifact share executables
        # while a re-exported model (new weights, new graph) can never
        # collide with the old one
        self._artifact_digest = hashlib.sha256(raw).hexdigest()
        n = int.from_bytes(raw[:8], "little")
        self._meta = pickle.loads(raw[8:8 + n])
        self._exported = jax.export.deserialize(raw[8 + n:])
        m = self._meta
        self._input_names = list(
            m.get("feed_names")
            or [f"x{i}" for i in range(len(m["in_shapes"]))])
        self._output_names: Optional[List[str]] = (
            list(m["fetch_names"]) if m.get("fetch_names") else None)
        self._inputs: Dict[str, np.ndarray] = {}
        self._external: set = set()
        self._outputs: Dict[str, jnp.ndarray] = {}
        self._compiled: Dict[tuple, object] = {}
        self._compile_count = 0
        # batch buckets per rest-signature (shapes minus the leading dim):
        # every compiled/declared variant whose inputs share a leading dim
        # registers its batch size here, and the pad policy targets them
        self._batch_buckets: Dict[tuple, set] = {}
        self._batched_out_mask: object = False    # False = not computed
        if config._aot_on_load:
            self._aot_compile()

    # -- compile management ------------------------------------------------
    @staticmethod
    def _split_batch(shapes_dtypes):
        """(rest_key, batch) when every input shares a leading dim, else
        (None, None) — scalars or ragged leading dims can't be padded."""
        batches = {s[0] for s, _ in shapes_dtypes if len(s) >= 1}
        if len(batches) != 1 or any(len(s) < 1 for s, _ in shapes_dtypes):
            return None, None
        rest = tuple((s[1:], str(d)) for s, d in shapes_dtypes)
        return rest, batches.pop()

    def _register_bucket(self, shapes_dtypes):
        rest, batch = self._split_batch(shapes_dtypes)
        if rest is not None:
            self._batch_buckets.setdefault(rest, set()).add(batch)

    def batched_output_mask(self) -> Optional[List[bool]]:
        """Which outputs carry the batch dim, from the artifact itself:
        a shape-polymorphic export names the batch dim symbolically in
        ``out_avals``, so outputs whose leading dim is that symbol are
        exactly the ones to slice after a padded run.  None when the
        artifact is fully static (no symbol to track) — callers fall
        back to a shape heuristic."""
        if self._batched_out_mask is False:
            mask = None
            try:
                in_sym = any(not isinstance(d, (int, np.integer))
                             for a in self._exported.in_avals
                             for d in a.shape)
                if in_sym:
                    mask = [len(a.shape) >= 1
                            and not isinstance(a.shape[0],
                                               (int, np.integer))
                            for a in self._exported.out_avals]
            except Exception:   # exported object without aval metadata
                mask = None
            self._batched_out_mask = mask
        return self._batched_out_mask

    def _pick_bucket(self, rest, batch) -> int:
        """Smallest known bucket that fits, else the next power of two."""
        fitting = [b for b in self._batch_buckets.get(rest, ())
                   if b >= batch]
        if fitting:
            return min(fitting)
        return 1 << (batch - 1).bit_length()

    def _lowered(self, shapes_dtypes, no_donate=frozenset(),
                 from_run=False):
        key = (tuple(shapes_dtypes), frozenset(no_donate))
        fn = self._compiled.get(key)
        if fn is None:
            self._compile_count += 1
            if from_run:
                monitor.stat_add("inference.compile_misses")
            call = self._exported.call
            # donate predictor-staged inputs on TPU (single-use per call);
            # share_external_data buffers stay caller-owned (CPU backend
            # can't alias either way and would only warn)
            donate = (tuple(i for i, n in enumerate(self._input_names)
                            if n not in no_donate)
                      if jax.default_backend() == "tpu" else ())
            def build():
                f = jax.jit(lambda *a: call(*a), donate_argnums=donate)
                avals = [jax.ShapeDtypeStruct(s, d)
                         for s, d in shapes_dtypes]
                return f.lower(*avals).compile()  # AOT: no serve trace

            # persistent AOT cache (FLAGS_compile_cache_dir): keyed by
            # the artifact's content digest + this bucket's signature —
            # a warm cold start deserializes instead of compiling, and
            # the provenance ("loaded"/"compiled") rides the compile
            # record so explain_compiles() shows which happened
            from ..core import compile_cache
            fn, cache_prov = compile_cache.cached_compile("predictor", {
                "artifact": self._artifact_digest,
                "bucket": tuple((tuple(s), str(d))
                                for s, d in shapes_dtypes),
                "donate": donate,
            }, build)
            self._compiled[key] = fn
            self._register_bucket(shapes_dtypes)
            # recompile attribution AFTER the lower/compile succeeded —
            # a failing (and retried) compile must not record identical
            # signatures and read as "unexplained".  After load, every
            # further compile is a new shape bucket (or donation-set
            # change).
            from ..observability import record_compile
            record_compile("predictor", self._serial, {
                "bucket": tuple(shapes_dtypes),
                "undonated_inputs": tuple(sorted(no_donate)),
            }, note="serve-path miss" if from_run else "aot",
                cache=cache_prov)
        return fn

    def _aot_compile(self):
        """Compile at load for declared buckets, plus the saved example
        shapes when they are fully static.  Dtypes are canonicalized
        exactly as run() does (i64->i32 / f64->f32 under x64-disabled
        jax), so serve-time lookups hit these variants."""
        canon = jax.dtypes.canonicalize_dtype
        for bucket in self.config._shape_buckets:
            sd = [(tuple(s), canon(np.dtype(d))) for s, d in
                  zip(bucket, self._meta["in_dtypes"])]
            self._lowered(sd)
        try:
            shapes = [tuple(int(d) for d in s)
                      for s in self._meta["in_shapes"]]
        except ValueError:
            return  # symbolic dims: compile per served shape
        sd = [(s, canon(np.dtype(d)))
              for s, d in zip(shapes, self._meta["in_dtypes"])]
        self._lowered(sd)

    # -- handle API --------------------------------------------------------
    def get_input_names(self) -> List[str]:
        return list(self._input_names)

    def get_output_names(self) -> List[str]:
        if self._output_names is not None:
            return list(self._output_names)
        return [f"out{i}" for i in range(len(self._outputs) or 1)]

    def get_input_handle(self, name: str) -> Tensor:
        return Tensor(name, self, is_input=True)

    def get_output_handle(self, name: str) -> Tensor:
        return Tensor(name, self, is_input=False)

    # -- execution ---------------------------------------------------------
    def run(self, inputs: Optional[Sequence] = None):
        """Serve one batch.  ``run([arr, ...])`` or stage via input
        handles first.  Returns the output list (also readable through
        output handles).

        A batch size with no compiled variant is padded up to a bucket
        (and the outputs sliced back) under the default ``"bucket"``
        policy — see :meth:`Config.set_batch_pad_policy`.
        """
        if inputs is not None:
            for n, a in zip(self._input_names, inputs):
                self._inputs[n] = np.asarray(a)
        raw = []
        for n in self._input_names:
            if n not in self._inputs:
                raise ValueError(f"input '{n}' not staged; call "
                                 f"get_input_handle('{n}').copy_from_cpu()")
            a = self._inputs[n]
            if not hasattr(a, "dtype"):     # share_external_data may
                a = np.asarray(a)           # stage a bare list/tuple
            raw.append(a)
        # the signature must match what jnp.asarray will produce below
        # (x64-disabled jax canonicalizes f64->f32, i64->i32)
        canon = jax.dtypes.canonicalize_dtype
        sd = tuple((tuple(np.shape(a)), canon(np.dtype(a.dtype)))
                   for a in raw)
        key = (sd, frozenset(self._external))
        n_real = None
        if key not in self._compiled \
                and self.config.batch_pad_policy() == "bucket":
            rest, batch = self._split_batch(sd)
            if rest is not None:
                target = self._pick_bucket(rest, batch)
                if target != batch:
                    raw = [np.concatenate(
                        [a, np.zeros((target - batch,) + tuple(
                            np.shape(a)[1:]), dtype=a.dtype)])
                        for a in raw]
                    sd = tuple((tuple(a.shape), canon(np.dtype(a.dtype)))
                               for a in raw)
                    n_real, n_padded = batch, target
                    if (sd, frozenset(self._external)) in self._compiled:
                        monitor.stat_add("inference.pad_hits")
        args = [jnp.asarray(a) for a in raw]
        fn = self._lowered(sd, no_donate=self._external, from_run=True)
        outs = fn(*args)
        if not isinstance(outs, (list, tuple)):
            outs = [outs]
        if n_real is not None:
            mask = self.batched_output_mask()
            outs = [o[:n_real]
                    if (getattr(o, "ndim", 0) >= 1
                        and (mask[i] if mask is not None and i < len(mask)
                             else o.shape[0] == n_padded)) else o
                    for i, o in enumerate(outs)]
        names = (self._output_names
                 or [f"out{i}" for i in range(len(outs))])
        self._outputs = dict(zip(names, outs))
        self._output_names = names
        return list(outs)

    def num_compiled_variants(self) -> int:
        """Observability: distinct shape buckets compiled so far."""
        return self._compile_count


def create_predictor(config: Config) -> Predictor:
    """reference: paddle_infer::CreatePredictor."""
    return Predictor(config)
