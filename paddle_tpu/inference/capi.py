"""Build + load the C-ABI predictor shim (csrc/predictor_capi.cpp).

Reference: fluid/inference/capi/paddle_c_api.h + go/paddle/predictor.go —
a C surface any language with FFI (Go, Rust, C#) can bind.  Here the shim
embeds CPython and drives the Python Predictor (the XLA AOT executable);
this module compiles it on demand and exposes a ctypes harness that both
tests it and documents the calling convention external programs use.
"""
from __future__ import annotations

import ctypes
import os
import sysconfig
import threading
from typing import List, Sequence

import numpy as np

_CSRC = os.path.join(os.path.dirname(__file__), "csrc")
_LOCK = threading.Lock()
_LIB = None
_LIB_TRIED = False


class PT_Output(ctypes.Structure):
    _fields_ = [("data", ctypes.POINTER(ctypes.c_float)),
                ("shape", ctypes.POINTER(ctypes.c_int64)),
                ("ndim", ctypes.c_int32),
                ("numel", ctypes.c_int64)]


def load_capi():
    """Compile (once) and dlopen the C ABI; raises on failure (the C
    surface is an explicit product feature, not a soft fallback)."""
    global _LIB, _LIB_TRIED
    with _LOCK:
        if _LIB_TRIED:
            if _LIB is None:
                raise RuntimeError("paddle_tpu C ABI failed to build "
                                   "earlier in this process")
            return _LIB
        _LIB_TRIED = True
        src = os.path.join(_CSRC, "predictor_capi.cpp")
        hdr = os.path.join(_CSRC, "paddle_tpu_capi.h")
        so = os.path.join(_CSRC, "libpaddle_tpu_capi.so")
        inc = sysconfig.get_path("include")
        ver = f"{os.sys.version_info.major}.{os.sys.version_info.minor}"
        libdir = sysconfig.get_config_var("LIBDIR") or ""
        newest_src = max((os.path.getmtime(f) for f in (src, hdr)
                          if os.path.exists(f)), default=0.0)
        if os.path.exists(src) and (
                not os.path.exists(so)
                or os.path.getmtime(so) < newest_src):
            from ..utils.native_build import build_shared_lib
            build_shared_lib(
                ["g++", "-O2", "-std=c++17", "-shared", "-fPIC",
                 f"-I{inc}"],
                [src, f"-L{libdir}", f"-lpython{ver}"], so,
                what="C ABI build")
        lib = ctypes.CDLL(so, mode=ctypes.RTLD_GLOBAL)
        lib.PT_NewPredictor.restype = ctypes.c_void_p
        lib.PT_NewPredictor.argtypes = [ctypes.c_char_p]
        lib.PT_PredictorRun.restype = ctypes.c_int32
        lib.PT_PredictorRun.argtypes = [
            ctypes.c_void_p,
            ctypes.POINTER(ctypes.POINTER(ctypes.c_float)),
            ctypes.POINTER(ctypes.POINTER(ctypes.c_int64)),
            ctypes.POINTER(ctypes.c_int32), ctypes.c_int32]
        lib.PT_GetOutput.restype = ctypes.c_int32
        lib.PT_GetOutput.argtypes = [ctypes.c_void_p, ctypes.c_int32,
                                     ctypes.POINTER(PT_Output)]
        lib.PT_FreeOutput.argtypes = [ctypes.POINTER(PT_Output)]
        lib.PT_DeletePredictor.argtypes = [ctypes.c_void_p]
        _LIB = lib
        return _LIB


class CPredictor:
    """ctypes harness over the C ABI (what predictor.go would be in Go)."""

    def __init__(self, model_path_prefix: str):
        self._lib = load_capi()
        self._h = self._lib.PT_NewPredictor(
            model_path_prefix.encode("utf-8"))
        if not self._h:
            raise RuntimeError(
                f"PT_NewPredictor failed for '{model_path_prefix}'")

    def run(self, inputs: Sequence[np.ndarray]) -> List[np.ndarray]:
        lib = self._lib
        arrs = [np.ascontiguousarray(a, np.float32) for a in inputs]
        n = len(arrs)
        bufs = (ctypes.POINTER(ctypes.c_float) * n)(
            *[a.ctypes.data_as(ctypes.POINTER(ctypes.c_float))
              for a in arrs])
        shapes_store = [(ctypes.c_int64 * a.ndim)(*a.shape) for a in arrs]
        shapes = (ctypes.POINTER(ctypes.c_int64) * n)(
            *[ctypes.cast(s, ctypes.POINTER(ctypes.c_int64))
              for s in shapes_store])
        ndims = (ctypes.c_int32 * n)(*[a.ndim for a in arrs])
        n_out = lib.PT_PredictorRun(self._h, bufs, shapes, ndims, n)
        if n_out < 0:
            raise RuntimeError("PT_PredictorRun failed")
        outs = []
        for i in range(n_out):
            o = PT_Output()
            if lib.PT_GetOutput(self._h, i, ctypes.byref(o)) != 0:
                raise RuntimeError(f"PT_GetOutput({i}) failed")
            shape = tuple(o.shape[d] for d in range(o.ndim))
            arr = np.ctypeslib.as_array(o.data, shape=(o.numel,)).copy()
            outs.append(arr.reshape(shape))
            lib.PT_FreeOutput(ctypes.byref(o))
        return outs

    def close(self):
        if getattr(self, "_h", None):
            self._lib.PT_DeletePredictor(self._h)
            self._h = None

    def __del__(self):
        try:
            self.close()
        except Exception:
            pass
