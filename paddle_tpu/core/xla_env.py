"""XLA_FLAGS knobs that must precede backend initialisation.

XLA parses the ``XLA_FLAGS`` environment variable once, when the
backend client is created — and it aborts the process on flags its
build does not define (the CPU jaxlib, for example, knows the
``--xla_gpu_*`` family but dies on ``--xla_tpu_*``).  So anything that
wants to turn on the latency-hiding scheduler has to (a) run before
the first device query and (b) only append flags the target platform's
build actually defines.  ``paddle_tpu/__init__.py`` calls
:func:`apply_latency_hiding_flags` at import, gated on
``FLAGS_xla_latency_hiding`` (read from the environment — by the time
a ``set_flags()`` call could flip it, the backend usually exists).

Why this knob exists: the grad-comm stage (``distributed/grad_comm``)
emits each gradient bucket's collective dependent only on that
bucket's grads.  On TPU/GPU, XLA's latency-hiding scheduler is what
turns those into async start/done pairs hoisted across the remaining
backward compute — without it the compiler schedules collectives
roughly where they appear, and ``overlap='auto'`` falls back to the
explicit ppermute-chunked ring lowering instead.  On CPU there is no
such scheduler and nothing overlaps at all, so auto keeps the fused
per-bucket collectives (``overlap='ring'`` still forces the chunked
lowering for testing).  Path resolution asks
:func:`latency_hiding_active` — what actually reached ``XLA_FLAGS`` —
never the raw flag value.
"""
from __future__ import annotations

import os
from typing import List, Optional

__all__ = ["apply_latency_hiding_flags", "latency_hiding_active",
           "LATENCY_HIDING_FLAGS"]

# per-platform scheduler flags — only ever appended for the platform
# the process is about to initialise, because an unknown flag in
# XLA_FLAGS is a FATAL parse error, not a warning
LATENCY_HIDING_FLAGS = {
    "tpu": ("--xla_tpu_enable_latency_hiding_scheduler=true",),
    "gpu": ("--xla_gpu_enable_latency_hiding_scheduler=true",),
    "cuda": ("--xla_gpu_enable_latency_hiding_scheduler=true",),
}


def _spec_present(*modules: str) -> bool:
    import importlib.util
    for mod in modules:
        try:
            if importlib.util.find_spec(mod) is not None:
                return True
        except (ImportError, ValueError):
            continue
    return False


def _target_platform() -> str:
    """The platform jax will initialise: the first entry of
    ``JAX_PLATFORMS`` when the user pinned one, else ``tpu`` when a
    libtpu is importable / ``gpu`` when a CUDA plugin is (the wheel's
    presence is what makes jax pick the backend), else ``cpu``."""
    plats = os.environ.get("JAX_PLATFORMS") or os.environ.get(
        "JAX_PLATFORM_NAME", "")
    first = plats.split(",")[0].strip().lower()
    if first:
        return first
    if _spec_present("libtpu"):
        return "tpu"
    if _spec_present("jax_cuda12_plugin", "jax_cuda11_plugin",
                     "jax_plugins.xla_cuda12"):
        return "gpu"
    return "cpu"


def latency_hiding_active(platform: str) -> bool:
    """Whether the latency-hiding scheduler flags for ``platform`` are
    actually IN ``XLA_FLAGS`` — the question grad_comm's overlap path
    resolution asks.  Deliberately not the raw ``FLAGS_xla_latency_
    hiding`` value: the knob can be requested and still never applied
    (set after backend init, or on a platform the detector missed), in
    which case compiling the fused path and calling its comm "hidden"
    would be a lie — the ring fallback is the right lowering then.
    Flags a user appended to ``XLA_FLAGS`` by hand count too."""
    current = os.environ.get("XLA_FLAGS", "")
    wanted = LATENCY_HIDING_FLAGS.get((platform or "").lower(), ())
    return bool(wanted) and all(f in current for f in wanted)


def _backend_initialized() -> bool:
    """Whether XLA has already parsed XLA_FLAGS (backend client
    exists) — appending after that is a silent no-op."""
    try:
        import jax._src.xla_bridge as _xb
        return bool(getattr(_xb, "_backends", None))
    except Exception:  # noqa: BLE001 - private API moved; assume early
        return False


def apply_latency_hiding_flags(platform: Optional[str] = None
                               ) -> List[str]:
    """Append the latency-hiding scheduler flags for ``platform``
    (auto-detected when None) to ``XLA_FLAGS`` — if
    ``FLAGS_xla_latency_hiding`` asks for it and the backend has not
    been created yet.  Returns the flags actually appended (empty when
    off, already present, unsupported platform, or too late).
    Idempotent: flags already in ``XLA_FLAGS`` are never duplicated."""
    from . import flags as _flags
    if not _flags.get_flag("xla_latency_hiding"):
        return []
    plat = (platform or _target_platform()).lower()
    wanted = LATENCY_HIDING_FLAGS.get(plat, ())
    if not wanted:
        return []
    current = os.environ.get("XLA_FLAGS", "")
    add = [f for f in wanted if f not in current]
    if not add:
        return []
    if _backend_initialized():
        import warnings
        warnings.warn(
            "FLAGS_xla_latency_hiding was requested after the jax "
            "backend initialised — XLA_FLAGS is parsed once at backend "
            "creation, so the latency-hiding scheduler flags cannot be "
            "applied to this process.  Set FLAGS_xla_latency_hiding=1 "
            "in the environment before the first jax device query "
            "(the supervisor's child_env is the right place for "
            "supervised training).", RuntimeWarning)
        return []
    os.environ["XLA_FLAGS"] = (current + " " + " ".join(add)).strip()
    return add
