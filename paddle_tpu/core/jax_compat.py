"""jax version-compatibility shims.

The container pins whatever jax the TPU runtime ships; the source tracks
current jax spellings.  Differences are absorbed here, in one place:

- ``shard_map`` moved from ``jax.experimental.shard_map`` to the top
  level in jax 0.5;
- its replication-check kwarg was renamed ``check_rep`` → ``check_vma``
  (jax 0.6).  Callers use the new name; older jax gets it translated;
- ``jax.lax.axis_size`` (jax 0.6) falls back to ``jax.core.axis_frame``
  inside a bound axis context;
- ``jax.lax.pvary`` falls back to identity (only the new varying-type
  checker needs the annotation; we run with it disabled);
- ``jax.ffi`` (jax 0.5) falls back to ``jax.extend.ffi`` — same
  surface (ffi_call / include_dir / register_ffi_target / pycapsule);
- AOT executable serialization lives behind
  :func:`serialize_executable` / :func:`deserialize_executable`
  (``jax.experimental.serialize_executable`` today) so the persistent
  compile cache (core/compile_cache.py) has exactly one seam to absorb
  the next module move.
"""
from __future__ import annotations

import inspect

import jax

try:
    from jax import shard_map as _shard_map  # jax >= 0.5
except ImportError:
    from jax.experimental.shard_map import shard_map as _shard_map

if hasattr(jax, "ffi"):
    ffi = jax.ffi
else:  # jax < 0.5
    from jax.extend import ffi  # noqa: F401

_PARAMS = frozenset(inspect.signature(_shard_map).parameters)


def shard_map(f, *, mesh, in_specs, out_specs, check_vma=None, **kw):
    if check_vma is not None:
        if "check_vma" in _PARAMS:
            kw["check_vma"] = check_vma
        elif "check_rep" in _PARAMS:  # pre-rename spelling
            kw["check_rep"] = check_vma
    return _shard_map(f, mesh=mesh, in_specs=in_specs,
                      out_specs=out_specs, **kw)


def distributed_is_initialized() -> bool:
    """``jax.distributed.is_initialized()`` (jax 0.5); older jax probes
    the coordination-service client directly.  Never initialises the
    backend (that would break the rendezvous this probe guards)."""
    fn = getattr(jax.distributed, "is_initialized", None)
    if fn is not None:
        return bool(fn())
    try:
        from jax._src.distributed import global_state
        return global_state.client is not None
    except Exception:  # pragma: no cover - internal layout changed
        return False


def axis_size(name) -> int:
    """Concrete size of a bound mesh axis (inside shard_map)."""
    fn = getattr(jax.lax, "axis_size", None)
    if fn is not None:
        return fn(name)
    frame = jax.core.axis_frame(name)  # older jax: frame or bare int
    return getattr(frame, "size", frame)


def executable_serialization_available() -> bool:
    """Whether this jax can round-trip compiled executables at all."""
    try:
        from jax.experimental import serialize_executable  # noqa: F401
        return True
    except ImportError:
        return False


def serialize_executable(compiled):
    """``(payload_bytes, in_tree, out_tree)`` for a ``lower().compile()``
    result.  The trees are picklable pytree defs; donation and static
    shapes ride the payload.  This is OUR serialization path — jax's
    persistent compilation cache stays off (it heap-corrupts reloading
    NamedSharding executables on jaxlib 0.4.37)."""
    from jax.experimental.serialize_executable import serialize
    return serialize(compiled)


def deserialize_executable(payload, in_tree, out_tree):
    """Rebuild a callable ``Compiled`` from :func:`serialize_executable`
    output on the current backend.  Raises on any incompatibility —
    callers (compile_cache) treat every failure as a cache reject and
    fall back to a fresh compile."""
    from jax.experimental.serialize_executable import deserialize_and_load
    return deserialize_and_load(payload, in_tree, out_tree)


def pvary(x, axis_name):
    """Mark ``x`` device-varying over ``axis_name`` for the replication
    checker; identity on jax without varying types (checker disabled)."""
    pcast = getattr(jax.lax, "pcast", None)  # jax >= 0.9 spelling
    if pcast is not None:
        return pcast(x, axis_name, to="varying")
    fn = getattr(jax.lax, "pvary", None)
    if fn is not None:
        return fn(x, axis_name)
    return x
