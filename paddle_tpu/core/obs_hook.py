"""Observability hook shared by every instrumented layer.

Lives in core so hot paths (eager dispatch, Executor.run, the serving
dispatcher) pay ONE module-attribute None-check when tracing is off —
the same gating pattern as :mod:`core.profiler_hook`.  Instrumented
sites read ``obs_hook._tracer`` directly (a single LOAD_ATTR, no call)
and only touch the tracer object when it is not None; the crash hook
``_crash`` gates the flight recorder the same way.

This module must stay import-free: it is pulled in by core, utils, io
and serving alike, and a single stray import here would cycle."""
from __future__ import annotations

_tracer = None      # paddle_tpu.observability.Tracer when enabled
_crash = None       # callable(exc, context_str) when a flight
                    # recorder is installed
_perf = None        # paddle_tpu.observability.perf.PerfObservatory
                    # when the runtime performance observatory is on
_heartbeat = None   # paddle_tpu.distributed.supervisor.HeartbeatWriter
                    # when this process runs under a TrainingSupervisor
_anomaly = None     # paddle_tpu.distributed.anomaly.AnomalyPolicy when
                    # a data-plane anomaly policy is installed
_export = None      # paddle_tpu.observability.export.TelemetryExporter
                    # when this process spools telemetry for the fleet
                    # aggregator (FLAGS_obs_spool_dir)


def set_tracer(tracer) -> None:
    global _tracer
    _tracer = tracer


def current():
    return _tracer


def set_perf(perf) -> None:
    global _perf
    _perf = perf


def current_perf():
    return _perf


def set_crash_handler(fn) -> None:
    global _crash
    _crash = fn


def crash_handler():
    return _crash


def set_heartbeat(hb) -> None:
    global _heartbeat
    _heartbeat = hb


def current_heartbeat():
    return _heartbeat


def set_anomaly_policy(policy) -> None:
    global _anomaly
    _anomaly = policy


def current_anomaly_policy():
    return _anomaly


def set_export(exporter) -> None:
    global _export
    _export = exporter


def current_export():
    return _export
