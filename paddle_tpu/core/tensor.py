"""The Tensor type.

TPU-native analog of the reference's ``framework::Tensor`` + dygraph
``VarBase`` (reference: paddle/fluid/framework/tensor.h:89,
imperative/layer.cc).  A Tensor is a thin named wrapper over a ``jax.Array``
(or a jax tracer during ``to_static`` tracing) carrying autograd metadata:

- ``stop_gradient`` (paddle semantics: default True; Parameters default False)
- ``grad`` — accumulated leaf gradient deposited by the tape sweep
- ``_bw_id`` — unique id keying cotangent accumulation during backward

There is no LoD: variable-length sequences are handled by padding/masking and
ragged Pallas kernels (SURVEY §7 hard-parts), which is the honest TPU design —
XLA requires static shapes.

Most math/manipulation methods are monkey-patched from ``paddle_tpu.ops``
(mirroring how the reference patches methods onto VarBase in
python/paddle/fluid/dygraph/varbase_patch_methods.py).
"""
from __future__ import annotations

import itertools
from typing import Any, List, Optional

import jax
import jax.numpy as jnp
import numpy as np

from . import autograd
from .dtype import convert_dtype, dtype_name, get_default_dtype

_bw_counter = itertools.count(1)
_name_counter = itertools.count(0)


class Tensor:
    __slots__ = ("data", "stop_gradient", "name", "persistable", "_bw_id",
                 "_produced", "_node", "_grad_data", "_backward_hooks",
                 "trainable", "placement", "__weakref__")

    def __init__(self, data, stop_gradient: bool = True, name: str | None = None,
                 persistable: bool = False, _produced: bool = False):
        if isinstance(data, Tensor):
            data = data.data
        if not isinstance(data, (jax.Array,)) and not hasattr(data, "aval"):
            data = jnp.asarray(data)
        self.data = data
        self.stop_gradient = stop_gradient
        self.name = name if name is not None else f"tensor_{next(_name_counter)}"
        self.persistable = persistable
        self._bw_id = next(_bw_counter)
        self._produced = _produced
        self._node = None
        self._grad_data = None
        self._backward_hooks: List = []
        self.trainable = not stop_gradient
        self.placement = None  # PartitionSpec set by parallel.set_placement

    # -- basic metadata ----------------------------------------------------
    @property
    def shape(self) -> List[int]:
        return list(self.data.shape)

    @property
    def shape_tuple(self):
        return tuple(self.data.shape)

    @property
    def dtype(self):
        return self.data.dtype

    @property
    def ndim(self) -> int:
        return self.data.ndim

    rank = ndim

    @property
    def size(self) -> int:
        return int(np.prod(self.data.shape)) if self.data.shape else 1

    @property
    def place(self) -> str:
        try:
            devs = self.data.devices()
            return str(next(iter(devs)))
        except Exception:
            return "traced"

    @property
    def is_leaf(self) -> bool:
        return not self._produced

    def __len__(self):
        if self.ndim == 0:
            raise TypeError("len() of a 0-d tensor")
        return self.data.shape[0]

    def __repr__(self):
        sg = self.stop_gradient
        try:
            body = np.array2string(np.asarray(self.data), precision=8,
                                   separator=", ")
        except Exception:
            body = f"<traced {self.data}>"
        return (f"Tensor(shape={self.shape}, dtype={dtype_name(self.dtype)}, "
                f"stop_gradient={sg},\n       {body})")

    # -- host interop ------------------------------------------------------
    def numpy(self) -> np.ndarray:
        return np.asarray(self.data)

    def item(self, *args):
        return np.asarray(self.data).item(*args)

    def tolist(self):
        return np.asarray(self.data).tolist()

    def __float__(self):
        return float(np.asarray(self.data))

    def __int__(self):
        return int(np.asarray(self.data))

    def __bool__(self):
        if isinstance(self.data, jax.core.Tracer):
            raise TypeError(
                "[operator < bool > error] Python `if`/`while` tested a "
                "traced Tensor inside paddle.jit.to_static / a compiled "
                "step; the branch cannot be resolved at trace time and "
                "would silently freeze one path into the program. Use "
                "paddle.cond / paddle.where for branches, "
                "paddle.while_loop for loops, or mark the function "
                "non-static.")
        return bool(np.asarray(self.data))

    def __index__(self):
        return int(np.asarray(self.data))

    def __array__(self, dtype=None):
        a = np.asarray(self.data)
        return a.astype(dtype) if dtype is not None else a

    # -- autograd ----------------------------------------------------------
    @property
    def grad(self) -> Optional["Tensor"]:
        if self._grad_data is None:
            return None
        g = self._grad_data
        from .selected_rows import SelectedRows
        if isinstance(g, SelectedRows):
            # user-facing view densifies (reference pybind does the same
            # via get_tensor_from_selected_rows); optimizers read the
            # sparse _grad_data directly
            g = g.to_dense()
        return Tensor(g, stop_gradient=True, name=self.name + "@GRAD")

    @grad.setter
    def grad(self, value):
        self._grad_data = None if value is None else (
            value.data if isinstance(value, Tensor) else jnp.asarray(value))

    def backward(self, grad_tensor=None, retain_graph: bool = False):
        autograd.backward(self, grad_tensor, retain_graph)

    def clear_grad(self):
        self._grad_data = None

    clear_gradient = clear_grad

    def register_hook(self, hook):
        """Run ``hook(grad)`` when this tensor's gradient flows (dygraph)."""
        self._backward_hooks.append(hook)

        class _Handle:
            def remove(h):
                try:
                    self._backward_hooks.remove(hook)
                except ValueError:
                    pass
        return _Handle()

    def detach(self) -> "Tensor":
        return Tensor(self.data, stop_gradient=True, name=self.name + ".detach")

    def clone(self) -> "Tensor":
        from .dispatch import apply
        return apply(jnp.copy, self, op_name="clone")

    # -- in-place-style helpers (functional under the hood) ---------------
    def _rebind(self, other: "Tensor"):
        """Make self an alias of ``other``'s value+autograd position.

        Used by __setitem__ and in-place APIs: XLA is functional, so "in
        place" means producing a new value and re-pointing this Python
        identity at it (reference keeps inplace version counters instead,
        tensor.h:77-87).
        """
        self.data = other.data
        self._bw_id = other._bw_id
        self._produced = other._produced
        self._node = other._node
        self.stop_gradient = other.stop_gradient

    def set_value(self, value):
        v = value.data if isinstance(value, Tensor) else jnp.asarray(value)
        if tuple(v.shape) != self.shape_tuple:
            raise ValueError(
                f"set_value shape mismatch: {list(v.shape)} vs {self.shape}")
        self.data = v.astype(self.data.dtype)
        return self

    def zero_(self):
        self.data = jnp.zeros_like(self.data)
        return self

    def fill_(self, value):
        self.data = jnp.full_like(self.data, value)
        return self

    # -- dtype/shape fundamentals (more patched in from ops) ---------------
    def astype(self, dtype) -> "Tensor":
        from .dispatch import apply
        d = convert_dtype(dtype)
        return apply(lambda x: x.astype(d), self, op_name="cast")

    cast = astype

    def cpu(self):
        return Tensor(jax.device_put(self.data, jax.devices("cpu")[0]),
                      stop_gradient=self.stop_gradient, name=self.name)

    def pin_memory(self):
        return self

    def cuda(self, *a, **k):  # parity shim: "accelerator" means TPU here
        return self


# raw storage descriptor of Tensor.data — Parameter overrides ``data``
# with a property that resolves through a static Executor's
# device-resident state, but the bytes still live in this slot
_TENSOR_DATA_SLOT = Tensor.data


class Parameter(Tensor):
    """Trainable tensor (reference: python/paddle/fluid/framework.py Parameter)."""
    # _param_owner_step: weakref to a compiled step that holds the
    # authoritative value (ZeRO-3 padded shards / LocalSGD replicas);
    # Layer.state_dict syncs through it before reading p.data
    __slots__ = ("regularizer", "need_clip", "optimize_attr",
                 "is_distributed", "_param_owner_step", "_exec_src")

    def __init__(self, data, name=None, trainable=True, regularizer=None,
                 need_clip=True):
        # must precede super().__init__: the ``data`` property setter
        # below reads it while Tensor.__init__ assigns self.data
        self._exec_src = None
        super().__init__(data, stop_gradient=not trainable, name=name,
                         persistable=True)
        self.trainable = trainable
        self.regularizer = regularizer
        self.need_clip = need_clip
        self.optimize_attr = {"learning_rate": 1.0}
        self.is_distributed = False

    # -- executor-resident storage (static hot path) -----------------------
    # While a static Executor trains this Parameter's Program, the
    # authoritative value lives in the Executor's device-resident state
    # (static/executor.py _ExecState) and is threaded run-to-run through
    # one donated XLA program; ``_exec_src`` is (state, index) while
    # bound.  Reads resolve through the live state — and mark the array
    # as escaped, so the next donated run copies that slot instead of
    # invalidating the user-held reference.  Direct writes unbind this
    # Parameter and tell the state to reload from the slot on its next
    # run.  Unbound Parameters (eager mode) pay one extra None-check.
    @property
    def data(self):
        src = getattr(self, "_exec_src", None)
        if src is not None:
            return src[0].fetch_param(src[1])
        return _TENSOR_DATA_SLOT.__get__(self)

    @data.setter
    def data(self, value):
        src = getattr(self, "_exec_src", None)
        if src is not None:
            self._exec_src = None
            src[0].param_written(src[1])
        _TENSOR_DATA_SLOT.__set__(self, value)

    def __getstate__(self):
        # pickle/deepcopy: materialise the executor-resident value; the
        # state binding is process-local and never serialised or copied
        d = {}
        for cls in type(self).__mro__:
            for s in getattr(cls, "__slots__", ()):
                if s in ("__weakref__", "_exec_src", "data"):
                    continue
                try:
                    d[s] = getattr(self, s)
                except AttributeError:
                    pass
        d["data"] = self.data
        return (None, d)

    def __setstate__(self, state):
        d = state[1] if isinstance(state, tuple) else state
        self._exec_src = None
        for k, v in d.items():
            setattr(self, k, v)

    def __repr__(self):
        return "Parameter containing:\n" + super().__repr__()


def to_tensor(data, dtype=None, place=None, stop_gradient=True) -> Tensor:
    """paddle.to_tensor parity."""
    if isinstance(data, Tensor):
        d = data.data
    else:
        d = data
    dt = convert_dtype(dtype)
    if dt is None and not hasattr(d, "dtype"):
        # python scalars/lists: follow default dtype for floats
        a = np.asarray(d)
        if a.dtype == np.float64:
            dt = get_default_dtype()
        elif a.dtype == np.int64:
            dt = jnp.int64 if jax.config.read("jax_enable_x64") else jnp.int32
    arr = jnp.asarray(d, dtype=dt) if dt is not None else jnp.asarray(d)
    return Tensor(arr, stop_gradient=stop_gradient)
