"""paddle_tpu.core — substrate: dtype policy, flags, errors, RNG, Tensor,
autograd tape, and the shared op-dispatch point (SURVEY §7 step 1-2)."""
from . import autograd, dispatch, dtype, enforce, flags, rng  # noqa: F401
from .autograd import enable_grad, grad, no_grad  # noqa: F401
from .dtype import (get_default_dtype, set_default_dtype)  # noqa: F401
from .flags import get_flags, set_flags  # noqa: F401
from .rng import get_rng_state, seed, set_rng_state  # noqa: F401
from .tensor import Parameter, Tensor, to_tensor  # noqa: F401
