"""Replay-scope hook shared between core.dispatch and the static package.

Lives in core so the eager op hot path can check it with one function
call instead of importing the static package.  See
static/program.py for the design (composite control-flow replay)."""
from __future__ import annotations

import threading
from typing import Callable, Optional

_tls = threading.local()


class replay_scope:
    """While active, symbolic Variables (and Parameters, inside a compiled
    Program) resolve through ``lookup`` at the dispatch point instead of
    being recorded / read eagerly."""

    def __init__(self, lookup: Callable):
        self._lookup = lookup

    def __enter__(self):
        self._prev = getattr(_tls, "replay", None)
        _tls.replay = self._lookup
        return self

    def __exit__(self, *exc):
        _tls.replay = self._prev
        return False


def current_replay() -> Optional[Callable]:
    return getattr(_tls, "replay", None)
