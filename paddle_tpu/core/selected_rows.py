"""SelectedRows — the sparse row-gradient representation.

TPU-native analog of the reference's ``framework::SelectedRows``
(reference: paddle/fluid/framework/selected_rows.h:34: rows_ + value_ +
height_) used by ``embedding(..., sparse=True)``: the backward of a
lookup touches only the looked-up rows, so the gradient is (rows, values)
instead of a mostly-zero [height, dim] dense array.  Optimizers apply it
with row-wise scatter updates (operators/optimizers/sgd_op.h SelectedRows
branch; Adam's lazy_mode path, adam_op.h).
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np


class SelectedRows:
    """rows: int32 [n]; values: [n, *dim]; height: size of the full dim 0."""

    __slots__ = ("rows", "values", "height")

    # make numpy/jax defer `dense + sr` to our __radd__ instead of
    # broadcasting over the object
    __array_ufunc__ = None
    __array_priority__ = 1000

    def __init__(self, rows, values, height: int):
        self.rows = jnp.asarray(rows).reshape(-1)
        self.values = jnp.asarray(values)
        self.height = int(height)

    @property
    def shape(self):
        return (self.height,) + tuple(self.values.shape[1:])

    @property
    def dtype(self):
        return self.values.dtype

    def merge(self) -> "SelectedRows":
        """Sum duplicate rows (reference: operators/math/
        selected_rows_functor.cc MergeAdd).  Keeps the result sparse with
        one entry per unique touched row."""
        uniq, inv = jnp.unique(self.rows, return_inverse=True)
        vals = jnp.zeros((uniq.shape[0],) + self.values.shape[1:],
                         self.values.dtype)
        vals = vals.at[inv.reshape(-1)].add(self.values)
        return SelectedRows(uniq, vals, self.height)

    def to_dense(self):
        out = jnp.zeros(self.shape, self.values.dtype)
        return out.at[self.rows].add(self.values)

    # dense/sparse accumulation (tape deposits may mix both)
    def __add__(self, other):
        if isinstance(other, SelectedRows):
            assert other.height == self.height
            return SelectedRows(jnp.concatenate([self.rows, other.rows]),
                                jnp.concatenate([self.values, other.values]),
                                self.height)
        return jnp.asarray(other).at[self.rows].add(self.values)

    def __radd__(self, other):
        return self.__add__(other)

    def __mul__(self, s):
        return SelectedRows(self.rows, self.values * s, self.height)

    __rmul__ = __mul__

    def numpy(self):
        return np.asarray(self.to_dense())

    def __repr__(self):
        return (f"SelectedRows(height={self.height}, "
                f"nnz_rows={self.rows.shape[0]}, "
                f"dim={tuple(self.values.shape[1:])})")
