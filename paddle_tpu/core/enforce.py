"""Typed error system.

TPU-native equivalent of the reference's ``PADDLE_ENFORCE_*`` macros and error
taxonomy (reference: paddle/fluid/platform/enforce.h:410-505,
errors.cc, error_codes.proto).  We keep the error-code taxonomy as exception
classes so user code can catch narrow categories, and attach the offending op
name the way ``AppendErrorOpHint`` does (reference: imperative/tracer.cc:188).
"""
from __future__ import annotations

from . import obs_hook


class EnforceError(RuntimeError):
    """Base of the taxonomy (reference: error_codes.proto).

    When a flight recorder is installed (observability), constructing
    any error in the taxonomy dumps the black box — the framework's
    typed failures are exactly the crashes worth a post-mortem.  The
    handler dedups by exception object, so a later re-report (e.g. the
    Executor catching this error) never double-dumps."""
    code = "LEGACY"

    def __init__(self, *args):
        super().__init__(*args)
        h = obs_hook._crash
        if h is not None:
            h(self, f"enforce.{type(self).__name__}")


class InvalidArgumentError(EnforceError, ValueError):
    code = "INVALID_ARGUMENT"


class NotFoundError(EnforceError, KeyError):
    code = "NOT_FOUND"


class OutOfRangeError(EnforceError, IndexError):
    code = "OUT_OF_RANGE"


class AlreadyExistsError(EnforceError):
    code = "ALREADY_EXISTS"


class ResourceExhaustedError(EnforceError, MemoryError):
    code = "RESOURCE_EXHAUSTED"


class PreconditionNotMetError(EnforceError):
    code = "PRECONDITION_NOT_MET"


class PermissionDeniedError(EnforceError):
    code = "PERMISSION_DENIED"


class ExecutionTimeoutError(EnforceError, TimeoutError):
    code = "EXECUTION_TIMEOUT"


class UnimplementedError(EnforceError, NotImplementedError):
    code = "UNIMPLEMENTED"


class UnavailableError(EnforceError):
    code = "UNAVAILABLE"


class FatalError(EnforceError):
    code = "FATAL"


class ExternalError(EnforceError):
    code = "EXTERNAL"


class GraphVerificationError(PreconditionNotMetError):
    """A static Program failed compile-time verification
    (static/analysis).  Carries the structured, source-anchored
    ``Diagnostic`` list on ``.diagnostics`` so tooling can render or
    filter findings instead of re-parsing the message."""

    def __init__(self, message="", diagnostics=()):
        super().__init__(message)
        self.diagnostics = list(diagnostics)


def enforce(cond, msg="", exc=InvalidArgumentError):
    """PADDLE_ENFORCE parity: raise typed error when cond is false."""
    if not cond:
        raise exc(msg() if callable(msg) else msg)


def enforce_eq(a, b, msg="", exc=InvalidArgumentError):
    if a != b:
        raise exc(f"Expected {a!r} == {b!r}. {msg() if callable(msg) else msg}")


def enforce_not_none(v, name="value", exc=NotFoundError):
    if v is None:
        raise exc(f"{name} must not be None")
    return v


def with_op_hint(e: Exception, op_name: str) -> Exception:
    """Append the op attribution hint on failure (tracer.cc:188 analog)."""
    hint = f"  [operator < {op_name} > error]"
    if e.args and isinstance(e.args[0], str) and hint not in e.args[0]:
        e.args = (e.args[0] + "\n" + hint,) + e.args[1:]
    elif not e.args:
        e.args = (hint,)
    return e
