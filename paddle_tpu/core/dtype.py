"""Dtype policy for paddle_tpu.

The reference framework carries dtype as `proto::VarType::Type` on every tensor
(reference: paddle/fluid/framework/framework.proto:91-117) and converts through
`framework::TransDataType`.  Here dtypes are plain numpy/jax dtypes with string
aliases matching the reference's public names (``'float32'``, ``'bfloat16'`` ...).

TPU-first policy: bfloat16 is a first-class compute dtype (MXU-native); float64
is supported but discouraged (TPU emulates it slowly).
"""
from __future__ import annotations

import jax.numpy as jnp
import numpy as np

# Public alias table: paddle name -> jnp dtype
_ALIASES = {
    "bool": jnp.bool_,
    "uint8": jnp.uint8,
    "int8": jnp.int8,
    "int16": jnp.int16,
    "int32": jnp.int32,
    "int64": jnp.int64,
    "float16": jnp.float16,
    "bfloat16": jnp.bfloat16,
    "float32": jnp.float32,
    "float64": jnp.float64,
    "complex64": jnp.complex64,
    "complex128": jnp.complex128,
}

# Reverse map (canonical name for a dtype)
_NAMES = {np.dtype(v): k for k, v in _ALIASES.items()}

float16 = jnp.float16
bfloat16 = jnp.bfloat16
float32 = jnp.float32
float64 = jnp.float64
int8 = jnp.int8
int16 = jnp.int16
int32 = jnp.int32
int64 = jnp.int64
uint8 = jnp.uint8
bool_ = jnp.bool_
complex64 = jnp.complex64
complex128 = jnp.complex128

_default_dtype = jnp.float32


def set_default_dtype(d):
    """paddle.set_default_dtype parity (reference: python/paddle/framework/framework.py)."""
    global _default_dtype
    _default_dtype = convert_dtype(d)


def get_default_dtype():
    return _default_dtype


def convert_dtype(d):
    """Normalise a string / numpy / jnp dtype spec to a jnp dtype."""
    if d is None:
        return None
    if isinstance(d, str):
        name = d.replace("paddle.", "")
        if name not in _ALIASES:
            raise TypeError(f"Unknown dtype alias: {d!r}")
        return _ALIASES[name]
    try:
        return np.dtype(d).type if not hasattr(d, "dtype") else d
    except TypeError:
        raise TypeError(f"Cannot interpret {d!r} as a dtype")


def dtype_name(d) -> str:
    """Canonical paddle-style name for a dtype."""
    return _NAMES.get(np.dtype(d), str(np.dtype(d)))


def is_floating(d) -> bool:
    return jnp.issubdtype(np.dtype(d), jnp.floating)


def is_integer(d) -> bool:
    return jnp.issubdtype(np.dtype(d), jnp.integer)
