"""Single op-dispatch point shared by eager and traced execution.

This is the analog of the reference's shared OpKernel dispatch — both dygraph
``Tracer::TraceOp`` (reference: imperative/tracer.cc:132) and the static
``Executor`` hot loop (reference: framework/executor.cc:460-466) funnel into
one kernel registry (operator.h:474).  Here every public op calls
:func:`apply` with a *pure jnp function*; the same pure function is used
eagerly (with tape recording) and under ``jax.jit`` tracing (tape off, jax
transforms handle differentiation).
"""
from __future__ import annotations

import time
from typing import Any, Callable, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from . import autograd, obs_hook, profiler_hook, static_hooks
from .enforce import with_op_hint
from .flags import get_flag


def _is_tensor(x) -> bool:
    from .tensor import Tensor
    return isinstance(x, Tensor)


def as_array(x):
    """Tensor → jax array; pass scalars/arrays through."""
    from .tensor import Tensor
    if isinstance(x, Tensor):
        return x.data
    return x


def _check_nan_inf(op_name, arrays):
    """FLAGS_check_nan_inf mode (reference: details/nan_inf_utils.h:28-33)."""
    for a in arrays:
        if hasattr(a, "dtype") and np.issubdtype(np.dtype(a.dtype), np.floating):
            if bool(jnp.any(~jnp.isfinite(a))):
                raise FloatingPointError(
                    f"NaN or Inf found in output of operator < {op_name} >")


from collections import OrderedDict

_VJP_CACHE: "OrderedDict" = OrderedDict()
_VJP_CACHE_CAP = 4096


def _cache_lookup(key):
    """LRU read: a hit moves the entry to the young end."""
    entry = _VJP_CACHE.get(key)
    if entry is not None:
        _VJP_CACHE.move_to_end(key)
    return entry


def _cache_store(key, entry):
    """Insert with oldest-half LRU eviction at the cap.  A full clear()
    here would force every live op to retrace on its next call — at
    steady state near the cap that is total retrace thrash (~3 ms/op);
    evicting the least-recently-used half keeps the hot set compiled."""
    if len(_VJP_CACHE) >= _VJP_CACHE_CAP:
        for k in list(_VJP_CACHE)[:_VJP_CACHE_CAP // 2]:
            del _VJP_CACHE[k]
    _VJP_CACHE[key] = entry


def _cached_fwd(fn, kw):
    """Compiled forward-only rule for the no-grad eager path (inference
    loops): one pjit call instead of one dispatch per primitive inside
    ``fn``.  Shares _VJP_CACHE under a 'fwd' marker key."""
    try:
        key = (fn, "fwd", tuple(sorted(kw.items())))
        hash(key)
    except TypeError:
        return None
    jfn = _cache_lookup(key)
    if jfn is None:
        jfn = jax.jit(lambda *a: fn(*a, **kw))
        _cache_store(key, jfn)
    return jfn


def _cached_rules(fn, kw, diff_idx, arrays):
    """Compiled fwd + bwd for a stable op function (the eager fast path —
    reference analog: the tracer's cached OpKernel lookup,
    pybind/op_function_generator.cc:492).  Keyed by (fn, kw, shapes):
    re-tracing ``jax.vjp`` per eager call costs ~3 ms/op in Python; the
    cached pjit fast path is ~10 us.  The backward recomputes the forward
    inside its own cached jit (XLA DCEs what the cotangent doesn't need).
    Returns None when kw isn't hashable."""
    del arrays  # avals are jit's cache dimension, not ours
    try:
        # shapes/dtypes are NOT part of the key: jax.jit already caches
        # per-aval under each entry, so one entry per (op, kw) suffices.
        # Keying on fn itself (not id(fn)) pins it alive — an id could be
        # reused after GC and silently serve another op's compiled rules.
        key = (fn, tuple(diff_idx), tuple(sorted(kw.items())))
        hash(key)
    except TypeError:
        return None
    entry = _cache_lookup(key)
    if entry is None:
        fwd = jax.jit(lambda *a: fn(*a, **kw))

        def bwd_impl(all_args, cts):
            def f_diff(*diff_args):
                full = list(all_args)
                for j, a in zip(diff_idx, diff_args):
                    full[j] = a
                return fn(*full, **kw)
            _, pull = jax.vjp(f_diff, *(all_args[i] for i in diff_idx))
            return pull(cts)

        entry = (fwd, jax.jit(bwd_impl))
        _cache_store(key, entry)
    return entry


def apply(fn: Callable, *inputs, op_name: str | None = None,
          nondiff: bool = False, cacheable: bool = False, **kw):
    """Run a pure op function over Tensor/array inputs.

    - Eager + grad needed: runs through ``jax.vjp`` and records a tape Node.
    - ``cacheable=True`` (opt-in for ops whose ``fn`` is a stable,
      module-level object): fwd and bwd run through compiled-rule caches,
      skipping per-call retracing on the eager hot path.
    - Otherwise: plain call (also the path taken under jit tracing, where
      the surrounding ``jax.grad`` owns differentiation).
    Returns Tensor or tuple of Tensors mirroring ``fn``'s output structure.
    """
    from .tensor import Tensor

    name = op_name or getattr(fn, "__name__", "op").lstrip("_")

    # static-graph handling.  Replay scope active (inside a compiled
    # Program / control-flow branch): Variables AND Parameters resolve to
    # their runtime traced arrays, then the op executes normally.  No
    # replay + symbolic Variable input: record the op into its Program
    # (the reference's Block.append_op path, framework.py:4160).
    replay = static_hooks.current_replay()
    if replay is not None:
        from .tensor import Parameter
        inputs = tuple(
            Tensor(replay(x))
            if (getattr(type(x), "_static_var", False)
                or isinstance(x, Parameter)) else x
            for x in inputs)
    elif any(getattr(type(x), "_static_var", False) for x in inputs):
        prog = next(x for x in inputs
                    if getattr(type(x), "_static_var", False)).program
        return prog.record(fn, list(inputs), kw, name)

    arrays = [as_array(x) for x in inputs]

    # AMP autocast hook — the single cast point shared by eager and traced
    # modes (reference: tracer.cc:160-163 AutoCastInputs)
    from ..amp import amp_active, amp_cast_inputs
    if amp_active():
        arrays = amp_cast_inputs(name, arrays)

    diff_idx = []
    if autograd.grad_enabled() and not nondiff:
        for i, x in enumerate(inputs):
            if _is_tensor(x) and not x.stop_gradient and jnp.issubdtype(
                    np.dtype(x.data.dtype), np.inexact):
                diff_idx.append(i)

    # host-op profiling (reference: RecordEvent inside Tracer::TraceOp)
    # + structured op tracing: both gated so the disabled path is one
    # module-attribute None-check each (observability contract)
    prof = profiler_hook.current()
    trc = obs_hook._tracer
    t_prof = (time.perf_counter()
              if (prof is not None or trc is not None) else None)

    try:
        if diff_idx:
            rules = (_cached_rules(fn, kw, diff_idx, arrays)
                     if cacheable and not isinstance(
                         arrays[diff_idx[0]], jax.core.Tracer) else None)
            if rules is not None:
                fwd, bwd = rules
                outs = fwd(*arrays)
                all_args = tuple(arrays)
                vjp_fn = lambda cts: bwd(all_args, cts)  # noqa: E731
            else:
                def f(*diff_args):
                    full = list(arrays)
                    for j, a in zip(diff_idx, diff_args):
                        full[j] = a
                    return fn(*full, **kw)

                outs, vjp_fn = jax.vjp(f, *(arrays[i] for i in diff_idx))
        else:
            jfn = (_cached_fwd(fn, kw)
                   if cacheable and arrays
                   and not any(isinstance(a, jax.core.Tracer)
                               for a in arrays) else None)
            outs = jfn(*arrays) if jfn is not None else fn(*arrays, **kw)
    except Exception as e:  # attach op attribution like AppendErrorOpHint
        raise with_op_hint(e, name)

    if prof is not None:
        # default: times the async host dispatch only (device work is
        # still in flight).  sync mode (Profiler(sync_ops=True) /
        # FLAGS_profiler_sync_ops) blocks on this op's outputs first, so
        # the recorded span covers the device work — at the price of
        # serializing the pipeline per op.
        if getattr(prof, "_sync_ops", False):
            for o in (outs if isinstance(outs, (tuple, list)) else (outs,)):
                if isinstance(o, jax.Array) and not isinstance(
                        o, jax.core.Tracer):
                    o.block_until_ready()
        prof._record(name, time.perf_counter() - t_prof)
    if trc is not None:
        trc.op(name, t_prof, time.perf_counter())

    multi = isinstance(outs, (tuple, list))
    out_seq = list(outs) if multi else [outs]

    if get_flag("check_nan_inf"):
        _check_nan_inf(name, out_seq)

    sg = not diff_idx
    out_tensors = [Tensor(o, stop_gradient=sg, _produced=not sg) for o in out_seq]

    if diff_idx:
        node = autograd.Node(
            inputs=[inputs[i] for i in diff_idx],
            vjp_fn=vjp_fn,
            out_ids=[t._bw_id for t in out_tensors],
            out_avals=[(t.shape_tuple, np.dtype(t.data.dtype)) for t in out_tensors],
            out_is_tuple=multi,
            # replay pins ALL input arrays (incl. non-differentiable ones)
            # until a backward with retain_graph=False frees it — the
            # price of create_graph double-backward support.  Eager loops
            # that never backprop should run under autograd.no_grad() (no
            # node, no retention).
            replay=(fn, kw, tuple(diff_idx), tuple(arrays)),
        )
        for t in out_tensors:
            t._node = node

    if multi:
        return tuple(out_tensors)
    return out_tensors[0]
