"""Global flags registry.

TPU-native equivalent of the reference's gflags tier (reference:
paddle/fluid/platform/flags.cc:33-577, surfaced to Python through
pybind/global_value_getter_setter.cc as ``core.globals()`` and
``paddle.set_flags``).  Flags may also be seeded from the environment with the
``FLAGS_`` prefix, matching the reference's env passthrough.
"""
from __future__ import annotations

import os
import threading
from dataclasses import dataclass
from typing import Any, Callable, Dict, Optional


@dataclass
class _FlagDef:
    name: str
    default: Any
    help: str
    parser: Callable[[str], Any]


_registry: Dict[str, _FlagDef] = {}
_values: Dict[str, Any] = {}
_lock = threading.Lock()


def _parse_bool(s: str) -> bool:
    return s.lower() in ("1", "true", "yes", "on")


def define_flag(name: str, default: Any, help: str = "") -> None:
    if isinstance(default, bool):
        parser: Callable[[str], Any] = _parse_bool
    elif isinstance(default, int):
        parser = int
    elif isinstance(default, float):
        parser = float
    else:
        parser = str
    with _lock:
        _registry[name] = _FlagDef(name, default, help, parser)
        env = os.environ.get(f"FLAGS_{name}")
        if env is not None:
            _values[name] = parser(env)
        else:
            _values.setdefault(name, default)


def get_flag(name: str) -> Any:
    if name not in _registry:
        raise KeyError(f"Unknown flag: {name}")
    return _values[name]


def set_flags(flags: Dict[str, Any]) -> None:
    """paddle.set_flags parity."""
    for k, v in flags.items():
        k = k.replace("FLAGS_", "")
        if k not in _registry:
            raise KeyError(f"Unknown flag: {k}")
        with _lock:
            _values[k] = v


def get_flags(names=None) -> Dict[str, Any]:
    if names is None:
        return dict(_values)
    if isinstance(names, str):
        names = [names]
    return {n.replace("FLAGS_", ""): get_flag(n.replace("FLAGS_", "")) for n in names}


# ---------------------------------------------------------------------------
# Core flag set (subset of reference platform/flags.cc relevant on TPU)
# ---------------------------------------------------------------------------
define_flag("check_nan_inf", False,
            "Scan every op output for NaN/Inf (reference: flags.cc:44).")
define_flag("benchmark", False,
            "Synchronise after each op and log timings (reference: flags.cc:38).")
define_flag("eager_delete_tensor_gb", 0.0,
            "Accepted for parity; XLA owns buffer lifetimes on TPU.")
define_flag("use_pallas_kernels", True,
            "Use Pallas fused kernels (flash attention etc.) when on TPU.")
define_flag("matmul_precision", "default",
            "jax matmul precision: default | float32 | tensorfloat32 | highest.")
define_flag("allocator_strategy", "xla",
            "Accepted for parity; XLA/TPU runtime owns allocation.")
define_flag("profile_dir", "",
            "If set, profiler traces are written here.")
define_flag("pallas_attention_min_seqlen", 1024,
            "Use the Pallas flash-attention kernel at/above this sequence "
            "length (below it XLA's fused attention is faster on-chip).")
define_flag("static_verify", False,
            "Run static.analysis verification (def-use, cross-program "
            "leaks, shape/dtype drift, name collisions, dead code) on "
            "each Program before its first compile, and record file:line "
            "anchors for every op at build time.  Off by default: "
            "verification adds one eval_shape re-trace per op.")
define_flag("shard_verify", False,
            "Run the shardcheck SPMD safety passes (static/analysis/"
            "shardcheck: plan coverage & divisibility, collective "
            "choreography, device-varying taint, wire-byte audit) once "
            "per (program, sharding-plan fingerprint) before the first "
            "sharded compile.  A plan/config the Executor would refuse "
            "at compile time then fails preflight as a structured "
            "GraphVerificationError carrying the same cause string.  "
            "Compile keys are unchanged, so the 0-recompile contract "
            "holds with the flag on or off.")
define_flag("static_anchors", False,
            "Record a file:line source anchor on every op "
            "Program.record appends — the cheap subset of "
            "FLAGS_static_verify (one frame walk per recorded op at "
            "build time, no per-run verification), so "
            "Program.analyze() reports and lint/analyze CLIs carry "
            "user-source anchors.")
define_flag("static_donate", True,
            "Donate parameter/optimizer buffers of the static Executor's "
            "compiled train step (jax.jit donate_argnums), updating "
            "weights in place run-to-run.  Aliasing-safe: any array a "
            "user obtains through Parameter.data is copied out of the "
            "donated set before the next run.  Turn off to keep every "
            "step's input buffers alive (debugging / buffer archaeology).")
define_flag("profiler_sync_ops", False,
            "Profiler op timing blocks on device completion per op "
            "(block_until_ready) instead of timing only the async host "
            "dispatch.  Accurate per-op device cost attribution at the "
            "price of serializing the pipeline; default off.  Also "
            "settable per-Profiler via Profiler(sync_ops=True).")
define_flag("fault_spec", "",
            "Deterministic fault-injection spec (paddle_tpu.testing.fault"
            " grammar: 'point_glob:p=...,count=...,exc=...;...').  Armed "
            "from the environment at import; after set_flags() call "
            "testing.fault.arm_from_flags().  Empty = injector disarmed "
            "(zero overhead).")
define_flag("fault_seed", 0,
            "Seed for the fault injector's RNG — a chaos run with the "
            "same spec+seed replays the same fault sequence.")
define_flag("fs_retry_times", 4,
            "Max attempts (1 initial + retries) for a filesystem op that "
            "fails with a transient error (ShellFS always; other "
            "registered filesystems when wrapped in RetryingFS).")
define_flag("fs_retry_backoff_s", 0.2,
            "Base exponential-backoff delay between fs retries; attempt "
            "n sleeps ~base*2^n plus up to 25% jitter, capped at 10s.")
define_flag("fs_retry_deadline_s", 60.0,
            "Wall-clock budget across all retry attempts of one fs op; "
            "past it the op gives up even with attempts remaining.")
define_flag("dataloader_timeout", 120,
            "Seconds a DataLoader iterator waits on worker results "
            "before declaring the pool stalled (DataLoader(timeout=) "
            "overrides per loader).")
define_flag("dataloader_batch_retries", 3,
            "Times one batch may be re-enqueued after DataLoader worker "
            "deaths before the epoch fails for good.")
define_flag("dataloader_respawn_backoff_s", 0.2,
            "Base delay before respawning a dead DataLoader worker when "
            "deaths are clustering: the first death in the crash-loop "
            "window respawns immediately, the Nth waits "
            "~base*2^(N-2) (capped by "
            "FLAGS_dataloader_respawn_backoff_max_s).  Keeps a flapping "
            "node from burning the batch retry budget in a tight "
            "respawn loop.")
define_flag("dataloader_respawn_backoff_max_s", 5.0,
            "Cap on the per-respawn backoff delay.")
define_flag("dataloader_crashloop_window_s", 30.0,
            "Sliding window for DataLoader worker crash-loop detection.")
define_flag("dataloader_crashloop_budget", 6,
            "Worker deaths tolerated inside the crash-loop window; one "
            "more raises WorkerCrashLoop with the full exit_history "
            "instead of respawning again (fast-fail for a poisoned "
            "dataset or a dying node).")
define_flag("mesh_replace_warn_only", False,
            "Downgrade the error raised when init_mesh/set_mesh would "
            "replace a live mesh that compiled programs still hold "
            "shardings against (distributed/mesh.py) to a warning.  The "
            "stale executables keep the OLD device placement — only set "
            "this when you know every holder is about to be rebuilt.")
define_flag("checkpoint_keep_max", 2,
            "Snapshots retained per checkpoint dir (keep_checkpoint_max; "
            ">=2 keeps a fallback for corrupt-latest recovery).")
define_flag("inference_pad_policy", "bucket",
            "Predictor.run on a batch size with no compiled variant: "
            "'bucket' pads the leading dim to the smallest compiled/"
            "declared bucket (next power of two when none fits) and "
            "slices outputs back — zero recompiles after warmup; 'none' "
            "compiles a fresh variant per batch size (legacy).")
define_flag("serving_dispatch_retries", 2,
            "InferenceEngine: batch dispatch attempts after a failure "
            "before the batch's requests are failed (inference is pure, "
            "so a flaked dispatch is safely retried).")
define_flag("serving_decode_retries", 2,
            "GenerationEngine: decode-step attempts after a failure "
            "before the in-flight sequences are failed (the step is "
            "functional over the KV pool, so a flaked dispatch is "
            "safely retried).")
define_flag("metrics_dump_path", "",
            "When set, training appends periodic monitor-metrics "
            "snapshots (stats + histograms, one JSON object per line) "
            "to this JSONL file — Model.fit auto-attaches the "
            "hapi.callbacks.MetricsDump callback; other loops can call "
            "observability.dump_metrics() directly.")
define_flag("flight_recorder_path", "",
            "Default dump path for the crash flight recorder "
            "(observability.install_flight_recorder).  On EnforceError, "
            "an exception escaping Executor.run, SIGTERM or an "
            "unhandled exception, the last tracer events + a full "
            "metrics snapshot are written here atomically.")
define_flag("perf_sample_every", 16,
            "Runtime performance observatory (observability.enable_perf)"
            ": fence (block_until_ready) and sample device memory on "
            "every Nth step per compile identity.  Unsampled steps stay "
            "fully async — only host-side timestamps are taken — so the "
            "donated dispatch pipeline is never serialized.  <=0 "
            "disables fencing entirely (host anatomy only).")
define_flag("perf_chip", "",
            "Roofline chip spec used to turn the cost model's predicted "
            "FLOPs/traffic into a predicted step time for the drift "
            "tracker (static/analysis/cost.CHIP_SPECS key).  Empty = "
            "auto: 'cpu' on the CPU backend, 'v5e' on TPU.")
define_flag("pallas_interpret", False,
            "Let the automatic Pallas-tier selectors (the static "
            "Executor's epilogue-fusion pass, the fused Adam update, "
            "the paged-attention decode hook) pick Pallas kernels OFF "
            "TPU, running them in interpret mode.  Interpret mode is "
            "orders of magnitude slower than jnp — this exists so "
            "tests, bench and tools/kernel_smoke.py exercise the exact "
            "TPU kernel dataflow under JAX_PLATFORMS=cpu, never as a "
            "CPU performance path.  On a real TPU backend the tier "
            "needs only FLAGS_use_pallas_kernels.")
define_flag("xla_latency_hiding", False,
            "Enable XLA's latency-hiding scheduler by appending the "
            "backend's scheduler flags to XLA_FLAGS at import, BEFORE "
            "backend initialisation (core/xla_env.py; set it as the "
            "FLAGS_xla_latency_hiding environment variable — a "
            "set_flags() call after jax's backend exists is too late "
            "and is ignored with a warning).  With it on, the per-"
            "bucket grad_comm collectives (strategy.grad_comm.overlap="
            "'auto') are split into async start/done pairs the "
            "scheduler hoists across backward compute — comm hides "
            "behind backward instead of adding to it; without it, "
            "overlap='auto' falls back to the ppermute-chunked ring "
            "lowering on TPU/GPU.  TPU/GPU only: the CPU backend has "
            "no such scheduler (and rejects unknown XLA flags "
            "fatally), so CPU processes never get flags appended and "
            "auto keeps the fused per-bucket collectives there — a "
            "serial backend overlaps nothing; force overlap='ring' to "
            "exercise the chunked lowering on CPU.")
define_flag("anomaly_sentry", False,
            "Fuse the data-plane anomaly sentry into the static "
            "Executor's compiled train step: per-bucket gradient "
            "finiteness checks + grad-norm stats collapse to one scalar "
            "anomaly flag (psum'd over the dp axis so every replica "
            "takes the same branch), and the parameter/optimizer/"
            "step-counter/error-feedback update is applied through a "
            "jnp.where select — a flagged step is a bitwise no-op "
            "instead of a silent weight corruption.  The production "
            "analog of the reference's FLAGS_check_nan_inf (also "
            "opt-in), but one reduction per existing bucket view "
            "instead of per kernel launch: negligible next to real "
            "model math, measurable on micro-benchmarks (bench.py's "
            "static suite reports the measured overhead_pct).  "
            "Supervised production training should run with it on.  "
            "Flipping it recompiles (the executable either carries the "
            "sentry or it doesn't; attribution names the flip).")
define_flag("anomaly_skip_budget", 2,
            "AnomalyPolicy: consecutive sentry-flagged (skipped) steps "
            "tolerated before escalating — first past the budget "
            "quarantines the blamed batch, the next escalates to a "
            "snapshot rollback.")
define_flag("anomaly_rollback_budget", 1,
            "AnomalyPolicy: snapshot rollbacks attempted before the "
            "policy gives up and raises AnomalyEscalation (handing the "
            "incarnation to the TrainingSupervisor's restart path).")
define_flag("anomaly_spike_window", 32,
            "AnomalyPolicy rolling window (clean steps) for the "
            "loss-spike detector's median.")
define_flag("anomaly_spike_factor", 10.0,
            "AnomalyPolicy: a finite loss above median * factor over "
            "the rolling window counts as an anomaly (catches finite "
            "corruption — e.g. a bitflipped wire payload — that the "
            "non-finite sentry cannot flag).  <= 0 disables the "
            "spike detector.")
define_flag("compile_cache_dir", "",
            "Persistent AOT executable cache directory.  When set, the "
            "compiling layers that serve traffic (inference Predictor "
            "buckets, GenerationEngine decode/prefill variants, the "
            "static Executor's single-device inference step) serialize "
            "each compiled executable through core/compile_cache.py and "
            "reload it on the next cold start — a respawned replica "
            "skips XLA entirely for warm buckets (cold-start-to-first-"
            "token cut >5x; serve_smoke gates it).  Entries are keyed "
            "by the recompile-attribution signature plus a jax/jaxlib/"
            "backend/topology stamp, so a version or device change "
            "invalidates cleanly (compile_cache.rejects) instead of "
            "loading a stale executable.  We serialize ourselves via "
            "jax.experimental.serialize_executable — jax's own "
            "persistent compilation cache is deliberately NOT enabled "
            "(it heap-corrupts reloading NamedSharding executables on "
            "jaxlib 0.4.37; see core/xla_env.py / PR 8).  Empty = "
            "disabled (no filesystem traffic).")
define_flag("metrics_dump_max_mb", 0.0,
            "Size-based rotation threshold for the FLAGS_metrics_dump_"
            "path JSONL file: before each append, a file at/above this "
            "many MiB is atomically renamed to <path>.1 (existing "
            "rotated files shift up, the oldest beyond "
            "FLAGS_metrics_dump_keep is deleted) so long-lived replicas "
            "never grow one unbounded flight file.  <= 0 disables "
            "rotation (legacy unbounded append).")
define_flag("metrics_dump_keep", 3,
            "Rotated metrics-dump files retained (<path>.1 .. <path>.N) "
            "when FLAGS_metrics_dump_max_mb rotation triggers.")
define_flag("obs_spool_dir", "",
            "Fleet telemetry spool directory.  When set, this process "
            "installs the per-process telemetry exporter "
            "(observability.export) at import: checksummed metrics "
            "snapshots and tracer-ring segments are spooled atomically "
            "to <dir>/<role>-<pid>/ for the fleet aggregator "
            "(observability.fleet) to merge into one timeline / one "
            "Prometheus view.  Supervisors stage this into child "
            "environments automatically, so supervised children and "
            "serving replicas export with zero code changes.  Empty = "
            "off: instrumented sites pay one module-attribute "
            "None-check (the core.obs_hook contract).")
define_flag("obs_role", "",
            "Role label for this process's telemetry spool "
            "(<role>-<pid> directory name and the {proc=...} Prometheus "
            "label).  Supervisors stage '<name>-a<attempt>' for each "
            "child incarnation; empty = 'proc'.")
define_flag("obs_export_interval_s", 5.0,
            "Seconds between telemetry spool flushes.  The exporter's "
            "daemon thread flushes on this cadence; instrumented hot "
            "paths (Executor._run, the serving dispatchers) also tick "
            "it so a busy process that dies between timer fires still "
            "leaves a recent spool.  Ticks inside the interval are "
            "rate-limited to one time check.")
define_flag("pallas_attention_dropout_min_seqlen", 512,
            "Flash threshold when attention dropout is active: the XLA "
            "path must materialize [B,H,L,L] dropout masks in HBM, so "
            "the in-kernel-PRNG flash path wins from shorter sequences "
            "(measured v5e, BERT-base seq 512: 325 -> 288 ms/step).")
