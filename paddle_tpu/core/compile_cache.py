"""Persistent AOT executable cache — respawned replicas skip XLA.

Every cold start of a serving replica re-lowers and re-compiles every
batch bucket and decode variant, and that compile wall IS the
cold-start-to-first-token cost (seconds per variant vs milliseconds to
deserialize).  This module persists the compiled executables
themselves, keyed by the same structured signature the recompile-
attribution layer (:func:`observability.record_compile`) already
maintains, so the cache key and the compile cause are one vocabulary.

Design constraints:

- **We serialize ourselves** through the AOT ``lower().compile()`` +
  ``jax.experimental.serialize_executable`` path, routed via
  :mod:`paddle_tpu.core.jax_compat`.  jax's own persistent compilation
  cache stays OFF: it heap-corrupts reloading NamedSharding
  executables on jaxlib 0.4.37 (PR 8 caveat, core/xla_env.py).
- **Stamped invalidation.**  Each entry carries a version/topology
  stamp (jax, jaxlib, backend platform, device kind, device count,
  format version).  Any mismatch on load is a *reject* — counted as
  ``compile_cache.rejects``, never an exception on the serve path.
- **Device-fingerprint verification before first dispatch** (the
  load-path bugfix this subsystem ships with): a deserialized
  executable's input shardings must resolve onto the devices this
  process actually has.  A payload that deserializes but targets a
  different device set is rejected to a fresh compile instead of
  crashing (or silently corrupting) on first dispatch.
- **Single-process-topology scope.**  Entries are only written/read
  for single-device executables — the serving paths this cache exists
  for (Predictor buckets, GenerationEngine variants, the Executor's
  unsharded inference step).  Sharded train-step executables keep
  compiling fresh; their cost is amortized over hours, not paid per
  respawn.

Enabled by ``FLAGS_compile_cache_dir`` (empty = disabled, zero
filesystem traffic).  Stats: ``compile_cache.{hits,misses,rejects,
stores,errors}``; each event also emits a ``compile_cache`` tracer
event when observability is enabled.  ``explain_compiles()`` shows
loaded-vs-compiled per record via the ``cache=`` provenance field.
"""
from __future__ import annotations

import hashlib
import os
import pickle
import tempfile
import threading
from typing import Callable, Optional, Tuple

__all__ = ["enabled", "cache_dir", "stamp", "cache_key", "load",
           "store", "cached_compile", "stats", "reset_stats"]

_FORMAT = 1                     # bump to invalidate every entry at once
_SUFFIX = ".xcache"

_lock = threading.Lock()
_stamp_cache: Optional[dict] = None


def enabled() -> bool:
    from . import flags
    return bool(flags.get_flag("compile_cache_dir"))


def cache_dir() -> str:
    from . import flags
    return str(flags.get_flag("compile_cache_dir"))


def _emit(event: str, **args) -> None:
    from . import obs_hook
    trc = obs_hook._tracer
    if trc is not None:
        trc.emit("compile_cache", event, args=args)


def _count(name: str) -> None:
    from ..utils import monitor
    monitor.stat_add(f"compile_cache.{name}")


def stamp() -> dict:
    """The version/topology stamp baked into every entry.  Any field
    changing between store and load rejects the entry: a jax/jaxlib
    upgrade, a backend flip (cpu<->tpu), a different chip generation,
    or a different device count all produce executables that must not
    be mixed."""
    global _stamp_cache
    if _stamp_cache is None:
        import jax
        import jaxlib
        devs = jax.devices()
        _stamp_cache = {
            "format": _FORMAT,
            "jax": jax.__version__,
            "jaxlib": getattr(jaxlib, "__version__", "unknown"),
            "backend": jax.default_backend(),
            "device_kind": devs[0].device_kind if devs else "none",
            "device_count": len(devs),
        }
    return dict(_stamp_cache)


def _freeze(v):
    """Deterministic, content-stable form of a signature value (same
    rules as the attribution layer: scalars verbatim, containers
    recursively frozen, everything else repr'd)."""
    if isinstance(v, (int, float, bool, str, type(None))):
        return v
    if isinstance(v, bytes):
        return v.hex()
    if isinstance(v, (tuple, list)):
        return tuple(_freeze(x) for x in v)
    if isinstance(v, (set, frozenset)):
        return tuple(sorted(_freeze(x) for x in v))
    if isinstance(v, dict):
        return tuple(sorted((str(k), _freeze(x)) for k, x in v.items()))
    return repr(v)


def cache_key(component: str, signature: dict) -> str:
    """Content hash of (component, frozen signature, stamp) — the file
    name under the cache dir.  The signature is the same ordered dict
    the caller hands ``record_compile``, extended with whatever
    identifies the *content* across processes (artifact digest, param
    fingerprint, program fingerprint) — process-local serials must NOT
    be in it."""
    frozen = (component,
              tuple((str(k), _freeze(v)) for k, v in signature.items()),
              tuple(sorted(stamp().items())))
    return hashlib.sha256(repr(frozen).encode()).hexdigest()


def _path_for(key: str) -> str:
    return os.path.join(cache_dir(), key + _SUFFIX)


def _device_fingerprint_ok(compiled) -> bool:
    """Verify the deserialized executable's devices are THIS process's
    devices before the first dispatch.  ``input_shardings`` resolves to
    concrete Device objects at deserialize time; if any of them is not
    in ``jax.devices()`` the executable would dispatch onto hardware we
    don't have — reject it instead."""
    import jax
    have = {(d.platform, d.id) for d in jax.devices()}
    try:
        in_sh, _ = compiled.input_shardings
        for sh in jax.tree_util.tree_leaves(in_sh):
            for d in getattr(sh, "device_set", ()):
                if (d.platform, d.id) not in have:
                    return False
    except Exception:
        return False        # no introspectable shardings: don't trust it
    return True


def _single_device(compiled) -> bool:
    """Only single-device executables are cacheable (module docstring):
    judge the *executable*, not the process — a predictor bucket
    compiled for one device on a multi-device host is still safe."""
    import jax
    try:
        devs = set()
        in_sh, _ = compiled.input_shardings
        for sh in jax.tree_util.tree_leaves((in_sh,
                                             compiled.output_shardings)):
            for d in getattr(sh, "device_set", ()):
                devs.add((d.platform, d.id))
        if devs:
            return len(devs) == 1
    except Exception:
        pass
    return len(jax.devices()) == 1


def load(component: str, signature: dict):
    """A cached executable for this signature, or None (miss/reject).
    Every failure mode — unreadable file, stamp mismatch, deserialize
    error, device-fingerprint mismatch — is a reject + None; the serve
    path never sees an exception from here."""
    if not enabled():
        return None
    path = _path_for(cache_key(component, signature))
    if not os.path.exists(path):
        _count("misses")
        return None
    try:
        with open(path, "rb") as f:
            entry = pickle.load(f)
    except Exception as e:              # torn write, foreign file
        _count("rejects")
        _emit("reject", component=component, why=f"unreadable: {e}")
        return None
    if entry.get("stamp") != stamp():
        # a stale stamp means the key hash collided across stamps only
        # if the dir was populated by a different process config under
        # the same key — possible when the stamp itself changed after
        # files were written (jax upgrade in place).  Reject cleanly.
        _count("rejects")
        _emit("reject", component=component, why="stamp mismatch",
              entry_stamp=entry.get("stamp"), want=stamp())
        return None
    try:
        from . import jax_compat
        compiled = jax_compat.deserialize_executable(
            entry["payload"], entry["in_tree"], entry["out_tree"])
    except Exception as e:              # incompatible payload
        _count("rejects")
        _emit("reject", component=component, why=f"deserialize: {e}")
        return None
    if not _device_fingerprint_ok(compiled):
        _count("rejects")
        _emit("reject", component=component, why="device fingerprint")
        return None
    _count("hits")
    _emit("hit", component=component)
    return compiled


def store(component: str, signature: dict, compiled) -> bool:
    """Serialize a freshly compiled executable under its key.  Atomic
    (tmp + rename) so concurrent replicas sharing one cache dir never
    read a torn entry; single-device executables only (see module
    docstring).  Failures count ``compile_cache.errors`` and return
    False — the executable itself is unaffected."""
    if not enabled():
        return False
    try:
        if not _single_device(compiled):
            return False
        from . import jax_compat
        if not jax_compat.executable_serialization_available():
            return False
        payload, in_tree, out_tree = jax_compat.serialize_executable(
            compiled)
        entry = {"stamp": stamp(), "component": component,
                 "signature": {str(k): _freeze(v)
                               for k, v in signature.items()},
                 "payload": payload, "in_tree": in_tree,
                 "out_tree": out_tree}
        d = cache_dir()
        os.makedirs(d, exist_ok=True)
        fd, tmp = tempfile.mkstemp(dir=d, suffix=".tmp")
        try:
            with os.fdopen(fd, "wb") as f:
                pickle.dump(entry, f, protocol=pickle.HIGHEST_PROTOCOL)
            os.replace(tmp, _path_for(cache_key(component, signature)))
        except BaseException:
            try:
                os.unlink(tmp)
            except OSError:
                pass
            raise
    except Exception as e:          # serialization gap, disk full, ...
        _count("errors")
        _emit("error", component=component, why=str(e))
        return False
    _count("stores")
    _emit("store", component=component)
    return True


def cached_compile(component: str, signature: dict,
                   build: Callable[[], object]
                   ) -> Tuple[object, Optional[str]]:
    """The one-call integration point for a compile site: try the
    cache, else ``build()`` (the site's ``lower().compile()`` thunk)
    and store the result.  Returns ``(executable, provenance)`` where
    provenance is ``"loaded"`` / ``"compiled"`` for the compile
    record's ``cache=`` field, or None when the cache is disabled
    (records then omit the field entirely)."""
    if not enabled():
        return build(), None
    hit = load(component, signature)
    if hit is not None:
        return hit, "loaded"
    compiled = build()
    store(component, signature, compiled)
    return compiled, "compiled"


def stats() -> dict:
    """Current ``compile_cache.*`` counters (0 when never touched)."""
    from ..utils import monitor
    return {k: monitor.get_stat(f"compile_cache.{k}")
            for k in ("hits", "misses", "rejects", "stores", "errors")}


def reset_stats() -> None:
    from ..utils import monitor
    for k in ("hits", "misses", "rejects", "stores", "errors"):
        monitor.stat_reset(f"compile_cache.{k}")
