"""Profiler hook shared between core.dispatch and the profiler package.

Lives in core so the eager op hot path pays ONE None-check when profiling
is off (the reference gates the same way on g_state in
platform/profiler.cc)."""
from __future__ import annotations

from typing import Optional

_active = None


def set_active(profiler) -> None:
    global _active
    _active = profiler


def current() -> Optional[object]:
    return _active
