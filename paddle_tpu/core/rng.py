"""RNG / seeding.

TPU-native equivalent of the reference's global + per-device ``Generator``
(reference: paddle/fluid/framework/generator.h, generator.cc; pybind
generator_py.cc; ``paddle.seed``).

Design: JAX threaded-key PRNG instead of stateful Philox.  The global
``Generator`` owns a base key and a monotonically increasing counter; every
consumer calls :func:`next_key` which folds the counter into the base key.

Trace-safety: inside ``jit``/``to_static`` tracing, a *traced* base key can be
pushed with :func:`seed_scope` so random ops (dropout etc.) fold their
trace-time counter into a runtime key argument — every execution of the
compiled function can then use fresh randomness, unlike naive key capture.
"""
from __future__ import annotations

import contextlib
import threading

import jax
import jax.numpy as jnp


class Generator:
    """Stateful key source (reference: framework/generator.h).

    The base key materialises LAZILY: creating it touches the XLA
    backend, and importing the framework must not do that (multi-process
    jobs need jax.distributed.initialize to run first)."""

    def __init__(self, seed: int = 0):
        self._seed = seed
        self._key = None
        self._counter = 0
        self._lock = threading.Lock()

    def _base(self):
        if self._key is None:
            self._key = jax.random.key(self._seed)
        return self._key

    def manual_seed(self, seed: int):
        with self._lock:
            self._seed = seed
            self._key = None
            self._counter = 0
        return self

    @property
    def initial_seed(self) -> int:
        return self._seed

    def next_key(self):
        with self._lock:
            self._counter += 1
            c = self._counter
            base = self._base()
        return jax.random.fold_in(base, c)  # dispatch outside the lock


_global_generator = Generator(0)
_tls = threading.local()


def default_generator() -> Generator:
    return _global_generator


def seed(s: int) -> Generator:
    """paddle.seed parity."""
    return _global_generator.manual_seed(int(s))


def get_rng_state():
    return (_global_generator._seed, _global_generator._counter)


def set_rng_state(state):
    s, c = state
    _global_generator.manual_seed(s)
    _global_generator._counter = c


@contextlib.contextmanager
def seed_scope(key):
    """Route :func:`next_key` through ``key`` (a possibly-traced jax PRNG key).

    Used by the jit path so compiled programs take randomness as an input
    rather than baking trace-time keys in as constants.
    """
    prev = getattr(_tls, "scope", None)
    _tls.scope = [key, 0]
    try:
        yield
    finally:
        _tls.scope = prev


def next_key():
    scope = getattr(_tls, "scope", None)
    if scope is not None:
        scope[1] += 1
        return jax.random.fold_in(scope[0], scope[1])
    return _global_generator.next_key()


class StableDraw:
    """A random-op key source that is STABLE across re-executions of the
    same op but still per-run under a :func:`seed_scope`.

    Random ops (dropout and friends) must draw their key inside the
    traced function so compiled programs (static Executor, TrainStep)
    can thread a per-run key — but the eager tape's double-backward
    replays the stored fn in Python, and a plain :func:`next_key` there
    would advance the generator and regenerate a DIFFERENT mask than the
    forward that produced the first-order grads.  A StableDraw fixes the
    draw's identity at op-construction time (one generator tick) and
    resolves it lazily:

    - inside a seed_scope: ``fold_in(scope_key, id)`` — per-run via the
      scope's (possibly traced) key, identical on every replay;
    - eagerly: ``fold_in(base_key, id)`` — the same concrete key every
      replay, matching the pre-scope behavior.
    """

    __slots__ = ("_id",)

    def __init__(self):
        g = _global_generator
        with g._lock:
            g._counter += 1
            self._id = g._counter

    def key(self):
        scope = getattr(_tls, "scope", None)
        if scope is not None:
            return jax.random.fold_in(scope[0], self._id)
        return jax.random.fold_in(_global_generator._base(), self._id)


def stable_draw() -> StableDraw:
    return StableDraw()
