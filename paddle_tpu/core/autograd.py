"""Eager-mode autograd: a tensor-anchored gradient graph.

TPU-native replacement for the reference's dygraph engine: where the reference
records per-op ``GradOpNode``s during ``Tracer::TraceOp`` (reference:
paddle/fluid/imperative/tracer.cc:204-205) and sweeps them with dependency
counting in ``BasicEngine`` (reference: imperative/basic_engine.cc:39,154-235),
we record one :class:`Node` per traced op holding the ``jax.vjp`` closure.

Nodes are anchored to their *output tensors* (``Tensor._node``) and hold
references to their input tensors' producer nodes — so a graph lives exactly
as long as some tensor that can reach it, and dies with ordinary Python GC
(matching the reference, where the grad graph is freed when its VarBases
die).  ``backward`` collects the reachable subgraph and sweeps it in
descending record order (a valid reverse-topological order by construction).
Gradient accumulation (basic_engine.cc:154-216's EagerGradientAccumulator) is
plain cotangent summation keyed by snapshotted tensor ids.

The jit/``to_static`` path does NOT use this machinery — it differentiates
pure functions with ``jax.grad`` directly, mirroring how both of the
reference's execution modes share one kernel library (SURVEY §1).
"""
from __future__ import annotations

import contextlib
import itertools
import threading
from typing import Any, Dict, List, Optional

import jax
import numpy as np

from .enforce import UnimplementedError

_seq = itertools.count(1)


class Node:
    """One recorded op: inputs, output metadata, and the vjp closure.

    Input ids / leaf-ness / producer nodes are SNAPSHOTTED at record time:
    in-place-style APIs (``Tensor._rebind`` via ``__setitem__``) re-point a
    Python identity at a new autograd position, so reading ``t._bw_id`` at
    backward time would mis-route cotangents (the reference instead bumps an
    inplace version counter, tensor.h:77-87, and errors on misuse).

    ``vjp_fn`` is dropped after a non-retaining backward, freeing residuals
    and making a second backward raise — paddle's retain_graph semantics.
    """

    __slots__ = ("seq", "inputs", "in_ids", "in_leaf", "in_nodes", "vjp_fn",
                 "out_ids", "out_avals", "n_outs", "out_is_tuple",
                 "replay", "__weakref__")

    def __init__(self, inputs, vjp_fn, out_ids, out_avals,
                 out_is_tuple=False, replay=None):
        self.seq = next(_seq)
        self.inputs = inputs            # strong refs: leaves need .grad deposit
        self.in_ids = [t._bw_id for t in inputs]
        self.in_leaf = [t.is_leaf for t in inputs]
        self.in_nodes = [t._node for t in inputs]
        self.vjp_fn = vjp_fn
        self.out_ids = out_ids          # bw_id per output
        self.out_avals = out_avals      # (shape, dtype) per output
        self.n_outs = len(out_ids)
        self.out_is_tuple = out_is_tuple
        # (fn, kw, diff_idx, arrays): enough to re-derive this op's vjp as
        # a recordable op — the create_graph/double-backward path
        # (reference analog: partial_grad_engine.cc re-runs grad ops
        # through the tracer)
        self.replay = replay


_tls = threading.local()


def grad_enabled() -> bool:
    return getattr(_tls, "grad_enabled", True)


@contextlib.contextmanager
def no_grad():
    """paddle.no_grad parity.

    Also the memory lever for eager inference: outside no_grad every
    differentiable op records a tape node whose replay tuple pins its
    input arrays (double-backward support) until backward() frees them —
    large eager loops that never backprop should run inside this scope."""
    prev = grad_enabled()
    _tls.grad_enabled = False
    try:
        yield
    finally:
        _tls.grad_enabled = prev


@contextlib.contextmanager
def enable_grad():
    prev = grad_enabled()
    _tls.grad_enabled = True
    try:
        yield
    finally:
        _tls.grad_enabled = prev


def _is_float_dtype(dtype) -> bool:
    # jax.dtypes covers ml_dtypes extended floats (bfloat16/fp8), which
    # np.issubdtype misclassifies as non-float
    d = np.dtype(dtype)
    return (jax.dtypes.issubdtype(d, np.floating)
            or jax.dtypes.issubdtype(d, np.complexfloating))


def _zero_cotangent(shape, dtype):
    if not _is_float_dtype(dtype):
        return np.zeros(shape, jax.dtypes.float0)
    return np.zeros(shape, np.dtype(dtype))


def _collect(roots) -> List[Node]:
    """Reachable subgraph from root nodes, sorted in reverse record order."""
    seen: Dict[int, Node] = {}
    stack = [r for r in roots if r is not None]
    while stack:
        n = stack.pop()
        if id(n) in seen:
            continue
        seen[id(n)] = n
        for p in n.in_nodes:
            if p is not None and id(p) not in seen:
                stack.append(p)
    return sorted(seen.values(), key=lambda n: -n.seq)


def _sweep(nodes, cot, retain_graph, want=None, results=None,
           deposit_leaf_grad=False):
    """Shared reverse sweep for backward() and grad()."""
    from .tensor import Tensor

    for node in nodes:
        if not any(oid in cot for oid in node.out_ids):
            continue
        if node.vjp_fn is None:
            raise RuntimeError(
                "Trying to run backward through a graph that has already "
                "been freed; pass retain_graph=True to the first backward "
                "if you need to backward twice.")
        if want is not None:
            for oid in node.out_ids:
                if oid in want and oid in cot:
                    i = want[oid]
                    results[i] = cot[oid] if results[i] is None else (
                        results[i] + cot[oid])
        out_cots = tuple(
            cot.pop(oid) if oid in cot else _zero_cotangent(*aval)
            for oid, aval in zip(node.out_ids, node.out_avals))
        in_cots = (node.vjp_fn(out_cots) if node.out_is_tuple
                   else node.vjp_fn(out_cots[0]))
        if not retain_graph:
            node.vjp_fn = None
            node.replay = None  # frees the pinned input arrays too
        for tin, bid, leaf, g in zip(node.inputs, node.in_ids,
                                     node.in_leaf, in_cots):
            if g is None or tin is None:
                continue
            if isinstance(g, np.ndarray) and g.dtype == jax.dtypes.float0:
                continue
            from .selected_rows import SelectedRows
            if tin._backward_hooks:
                if isinstance(g, SelectedRows):
                    g = g.to_dense()  # hooks keep their dense contract
                for hook in tin._backward_hooks:
                    r = hook(Tensor(g, stop_gradient=True))
                    if r is not None:
                        g = r.data if isinstance(r, Tensor) else r
            if leaf and deposit_leaf_grad:
                if tin._grad_data is None:
                    tin._grad_data = g
                else:
                    tin._grad_data = tin._grad_data + g
            if not leaf or want is not None:
                cot[bid] = (cot[bid] + g) if bid in cot else g


def _make_grad_op(node):
    """Build a pure op computing node's input cotangents from (diff
    inputs, float-output cotangents) — recordable through the dispatch
    point, which is what makes grad-of-grad work."""
    import jax.numpy as jnp

    fn, kw, diff_idx, arrays = node.replay
    k = len(diff_idx)
    float_out = [_is_float_dtype(d) for _, d in node.out_avals]

    def grad_op(*args):
        diff_arrays = args[:k]
        ct_in = list(args[k:])
        cts = []
        for (shape, dt), is_f in zip(node.out_avals, float_out):
            if is_f:
                cts.append(jnp.asarray(ct_in.pop(0), dt))
            else:
                cts.append(np.zeros(shape, jax.dtypes.float0))

        def f(*d):
            full = list(arrays)
            for j, a in zip(diff_idx, d):
                full[j] = a
            return fn(*full, **kw)

        _, pull = jax.vjp(f, *diff_arrays)
        gs = pull(tuple(cts) if node.out_is_tuple else cts[0])
        return gs if len(gs) > 1 else gs[0]

    return grad_op, float_out


def _sweep_higher(nodes, cot, want, results):
    """create_graph sweep: cotangents are TENSORS and every vjp runs as a
    recorded op, so the result carries its own grad graph (reference:
    imperative/partial_grad_engine.cc create_graph mode)."""
    import jax.numpy as jnp
    from .dispatch import apply
    from .tensor import Tensor

    for node in nodes:
        if not any(oid in cot for oid in node.out_ids):
            continue
        if node.replay is None:
            raise UnimplementedError(
                f"create_graph=True through op without a replayable "
                f"gradient (custom sparse/manual node)")
        for oid in node.out_ids:
            if oid in want and oid in cot:
                i = want[oid]
                results[i] = (cot[oid] if results[i] is None
                              else results[i] + cot[oid])
        grad_op, float_out = _make_grad_op(node)
        ct_args = []
        for (shape, dt), oid, is_f in zip(node.out_avals, node.out_ids,
                                          float_out):
            if not is_f:
                continue
            t = cot.pop(oid, None)
            ct_args.append(t if t is not None
                           else Tensor(jnp.zeros(shape, dt)))
        # differentiate at the SNAPSHOTTED forward values (an in-place
        # _rebind may have repointed the live tensors), while keeping the
        # originals' autograd identity so third-order chains route
        _, _, diff_idx, arrays = node.replay
        snap_inputs, orig_of = [], {}
        for tin, j in zip(node.inputs, diff_idx):
            t = Tensor(arrays[j], stop_gradient=tin.stop_gradient,
                       _produced=tin._produced)
            t._bw_id = tin._bw_id
            t._node = tin._node
            snap_inputs.append(t)
            orig_of[id(t)] = tin
        with enable_grad():
            outs = apply(grad_op, *(snap_inputs + ct_args),
                         op_name="grad_of_grad")
        # the recorded grad node must deposit into the ORIGINAL tensors
        # (the snapshots only pin the forward-time values)
        first = outs[0] if isinstance(outs, tuple) else outs
        if first._node is not None:
            first._node.inputs = [orig_of.get(id(t), t)
                                  for t in first._node.inputs]
        in_cots = list(outs) if isinstance(outs, tuple) else [outs]
        for tin, bid, g in zip(node.inputs, node.in_ids, in_cots):
            if g is None:
                continue
            cot[bid] = (cot[bid] + g) if bid in cot else g


def backward(tensor, grad=None, retain_graph: bool = False):
    """Reverse sweep from ``tensor`` (paddle ``Tensor.backward`` parity).

    Reference analog: ``core.dygraph_run_backward`` → BasicEngine::Execute
    (pybind/imperative.cc:1542-1549; basic_engine.cc).
    """
    import jax.numpy as jnp
    from .tensor import Tensor

    if grad is None:
        if tensor.size != 1:
            raise RuntimeError(
                "grad must be provided for non-scalar tensor.backward()")
        g0 = jnp.ones(tensor.shape_tuple, tensor.dtype)
    else:
        g0 = grad.data if isinstance(grad, Tensor) else jnp.asarray(grad)

    if tensor._node is None:
        return
    nodes = _collect([tensor._node])
    cot: Dict[int, Any] = {tensor._bw_id: g0}
    with no_grad():
        _sweep(nodes, cot, retain_graph, deposit_leaf_grad=True)


def grad(outputs, inputs, grad_outputs=None, retain_graph=None,
         create_graph=False, only_inputs=True, allow_unused=False,
         no_grad_vars=None):
    """paddle.grad parity (reference: imperative/partial_grad_engine.cc).

    Computes grads of ``outputs`` w.r.t. ``inputs`` without touching ``.grad``.
    """
    from .tensor import Tensor
    import jax.numpy as jnp

    outs = outputs if isinstance(outputs, (list, tuple)) else [outputs]
    ins = inputs if isinstance(inputs, (list, tuple)) else [inputs]
    gouts = grad_outputs if isinstance(grad_outputs, (list, tuple)) else (
        [grad_outputs] * len(outs))
    if retain_graph is None:
        retain_graph = create_graph

    cot: Dict[int, Any] = {}
    for o, go in zip(outs, gouts):
        g = (jnp.ones(o.shape_tuple, o.dtype) if go is None
             else (go.data if isinstance(go, Tensor) else jnp.asarray(go)))
        cot[o._bw_id] = cot[o._bw_id] + g if o._bw_id in cot else g

    skip_ids = {t._bw_id for t in (no_grad_vars or [])}
    want = {t._bw_id: i for i, t in enumerate(ins)}
    results: List[Optional[Any]] = [None] * len(ins)

    nodes = _collect([o._node for o in outs])
    if create_graph:
        # cotangents become Tensors and every vjp is a recorded op — the
        # returned grads carry their own graph for a second backward
        cot_t = {bid: Tensor(g, stop_gradient=True)
                 for bid, g in cot.items()}
        _sweep_higher(nodes, cot_t, want, results)
        for bid, i in want.items():
            if bid in cot_t and results[i] is None:
                results[i] = cot_t[bid]
        out_tensors = [None if (r is None or ins[i]._bw_id in skip_ids)
                       else r for i, r in enumerate(results)]
        if not allow_unused:
            for i, r in enumerate(out_tensors):
                if r is None:
                    raise RuntimeError(
                        f"Input {i} is unreachable from outputs; pass "
                        f"allow_unused=True to get None instead.")
        return (out_tensors if isinstance(inputs, (list, tuple))
                else out_tensors[0])

    with no_grad():
        _sweep(nodes, cot, retain_graph, want=want, results=results)

    # leaves (and any wanted id whose cotangent is still pending)
    for bid, i in want.items():
        if bid in cot and results[i] is None:
            results[i] = cot[bid]

    from .selected_rows import SelectedRows
    out_tensors: List[Optional[Tensor]] = [
        None if (r is None or ins[i]._bw_id in skip_ids)
        else Tensor(r.to_dense() if isinstance(r, SelectedRows) else r,
                    stop_gradient=True)
        for i, r in enumerate(results)]
    if not allow_unused:
        for i, r in enumerate(out_tensors):
            if r is None:
                raise RuntimeError(
                    f"Input {i} is unreachable from outputs; pass "
                    f"allow_unused=True to get None instead.")
    return out_tensors if isinstance(inputs, (list, tuple)) else out_tensors[0]
