"""paddle_tpu.parallel — parallel execution building blocks
(reference analogs: imperative/reducer.cc DataParallel, fleet meta-parallel
layers, sharding_optimizer.py, section_worker.cc pipeline schedules; plus
beyond-reference ring attention, SURVEY §5.7)."""
from .data_parallel import DataParallel  # noqa: F401
from .localsgd import LocalSGDTrainStep  # noqa: F401
from .pipeline import (Pipeline, PipelineStage, pipelined_fn,  # noqa
                       pipeline_train_fn, stack_stage_params)
from .recompute import recompute, recompute_sequential  # noqa: F401
from .ring_attention import (reference_attention, ring_attention,  # noqa
                             ring_attention_per_device)
from .sharded_embedding import ShardedEmbedding  # noqa: F401
from .spmd_train_step import SpmdTrainStep  # noqa: F401
from .tp_layers import (ColumnParallelLinear, ParallelCrossEntropy,  # noqa
                        RowParallelLinear, VocabParallelEmbedding,
                        get_placement, set_placement, split)
