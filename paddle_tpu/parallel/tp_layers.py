"""Tensor-parallel (Megatron-style) layers.

Reference: ``paddle.distributed.split`` (collective.py:809) with
``_parallel_linear`` (:735, row/column parallel Linear) and
``_parallel_embedding`` (:769) built on c_allreduce/c_concat ops.

TPU-native: a TP layer is an ORDINARY layer whose weight carries a
``placement`` (PartitionSpec over the 'mp' mesh axis).  Under the SPMD train
step, GSPMD partitions the matmul and inserts the reduction collectives the
reference emits by hand — no explicit c_allreduce needed.  Eager
single-chip execution is unchanged (placement is metadata).
"""
from __future__ import annotations

import jax.numpy as jnp
from jax.sharding import PartitionSpec

from ..core.tensor import Parameter
from ..nn import functional as F
from ..nn import initializer as I
from ..nn.layer_base import Layer
from ..distributed.mesh import MP_AXIS


def set_placement(param: Parameter, *spec) -> Parameter:
    """Attach a PartitionSpec placement to a Parameter (consumed by
    SpmdTrainStep / dryrun_multichip for in_shardings)."""
    param.placement = PartitionSpec(*spec)
    return param


mark_placement = set_placement


def get_placement(param):
    return getattr(param, "placement", None)


class ColumnParallelLinear(Layer):
    """W split along output dim over 'mp'; output stays sharded unless
    gather_output (reference: collective.py:735 axis=1 branch)."""

    def __init__(self, in_features, out_features, weight_attr=None,
                 has_bias=True, gather_output=True, name=None):
        super().__init__()
        self.gather_output = gather_output
        self.weight = self.create_parameter(
            [in_features, out_features], attr=weight_attr,
            default_initializer=I.XavierNormal())
        set_placement(self.weight, None, MP_AXIS)
        if has_bias:
            self.bias = self.create_parameter([out_features], is_bias=True)
            set_placement(self.bias, MP_AXIS)
        else:
            self.bias = None

    def forward(self, x):
        return F.linear(x, self.weight, self.bias)


class RowParallelLinear(Layer):
    """W split along input dim over 'mp'; GSPMD inserts the psum the
    reference adds as c_allreduce_sum (collective.py:735 axis=0 branch)."""

    def __init__(self, in_features, out_features, weight_attr=None,
                 has_bias=True, input_is_parallel=False, name=None):
        super().__init__()
        self.weight = self.create_parameter(
            [in_features, out_features], attr=weight_attr,
            default_initializer=I.XavierNormal())
        set_placement(self.weight, MP_AXIS, None)
        if has_bias:
            self.bias = self.create_parameter([out_features], is_bias=True)
        else:
            self.bias = None

    def forward(self, x):
        return F.linear(x, self.weight, self.bias)


class VocabParallelEmbedding(Layer):
    """Embedding table row-split over 'mp'
    (reference: _parallel_embedding collective.py:769)."""

    def __init__(self, num_embeddings, embedding_dim, weight_attr=None,
                 name=None):
        super().__init__()
        self.weight = self.create_parameter(
            [num_embeddings, embedding_dim], attr=weight_attr,
            default_initializer=I.Normal(0.0, 0.02))
        set_placement(self.weight, MP_AXIS, None)

    def forward(self, x):
        return F.embedding(x, self.weight)


class ParallelCrossEntropy(Layer):
    """Loss over mp-sharded logits; GSPMD handles the reduction."""

    def __init__(self, name=None):
        super().__init__()

    def forward(self, logits, label):
        return F.cross_entropy(logits, label, reduction="mean")


def split(x, size, operation, axis=0, num_partitions=1, gather_out=True,
          weight_attr=None, bias_attr=None, name=None):
    """paddle.distributed.split parity (reference: collective.py:809).

    Returns a TP layer applied to x."""
    if operation == "linear":
        in_f, out_f = size
        if axis == 0:
            layer = RowParallelLinear(in_f, out_f, weight_attr,
                                      bias_attr is not False)
        else:
            layer = ColumnParallelLinear(in_f, out_f, weight_attr,
                                         bias_attr is not False,
                                         gather_output=gather_out)
        return layer(x)
    if operation == "embedding":
        n, d = size
        layer = VocabParallelEmbedding(n, d, weight_attr)
        return layer(x)
    raise ValueError(f"unsupported split operation: {operation}")
