"""SpmdTrainStep — the multi-chip training step.

TPU-native replacement for the reference's entire multi-device execution
stack: ParallelExecutor SSA graphs + per-grad allreduce insertion
(reference: multi_devices_graph_pass.cc:484,724; details/
all_reduce_op_handle.cc), fleet GraphExecutionOptimizer, and the sharding
meta-optimizer (sharding_optimizer.py:33).

One jit'd step over a ``Mesh`` with explicit in/out shardings:
- batch sharded over 'dp'  → gradient psum falls out of GSPMD (the DDP
  Reducer's fused allreduce, reducer.cc, becomes compiler-scheduled)
- ZeRO stage 1: optimizer slots sharded over 'dp'
- ZeRO stage 2: grads constrained to 'dp' shardings before the update, so
  XLA reduce-scatters gradients, updates shard-locally, and all-gathers
  the new params (the reference's broadcast+reduce choreography,
  sharding_optimizer.py:103-171, becomes three compiler-inserted
  collectives)
- ZeRO stage 3: params themselves sharded over 'dp'.  Params whose dim 0
  is not divisible by dp are stored *padded* to the next multiple (the
  reference pads to numel, meta_optimizers/sharding/shard.py) and sliced
  back inside the trace, so odd vocab sizes and bias vectors still shard.
- TP: params carrying placements (parallel/tp_layers.py) partition their
  matmuls over 'mp'.
- strategy.gradient_merge → in-step microbatch accumulation;
  strategy.amp (float16) → in-graph dynamic loss scaling;
  strategy.recompute → jax.checkpoint over the loss (rematerialised
  backward, recompute_optimizer.py:18);
  strategy.grad_comm (and its alias strategy.fp16_allreduce ==
  grad_comm.dtype='bf16') → the explicit gradient-collective stage
  (distributed/grad_comm.py): grads bucketed and quantised to the wire
  dtype, reduced inside a shard_map over 'dp' with per-bucket
  latency-vs-bandwidth algorithm selection
  (fp16_allreduce_optimizer.py:18; bf16 instead of fp16 because bf16
  shares f32's exponent range — no loss-scale overflow on the wire — and
  is the TPU-native half type.  The error-feedback residual carry lives
  on the static Executor path).
"""
from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec

from ..distributed.mesh import DP_AXIS, ensure_mesh
from ..distributed.strategy import DistributedStrategy
from ..jit.train_step import TrainStep
from .tp_layers import get_placement


def _numel(shape):
    n = 1
    for d in shape:
        n *= d
    return n


def _pvary(x, axis):
    """Mark ``x`` as device-varying over ``axis`` inside shard_map
    (jax>=0.9 spells this lax.pcast(to='varying'); identity on jax
    without varying types)."""
    from ..core.jax_compat import pvary
    return pvary(x, axis)


def _shardable(shape, n):
    return len(shape) > 0 and shape[0] % n == 0 and shape[0] >= n


class SpmdTrainStep(TrainStep):
    """TrainStep + mesh shardings.  ``strategy`` controls ZeRO stage etc."""

    def __init__(self, model, loss_fn, optimizer, mesh=None,
                 strategy: Optional[DistributedStrategy] = None,
                 n_inputs: int = 1, donate: bool = True, scaler=None,
                 accumulate_steps: Optional[int] = None):
        strategy = strategy or DistributedStrategy()
        from ..distributed.strategy import validate_toggles
        validate_toggles(strategy)
        if accumulate_steps is None:
            accumulate_steps = (strategy.gradient_merge_configs.k_steps
                                if strategy.gradient_merge else 1)
        amp_level = None
        if strategy.amp:
            c = strategy.amp_configs
            if scaler is None and c.dtype == "float16":
                from ..amp import GradScaler
                scaler = GradScaler(
                    init_loss_scaling=c.init_loss_scaling,
                    incr_ratio=c.incr_ratio, decr_ratio=c.decr_ratio,
                    incr_every_n_steps=c.incr_every_n_steps,
                    decr_every_n_nan_or_inf=c.decr_every_n_nan_or_inf,
                    use_dynamic_loss_scaling=c.use_dynamic_loss_scaling)
            # wire the autocast itself, not just the scaler — bf16 O1/O2
            # previously compiled with no cast at all (silent no-op)
            amp_level = "O2" if c.use_pure_fp16 else "O1"
            model._amp_dtype = c.dtype
        super().__init__(model, loss_fn, optimizer, n_inputs, donate,
                         scaler=scaler, accumulate_steps=accumulate_steps,
                         recompute=strategy.recompute, amp_level=amp_level)
        self.mesh = mesh or ensure_mesh()
        self.strategy = strategy
        # explicit gradient-collective stage (distributed/grad_comm.py):
        # strategy.grad_comm knobs, with strategy.fp16_allreduce as the
        # backward-compatible alias for a bf16 wire
        from ..distributed import grad_comm as _gc
        self._grad_comm = _gc.resolve(strategy)
        self._comm_plan = None
        if self._grad_comm is not None:
            zero3 = (strategy.sharding
                     and strategy.sharding_configs.stage >= 3)
            msg = _gc.incompatibility(
                self._grad_comm, self.mesh.shape,
                sharded_params=(["<ZeRO-3 stage-3 params>"] if zero3
                                else ()))
            if msg is not None:
                raise NotImplementedError(msg)
            if (self._grad_comm.error_feedback
                    and self._grad_comm.source == "grad_comm"
                    and self._grad_comm.dtype != "fp32"):
                import warnings
                warnings.warn(
                    "grad_comm.error_feedback: the per-device residual "
                    "carry lives in the static Executor's donated state; "
                    "SpmdTrainStep reduces without error feedback.  Use "
                    "the static path (fleet + Executor) for EF, or set "
                    "error_feedback=False to silence this.")
        # -- ZeRO-3 padding plan (reference: sharding/shard.py pads numel) --
        self._padded = {}
        if (strategy.sharding and strategy.sharding_configs.stage >= 3
                and DP_AXIS in self.mesh.shape):
            dp = self.mesh.shape[DP_AXIS]
            min_numel = strategy.sharding_configs.min_shard_numel
            for i, p in enumerate(self._params):
                shp = p.shape_tuple
                if (get_placement(p) is None and len(shp) > 0
                        and _numel(shp) >= min_numel and shp[0] % dp != 0):
                    pad_d0 = -(-shp[0] // dp) * dp
                    self._padded[i] = (shp[0], pad_d0)
        self._p_store = None       # padded/sharded master copies
        self._store_dirty = False
        self._seen_pdata = {}      # padded idx -> p.data identity at encode

    # -- sharding rules ----------------------------------------------------
    def _dp_size(self) -> int:
        return self.mesh.shape.get(DP_AXIS, 1)

    def _stage3_sharded(self, i, p) -> bool:
        if not (self.strategy.sharding
                and self.strategy.sharding_configs.stage >= 3
                and DP_AXIS in self.mesh.shape
                and get_placement(p) is None):
            return False
        if i in self._padded:
            return True
        shp = p.shape_tuple
        return (_numel(shp) >= self.strategy.sharding_configs.min_shard_numel
                and _shardable(shp, self._dp_size()))

    def _param_spec(self, i, p) -> PartitionSpec:
        pl = get_placement(p)
        if pl is not None:
            return pl
        if self._stage3_sharded(i, p):
            return PartitionSpec(DP_AXIS)
        return PartitionSpec()

    def _slot_spec(self, i, p, slot_shape) -> PartitionSpec:
        pl = get_placement(p)
        if pl is not None and tuple(slot_shape) == p.shape_tuple:
            return pl
        stored_shape = self._stored_shape(i, p)
        if (self.strategy.sharding
                and self.strategy.sharding_configs.stage >= 1
                and DP_AXIS in self.mesh.shape
                and tuple(slot_shape) == stored_shape
                and _shardable(slot_shape, self._dp_size())):
            return PartitionSpec(DP_AXIS)
        return PartitionSpec()

    def _stored_shape(self, i, p):
        if i in self._padded:
            return (self._padded[i][1],) + p.shape_tuple[1:]
        return p.shape_tuple

    def _ns(self, spec) -> NamedSharding:
        return NamedSharding(self.mesh, spec)

    # -- ZeRO-3 padded store ----------------------------------------------
    def _encode_param(self, i, arr):
        if i in self._padded:
            d0, pad_d0 = self._padded[i]
            widths = [(0, pad_d0 - d0)] + [(0, 0)] * (arr.ndim - 1)
            arr = jnp.pad(arr, widths)
        return arr

    def _decode_params(self, p_list):
        if not self._padded:
            return p_list
        out = []
        for i, a in enumerate(p_list):
            if i in self._padded:
                a = jax.lax.slice_in_dim(a, 0, self._padded[i][0], axis=0)
            out.append(a)
        return out

    def _encode_and_demote(self, i):
        """Encode padded param i into its dp-sharded store form, then
        demote ``p.data`` to a host mirror — keeping the original full
        device array alive would erase the stage-3 memory saving."""
        import weakref

        import numpy as _np
        p = self._params[i]
        stored = jax.device_put(self._encode_param(i, p.data),
                                self._ns(self._param_spec(i, p)))
        host = _np.asarray(p.data)
        p.data = host
        p._param_owner_step = weakref.ref(self)  # state_dict auto-sync
        self._seen_pdata[i] = host
        return stored

    def _param_arrays(self):
        if not self._padded:
            return super()._param_arrays()
        if self._p_store is None:
            store = list(p.data for p in self._params)
            for i in self._padded:
                store[i] = self._encode_and_demote(i)
            self._p_store = tuple(store)
        else:
            # rebuild the tuple each call: non-padded entries read p.data
            # fresh (honors external set_state_dict), padded entries are
            # re-encoded only when p.data changed identity since encode
            store = list(self._p_store)
            for i, p in enumerate(self._params):
                if i not in self._padded:
                    store[i] = p.data
                elif p.data is not self._seen_pdata.get(i):
                    store[i] = self._encode_and_demote(i)
            self._p_store = tuple(store)
        return self._p_store

    def _writeback_params(self, new_p):
        if not self._padded:
            return super()._writeback_params(new_p)
        self._p_store = tuple(new_p)
        for i, (p, arr) in enumerate(zip(self._params, new_p)):
            if i not in self._padded:
                p.data = arr
        self._store_dirty = True

    def sync_params(self):
        """Materialise padded ZeRO-3 shards back into model params.

        Under stage 3 with padding, ``p.data`` is not refreshed per step
        (doing so would keep a gathered full copy alive and erase the
        memory saving); call this before ``state_dict()``/checkpointing."""
        if self._p_store is not None and self._store_dirty:
            for i in self._padded:
                d0, _ = self._padded[i]
                self._params[i].data = self._p_store[i][:d0]
                self._seen_pdata[i] = self._params[i].data
            self._store_dirty = False

    # -- ZeRO-2: reduce-scatter grads + sharded update --------------------
    def _grad_transform(self, grads):
        if not (self.strategy.sharding
                and self.strategy.sharding_configs.stage >= 2
                and DP_AXIS in self.mesh.shape):
            return grads
        n = self._dp_size()
        out = []
        for p, g in zip(self._params, grads):
            if get_placement(p) is None and _shardable(g.shape, n):
                # constraining the grad to 'dp' makes XLA lower the grad
                # psum as reduce-scatter, run the optimizer shard-local,
                # and all-gather the updated params — ZeRO-2 dataflow
                out.append(jax.lax.with_sharding_constraint(
                    g, self._ns(PartitionSpec(DP_AXIS))))
            else:
                out.append(g)
        return out

    # -- grad_comm: explicit bucketed/quantized grad reduction ------------
    def _wrap_loss_and_grad(self, fn):
        cfg = self._grad_comm
        if cfg is None:
            return fn
        mesh = self.mesh
        dp = self._dp_size()
        if dp <= 1:
            return fn  # nothing crosses a wire
        from ..distributed import grad_comm as _gc
        shapes = [self._stored_shape(i, p)
                  for i, p in enumerate(self._params)]
        plan = _gc.plan_reduction(shapes, dp=dp, cfg=cfg)
        self._comm_plan = plan

        def wrapped(p_cur, b_cur, mb_inputs, mb_labels, kidx):
            def local(ins, labs, k):
                # decorrelate per-shard dropout masks
                k = k * dp + jax.lax.axis_index(DP_AXIS)
                # differentiate w.r.t. a device-VARYING copy of the params:
                # grads stay local (no compiler-inserted f32 psum for the
                # invariant cotangent) so the ONLY reduction is ours below
                p_var = [_pvary(a, DP_AXIS) for a in p_cur]
                loss, new_b, grads = fn(p_var, b_cur, ins, labs, k)
                # bucketed quantize → reduce → dequantize: the wire
                # carries the plan's dtype (bf16 subsumes the old
                # fp16_allreduce cast/recast pair,
                # fp16_allreduce_optimizer.py:18); residual-less — the
                # error-feedback carry lives on the Executor path.
                # The overlap lowering follows the plan's resolved
                # path (strategy.grad_comm.overlap), same as the
                # Executor — ring/none/xla are numerics-compatible
                grads, _ = _gc.reduce_gradients(
                    grads, plan=plan, axis_name=DP_AXIS, residuals=None)
                loss = jax.lax.pmean(loss, DP_AXIS)
                new_b = jax.tree.map(
                    lambda a: jax.lax.pmean(a, DP_AXIS), new_b)
                return loss, new_b, grads

            from ..core.jax_compat import shard_map
            P = PartitionSpec
            # check_vma off: the int8 route's all_to_all/all_gather
            # results are replicated by construction, which the static
            # replication checker cannot infer
            return shard_map(
                local, mesh=mesh,
                in_specs=(P(DP_AXIS), P(DP_AXIS), P()),
                out_specs=P(), check_vma=False)(mb_inputs, mb_labels,
                                                kidx)

        return wrapped

    def _build(self, training: bool):
        step_fn = self._make_step_fn()
        p_specs = tuple(self._ns(self._param_spec(i, p))
                        for i, p in enumerate(self._params))
        b_specs = tuple(self._ns(PartitionSpec())
                        for _ in self._bnames)
        state = self._opt_state or self.optimizer.functional_init(
            list(self._param_arrays()))
        s_specs = [
            {k: self._ns(self._slot_spec(i, p, v.shape))
             for k, v in slots.items()}
            for i, (p, slots) in enumerate(zip(self._params, state))]
        scalar = self._ns(PartitionSpec())
        aux_specs = {k: scalar for k in self._aux_keys()}
        batch_spec = self._ns(PartitionSpec(DP_AXIS))
        jitted = jax.jit(
            step_fn,
            in_shardings=(p_specs, b_specs, s_specs, aux_specs, scalar,
                          None, None),
            out_shardings=(scalar, p_specs, b_specs, s_specs, aux_specs),
            donate_argnums=(0, 1, 2, 3) if self._donate else (),
        )
        return _ShardBatch(jitted, batch_spec, self.n_inputs)


class _ShardBatch:
    """Callable shim: places batch arrays with dp sharding, then calls the
    jitted step (jit infers shardings for key/inputs/labels from committed
    device placement)."""

    def __init__(self, jitted, batch_spec, n_inputs):
        self._jitted = jitted
        self._spec = batch_spec
        self.n_inputs = n_inputs

    def lower(self, *args):
        return self._jitted.lower(*args)

    def __call__(self, p_arr, b_arr, opt_state, aux, lr, inputs, labels):
        put = lambda a: jax.device_put(a, self._spec)
        inputs = tuple(put(a) for a in inputs)
        labels = tuple(put(a) for a in labels)
        return self._jitted(p_arr, b_arr, opt_state, aux, lr, inputs,
                            labels)
