"""SpmdTrainStep — the multi-chip training step.

TPU-native replacement for the reference's entire multi-device execution
stack: ParallelExecutor SSA graphs + per-grad allreduce insertion
(reference: multi_devices_graph_pass.cc:484,724; details/
all_reduce_op_handle.cc), fleet GraphExecutionOptimizer, and the sharding
meta-optimizer (sharding_optimizer.py:33).

One jit'd step over a ``Mesh`` with explicit in/out shardings:
- batch sharded over 'dp'  → gradient psum falls out of GSPMD (the DDP
  Reducer's fused allreduce, reducer.cc, becomes compiler-scheduled)
- ZeRO: optimizer slots (stage≥1) / params (stage 3) sharded over 'dp'
  (the reference's broadcast+reduce choreography, sharding_optimizer.py:103,
  becomes GSPMD all-gather/reduce-scatter)
- TP: params carrying placements (parallel/tp_layers.py) partition their
  matmuls over 'mp'.
"""
from __future__ import annotations

from typing import Any, Dict, Optional

import jax
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec

from ..core.tensor import Tensor
from ..distributed.mesh import DP_AXIS, MP_AXIS, ensure_mesh
from ..distributed.strategy import DistributedStrategy
from ..jit.train_step import TrainStep, _as_arr
from .tp_layers import get_placement


def _shardable(shape, n):
    return len(shape) > 0 and shape[0] % n == 0 and shape[0] >= n


class SpmdTrainStep(TrainStep):
    """TrainStep + mesh shardings.  ``strategy`` controls ZeRO stage etc."""

    def __init__(self, model, loss_fn, optimizer, mesh=None,
                 strategy: Optional[DistributedStrategy] = None,
                 n_inputs: int = 1, donate: bool = True):
        super().__init__(model, loss_fn, optimizer, n_inputs, donate)
        self.mesh = mesh or ensure_mesh()
        self.strategy = strategy or DistributedStrategy()

    # -- sharding rules ----------------------------------------------------
    def _param_spec(self, p) -> PartitionSpec:
        pl = get_placement(p)
        if pl is not None:
            return pl
        if (self.strategy.sharding
                and self.strategy.sharding_configs.stage >= 3
                and DP_AXIS in self.mesh.shape
                and _shardable(p.shape_tuple, self.mesh.shape[DP_AXIS])):
            return PartitionSpec(DP_AXIS)
        return PartitionSpec()

    def _slot_spec(self, p, slot_shape) -> PartitionSpec:
        pl = get_placement(p)
        if pl is not None and tuple(slot_shape) == p.shape_tuple:
            return pl
        if (self.strategy.sharding
                and self.strategy.sharding_configs.stage >= 1
                and DP_AXIS in self.mesh.shape
                and _shardable(slot_shape, self.mesh.shape[DP_AXIS])):
            return PartitionSpec(DP_AXIS)
        return PartitionSpec()

    def _ns(self, spec) -> NamedSharding:
        return NamedSharding(self.mesh, spec)

    def _build(self, training: bool):
        # rebuild step_fn exactly as TrainStep does, then jit with shardings
        step_fn = self._make_step_fn()
        p_specs = tuple(self._ns(self._param_spec(p)) for p in self._params)
        b_specs = tuple(self._ns(PartitionSpec())
                        for _ in self._bnames)
        state = self._opt_state or self.optimizer.functional_init(
            [p.data for p in self._params])
        s_specs = [
            {k: self._ns(self._slot_spec(p, v.shape))
             for k, v in slots.items()}
            for p, slots in zip(self._params, state)]
        batch_spec = self._ns(PartitionSpec(DP_AXIS))
        scalar = self._ns(PartitionSpec())
        jitted = jax.jit(
            step_fn,
            in_shardings=(p_specs, b_specs, s_specs, scalar, scalar,
                          scalar, None, None),
            out_shardings=(scalar, p_specs, b_specs, s_specs),
            donate_argnums=(0, 1, 2) if self._donate else (),
        )
        return _ShardBatch(jitted, batch_spec, self.n_inputs)

    def _make_step_fn(self):
        from ..core import autograd, rng
        from ..jit.bind import bind
        model, loss_fn, opt = self.model, self.loss_fn, self.optimizer
        params_meta = self._params
        bnames = self._bnames

        def step_fn(p_arr, b_arr, opt_state, lr, step_i, key_data, inputs,
                    labels):
            key = jax.random.wrap_key_data(key_data)

            def loss_of(p_list):
                with autograd.no_grad(), rng.seed_scope(key):
                    with bind(model, p_list, list(b_arr)) as res:
                        out = model(*[Tensor(a) for a in inputs])
                        lab = [Tensor(a) for a in labels]
                        loss_t = loss_fn(out, *lab)
                    # new_buffers is populated on bind-context exit
                    new_b = tuple(
                        _as_arr(res.new_buffers.get(n, old))
                        for n, old in zip(bnames, b_arr))
                return loss_t.data, new_b

            (loss, new_b), grads = jax.value_and_grad(
                loss_of, has_aux=True)(list(p_arr))
            new_p, new_s = opt.functional_update(
                list(p_arr), grads, opt_state, lr, step_i,
                params_meta=params_meta)
            return loss, tuple(new_p), new_b, new_s

        return step_fn

    def __call__(self, *batch):
        inputs = tuple(_as_arr(b) for b in batch[:self.n_inputs])
        labels = tuple(_as_arr(b) for b in batch[self.n_inputs:])
        if self._opt_state is None:
            self._opt_state = self.optimizer.functional_init(
                [p.data for p in self._params])
        training = self.model.training
        compiled = self._compiled.get(training)
        if compiled is None:
            compiled = self._build(training)
            self._compiled[training] = compiled
        from ..core import rng
        self.optimizer._step_count += 1
        lr = jnp.asarray(self.optimizer.get_lr(), jnp.float32)
        step_i = jnp.asarray(self.optimizer._step_count, jnp.float32)
        key_data = jax.random.key_data(rng.next_key())
        p_arr = tuple(p.data for p in self._params)
        from ..jit.bind import buffer_arrays
        b_arr = tuple(buffer_arrays(self.model))
        loss, new_p, new_b, new_s = compiled(
            p_arr, b_arr, self._opt_state, lr, step_i, key_data, inputs,
            labels)
        for p, arr in zip(self._params, new_p):
            p.data = arr
        buffers = dict(self.model.named_buffers())
        for n, arr in zip(self._bnames, new_b):
            buffers[n].data = arr
        self._opt_state = new_s
        return Tensor(loss)


class _ShardBatch:
    """Callable shim: places batch arrays with dp sharding, then calls the
    jitted step (jit infers shardings for key/inputs/labels from committed
    device placement)."""

    def __init__(self, jitted, batch_spec, n_inputs):
        self._jitted = jitted
        self._spec = batch_spec
        self.n_inputs = n_inputs

    def __call__(self, p_arr, b_arr, opt_state, lr, step_i, key_data,
                 inputs, labels):
        put = lambda a: jax.device_put(a, self._spec)
        inputs = tuple(put(a) for a in inputs)
        labels = tuple(put(a) for a in labels)
        return self._jitted(p_arr, b_arr, opt_state, lr, step_i, key_data,
                            inputs, labels)
