"""SpmdTrainStep — the multi-chip training step.

TPU-native replacement for the reference's entire multi-device execution
stack: ParallelExecutor SSA graphs + per-grad allreduce insertion
(reference: multi_devices_graph_pass.cc:484,724; details/
all_reduce_op_handle.cc), fleet GraphExecutionOptimizer, and the sharding
meta-optimizer (sharding_optimizer.py:33).

One jit'd step over a ``Mesh`` with explicit in/out shardings:
- batch sharded over 'dp'  → gradient psum falls out of GSPMD (the DDP
  Reducer's fused allreduce, reducer.cc, becomes compiler-scheduled)
- ZeRO stage 1: optimizer slots sharded over 'dp'
- ZeRO stage 2: grads constrained to 'dp' shardings before the update, so
  XLA reduce-scatters gradients, updates shard-locally, and all-gathers
  the new params (the reference's broadcast+reduce choreography,
  sharding_optimizer.py:103-171, becomes three compiler-inserted
  collectives)
- ZeRO stage 3: params themselves sharded over 'dp'
- TP: params carrying placements (parallel/tp_layers.py) partition their
  matmuls over 'mp'.
- strategy.gradient_merge → in-step microbatch accumulation;
  strategy.amp (float16) → in-graph dynamic loss scaling
  (both inherited from jit.TrainStep).
"""
from __future__ import annotations

from typing import Optional

import jax
from jax.sharding import NamedSharding, PartitionSpec

from ..distributed.mesh import DP_AXIS, ensure_mesh
from ..distributed.strategy import DistributedStrategy
from ..jit.train_step import TrainStep
from .tp_layers import get_placement


def _shardable(shape, n):
    return len(shape) > 0 and shape[0] % n == 0 and shape[0] >= n


class SpmdTrainStep(TrainStep):
    """TrainStep + mesh shardings.  ``strategy`` controls ZeRO stage etc."""

    def __init__(self, model, loss_fn, optimizer, mesh=None,
                 strategy: Optional[DistributedStrategy] = None,
                 n_inputs: int = 1, donate: bool = True, scaler=None,
                 accumulate_steps: Optional[int] = None):
        strategy = strategy or DistributedStrategy()
        if accumulate_steps is None:
            accumulate_steps = (strategy.gradient_merge_configs.k_steps
                                if strategy.gradient_merge else 1)
        if (scaler is None and strategy.amp
                and strategy.amp_configs.dtype == "float16"):
            from ..amp import GradScaler
            c = strategy.amp_configs
            scaler = GradScaler(
                init_loss_scaling=c.init_loss_scaling,
                incr_ratio=c.incr_ratio, decr_ratio=c.decr_ratio,
                incr_every_n_steps=c.incr_every_n_steps,
                decr_every_n_nan_or_inf=c.decr_every_n_nan_or_inf,
                use_dynamic_loss_scaling=c.use_dynamic_loss_scaling)
        super().__init__(model, loss_fn, optimizer, n_inputs, donate,
                         scaler=scaler, accumulate_steps=accumulate_steps)
        self.mesh = mesh or ensure_mesh()
        self.strategy = strategy

    # -- sharding rules ----------------------------------------------------
    def _dp_size(self) -> int:
        return self.mesh.shape.get(DP_AXIS, 1)

    def _param_spec(self, p) -> PartitionSpec:
        pl = get_placement(p)
        if pl is not None:
            return pl
        if (self.strategy.sharding
                and self.strategy.sharding_configs.stage >= 3
                and DP_AXIS in self.mesh.shape
                and _shardable(p.shape_tuple, self._dp_size())):
            return PartitionSpec(DP_AXIS)
        return PartitionSpec()

    def _slot_spec(self, p, slot_shape) -> PartitionSpec:
        pl = get_placement(p)
        if pl is not None and tuple(slot_shape) == p.shape_tuple:
            return pl
        if (self.strategy.sharding
                and self.strategy.sharding_configs.stage >= 1
                and DP_AXIS in self.mesh.shape
                and _shardable(slot_shape, self._dp_size())):
            return PartitionSpec(DP_AXIS)
        return PartitionSpec()

    def _ns(self, spec) -> NamedSharding:
        return NamedSharding(self.mesh, spec)

    # -- ZeRO-2: reduce-scatter grads + sharded update --------------------
    def _grad_transform(self, grads):
        if not (self.strategy.sharding
                and self.strategy.sharding_configs.stage >= 2
                and DP_AXIS in self.mesh.shape):
            return grads
        n = self._dp_size()
        out = []
        for p, g in zip(self._params, grads):
            if get_placement(p) is None and _shardable(g.shape, n):
                # constraining the grad to 'dp' makes XLA lower the grad
                # psum as reduce-scatter, run the optimizer shard-local,
                # and all-gather the updated params — ZeRO-2 dataflow
                out.append(jax.lax.with_sharding_constraint(
                    g, self._ns(PartitionSpec(DP_AXIS))))
            else:
                out.append(g)
        return out

    def _build(self, training: bool):
        step_fn = self._make_step_fn()
        p_specs = tuple(self._ns(self._param_spec(p)) for p in self._params)
        b_specs = tuple(self._ns(PartitionSpec())
                        for _ in self._bnames)
        state = self._opt_state or self.optimizer.functional_init(
            [p.data for p in self._params])
        s_specs = [
            {k: self._ns(self._slot_spec(p, v.shape))
             for k, v in slots.items()}
            for p, slots in zip(self._params, state)]
        scalar = self._ns(PartitionSpec())
        aux_specs = {k: scalar for k in self._aux_keys()}
        batch_spec = self._ns(PartitionSpec(DP_AXIS))
        jitted = jax.jit(
            step_fn,
            in_shardings=(p_specs, b_specs, s_specs, aux_specs, scalar,
                          None, None),
            out_shardings=(scalar, p_specs, b_specs, s_specs, aux_specs),
            donate_argnums=(0, 1, 2, 3) if self._donate else (),
        )
        return _ShardBatch(jitted, batch_spec, self.n_inputs)


class _ShardBatch:
    """Callable shim: places batch arrays with dp sharding, then calls the
    jitted step (jit infers shardings for key/inputs/labels from committed
    device placement)."""

    def __init__(self, jitted, batch_spec, n_inputs):
        self._jitted = jitted
        self._spec = batch_spec
        self.n_inputs = n_inputs

    def lower(self, *args):
        return self._jitted.lower(*args)

    def __call__(self, p_arr, b_arr, opt_state, aux, lr, inputs, labels):
        put = lambda a: jax.device_put(a, self._spec)
        inputs = tuple(put(a) for a in inputs)
        labels = tuple(put(a) for a in labels)
        return self._jitted(p_arr, b_arr, opt_state, aux, lr, inputs,
                            labels)
