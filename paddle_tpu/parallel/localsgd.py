"""LocalSGD / AdaptiveLocalSGD — reduced-frequency parameter averaging.

Reference semantics (fleet/meta_optimizers/localsgd_optimizer.py):
- every worker runs the inner optimizer on its own gradient (no per-step
  grad allreduce);
- params are averaged across workers every step until ``step >
  begin_step`` (localsgd_optimizer.py:190 cond), then every ``k_steps``;
- AdaptiveLocalSGD recomputes ``k`` at each sync (:417-433):
  ``k = clip(ceil(sqrt(lr_0 * loss / (lr * loss_0) * init_k)), 1, 16)``
  where ``loss_0``/``lr_0`` are captured during the warm-up syncs;
- inner-optimizer slots (momentum) stay local — the reference only
  allreduces the params (snapshot-delta choreography :150-185).

TPU-native redesign: instead of N per-worker programs + conditional
allreduce ops, every param/buffer/slot carries a leading *replica* axis of
size dp sharded over the mesh's 'dp' axis, and the local update is
``jax.vmap`` over that axis — XLA keeps each replica's compute on its own
devices because dim 0 is dp-sharded, so no collective runs on non-sync
steps.  The periodic sync is a mean over dim 0 (GSPMD lowers it to one
fused all-reduce) selected by an in-graph predicate; the adaptive-k state
machine is a handful of scalar ops in the same compiled step, so sync
steps and local steps are the SAME executable (no host-side branching,
zero recompiles).
"""
from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp

from ..core import autograd, rng
from ..core.tensor import Tensor
from ..distributed.mesh import DP_AXIS, ensure_mesh
from ..distributed.strategy import DistributedStrategy
from ..jit.bind import bind, buffer_arrays, buffer_names, param_list
from jax.sharding import NamedSharding, PartitionSpec

_as_arr = lambda x: x.data if isinstance(x, Tensor) else jnp.asarray(x)


class LocalSGDTrainStep:
    """Compiled LocalSGD step: per-replica local updates + periodic mean.

    ``adaptive=True`` enables the AdaptiveLocalSGD k schedule.  Model
    params are NOT refreshed per step (each dp shard owns a diverged
    replica): call :meth:`sync_to_model` before reading weights out of the
    model (``model.state_dict()``, checkpointing, eval).
    ``fleet.save_persistables``/``save_inference_model`` do this for the
    step they created; direct ``model.state_dict()`` reads are stale until
    you sync."""

    K_MIN, K_MAX = 1, 16   # localsgd_optimizer.py:425-428
    scaler = None          # optimizer checkpoint protocol (no fp16 scaler)

    def __init__(self, model, loss_fn, optimizer, mesh=None,
                 strategy: Optional[DistributedStrategy] = None,
                 n_inputs: int = 1, adaptive: Optional[bool] = None):
        strategy = strategy or DistributedStrategy()
        from ..distributed.strategy import validate_toggles
        validate_toggles(strategy)
        # composable toggles are wired below (amp bf16 autocast,
        # recompute); everything else must be loud, not silently dropped
        unsupported = [t for t in ("sharding", "gradient_merge",
                                   "fp16_allreduce", "tensor_parallel",
                                   "pipeline", "sequence_parallel")
                       if getattr(strategy, t)]
        if unsupported:
            raise NotImplementedError(
                f"strategy.localsgd does not compose with {unsupported}: "
                f"LocalSGD keeps full per-replica params/slots on each dp "
                f"shard (the reference restricts it similarly, "
                f"localsgd_optimizer.py:27-31 black_list).  Drop the "
                f"toggle(s) or use plain SpmdTrainStep.")
        if strategy.amp and strategy.amp_configs.dtype == "float16":
            raise NotImplementedError(
                "localsgd + float16 dynamic loss scaling is not wired; "
                "use amp_configs.dtype='bfloat16' (no scaler needed).")
        self.model = model
        self.loss_fn = loss_fn
        self.optimizer = optimizer
        self.n_inputs = n_inputs
        self.mesh = mesh or ensure_mesh()
        self.strategy = strategy
        self._amp = bool(strategy.amp)
        self._recompute = bool(strategy.recompute)
        if adaptive is None:
            adaptive = strategy.adaptive_localsgd
        self.adaptive = bool(adaptive)
        if self.adaptive:
            cfg = strategy.adaptive_localsgd_configs
            self._k0 = int(cfg.init_k_steps)
            self._begin = int(cfg.begin_step)
        else:
            cfg = strategy.localsgd_configs
            self._k0 = int(cfg.k_steps)
            self._begin = int(cfg.begin_step)
        others = [a for a, s in self.mesh.shape.items()
                  if a != DP_AXIS and s > 1]
        if others:
            raise NotImplementedError(
                f"LocalSGD averages full param replicas over 'dp'; model "
                f"shardings over {others} are not composable with it "
                f"(the reference restricts it to collective DP too, "
                f"localsgd_optimizer.py:34-47).")
        self.dp = self.mesh.shape.get(DP_AXIS, 1)
        self._params = param_list(model)
        self._bnames = buffer_names(model)
        self._p_rep = None
        self._b_rep = None
        self._s_rep = None
        self._aux = None
        self._compiled = None
        self._lr_value = None
        self._lr_device = None
        optimizer._bound_train_step = self

    # -- sharded replica store --------------------------------------------
    def _rep_sharding(self):
        return NamedSharding(self.mesh, PartitionSpec(DP_AXIS))

    def _replicate(self, arr):
        rep = jnp.broadcast_to(arr[None], (self.dp,) + arr.shape)
        return jax.device_put(rep, self._rep_sharding())

    def _init_state(self):
        import weakref
        for p in self._params:
            p._param_owner_step = weakref.ref(self)  # state_dict auto-sync
        self._p_rep = tuple(self._replicate(p.data) for p in self._params)
        self._b_rep = tuple(self._replicate(a)
                            for a in buffer_arrays(self.model))
        base = self.optimizer.functional_init(
            [p.data for p in self._params])
        self._s_rep = jax.tree.map(self._replicate, base)
        # seed the applied-step counter from the optimizer's host count so
        # a set_state_dict before (re)start is honored (TrainStep parity)
        start = int(self.optimizer._step_count)
        self._aux = {
            "step": jnp.asarray(start, jnp.int32),
            "draw": jnp.asarray(0, jnp.int32),
            "last": jnp.asarray(start, jnp.int32),
            "k": jnp.asarray(self._k0, jnp.int32),
            "key": jax.random.key_data(rng.next_key()),
            # 0.0 = "not captured yet" sentinel: the first sync captures
            # loss0/lr0 even when begin_step=0 (no warm-up syncs happen)
            "loss0": jnp.asarray(0.0, jnp.float32),
            "lr0": jnp.asarray(0.0, jnp.float32),
        }

    # -- optimizer checkpoint protocol ------------------------------------
    # optimizer.state_dict()/set_state_dict() talk to the bound step via
    # `_scaler_state` (the device-resident aux carry): expose ours under
    # that name, and let set_state_dict reset it so the next call reseeds
    # from the loaded host counter (optimizer.py:_effective_step).
    @property
    def _scaler_state(self):
        return self._aux

    @_scaler_state.setter
    def _scaler_state(self, value):
        if value is None:
            # also drop the replica store: loaded weights in p.data must
            # win over the stale diverged replicas
            self._p_rep = self._b_rep = self._s_rep = None
        self._aux = value

    # -- the compiled step -------------------------------------------------
    def _make_step_fn(self):
        model, loss_fn, opt = self.model, self.loss_fn, self.optimizer
        params_meta = self._params
        bnames = self._bnames
        dp, begin, k0 = self.dp, self._begin, self._k0
        adaptive = self.adaptive

        def step_fn(p_rep, b_rep, s_rep, aux, lr, inputs, labels):
            key = jax.random.wrap_key_data(aux["key"])
            attempt = aux["step"] + 1
            draw = aux["draw"] + 1
            step_f = attempt.astype(jnp.float32)
            base_key = jax.random.fold_in(key, draw)

            mb_in = tuple(a.reshape(dp, a.shape[0] // dp, *a.shape[1:])
                          for a in inputs)
            mb_lab = tuple(a.reshape(dp, a.shape[0] // dp, *a.shape[1:])
                           for a in labels)
            rep_keys = jax.vmap(
                lambda r: jax.random.key_data(
                    jax.random.fold_in(base_key, r)))(jnp.arange(dp))

            import contextlib
            use_amp, use_remat = self._amp, self._recompute
            amp_dtype = (self.strategy.amp_configs.dtype if use_amp
                         else "bfloat16")

            def amp_scope():
                if not use_amp:
                    return contextlib.nullcontext()
                from ..amp import auto_cast
                return auto_cast(level="O1", dtype=amp_dtype)

            def local(p_l, b_l, s_l, ins, labs, kd):
                k_r = jax.random.wrap_key_data(kd)

                def loss_of(pl):
                    with autograd.no_grad(), rng.seed_scope(k_r), \
                            amp_scope():
                        with bind(model, list(pl), list(b_l)) as res:
                            out = model(*[Tensor(a) for a in ins])
                            lab = [Tensor(a) for a in labs]
                            loss_t = loss_fn(out, *lab)
                        new_b = tuple(
                            _as_arr(res.new_buffers.get(n, old))
                            for n, old in zip(bnames, b_l))
                    return loss_t.data, new_b

                if use_remat:
                    loss_of = jax.checkpoint(loss_of)
                (loss, new_b), grads = jax.value_and_grad(
                    loss_of, has_aux=True)(list(p_l))
                new_p, new_s = opt.functional_update(
                    list(p_l), grads, s_l, lr, step_f,
                    params_meta=params_meta)
                return loss, tuple(new_p), new_b, new_s

            losses, new_p, new_b, new_s = jax.vmap(local)(
                p_rep, b_rep, s_rep, mb_in, mb_lab, rep_keys)
            loss_avg = jnp.mean(losses)

            # sync predicate (localsgd_optimizer.py:188-190): every step
            # while attempt <= begin_step, then every k steps after
            warm = attempt <= begin
            due = (attempt - aux["last"]) >= aux["k"]
            sync = jnp.logical_or(warm, due)
            mean0 = lambda a: jnp.broadcast_to(
                jnp.mean(a.astype(jnp.float32), axis=0, keepdims=True),
                a.shape).astype(a.dtype)
            # lax.cond, not where: the cross-replica mean lowers to an
            # all-reduce over 'dp', which must only execute on sync steps
            # (otherwise LocalSGD's bandwidth saving evaporates)
            new_p, new_b = jax.lax.cond(
                sync,
                lambda t: (jax.tree.map(mean0, t[0]),
                           jax.tree.map(mean0, t[1])),
                lambda t: t,
                (new_p, new_b))

            new_aux = dict(aux)
            new_aux.update(step=attempt, draw=draw,
                           last=jnp.where(sync, attempt, aux["last"]))
            if adaptive:
                # capture loss_0/lr_0 during warm-up syncs (:354-355) —
                # or at the first sync ever if begin_step=0 skipped warm-up
                captured = aux["loss0"] > 0
                grab = jnp.logical_and(sync, jnp.logical_or(
                    warm, jnp.logical_not(captured)))
                loss0 = jnp.where(grab, loss_avg, aux["loss0"])
                lr0 = jnp.where(grab, lr, aux["lr0"])
                # re-derive k at post-warm-up syncs (:417-433), only once
                # a baseline exists
                k_next = jnp.ceil(jnp.sqrt(
                    lr0 * loss_avg / (lr * loss0 + 1e-12) * k0))
                k_next = jnp.clip(k_next.astype(jnp.int32),
                                  self.K_MIN, self.K_MAX)
                adapt = jnp.logical_and(
                    jnp.logical_and(sync, ~warm),
                    jnp.logical_and(captured, ~grab))
                new_aux.update(
                    loss0=loss0, lr0=lr0,
                    k=jnp.where(adapt, k_next, aux["k"]))
            return loss_avg, new_p, new_b, new_s, new_aux

        return step_fn

    def _build(self):
        rep = self._rep_sharding()
        scalar = NamedSharding(self.mesh, PartitionSpec())
        p_specs = tuple(rep for _ in self._p_rep)
        b_specs = tuple(rep for _ in self._b_rep)
        s_specs = jax.tree.map(lambda _: rep, self._s_rep)
        aux_specs = {k: scalar for k in self._aux}
        batch = NamedSharding(self.mesh, PartitionSpec(DP_AXIS))
        jitted = jax.jit(
            self._make_step_fn(),
            in_shardings=(p_specs, b_specs, s_specs, aux_specs, scalar,
                          None, None),
            out_shardings=(scalar, p_specs, b_specs, s_specs, aux_specs),
            donate_argnums=(0, 1, 2, 3))

        def run(p, b, s, aux, lr, inputs, labels):
            put = lambda a: jax.device_put(a, batch)
            return jitted(p, b, s, aux, lr,
                          tuple(put(a) for a in inputs),
                          tuple(put(a) for a in labels))

        return run

    def __call__(self, *batch):
        inputs = tuple(_as_arr(b) for b in batch[:self.n_inputs])
        labels = tuple(_as_arr(b) for b in batch[self.n_inputs:])
        if inputs[0].shape[0] % self.dp:
            raise ValueError(
                f"batch size {inputs[0].shape[0]} not divisible by "
                f"dp={self.dp}")
        if self._p_rep is None:
            self._init_state()
        if self._compiled is None:
            self._compiled = self._build()
        self.optimizer._step_count += 1
        lr_val = float(self.optimizer.get_lr())
        if lr_val != self._lr_value:
            self._lr_value = lr_val
            self._lr_device = jnp.asarray(lr_val, jnp.float32)
        loss, self._p_rep, self._b_rep, self._s_rep, self._aux = (
            self._compiled(self._p_rep, self._b_rep, self._s_rep,
                           self._aux, self._lr_device, inputs, labels))
        self._model_dirty = True
        return Tensor(loss)

    @property
    def k_steps(self) -> int:
        """Current (possibly adapted) sync interval — host sync."""
        return int(self._aux["k"]) if self._aux is not None else self._k0

    def sync_params(self):
        """Unified step contract (TrainStep.sync_params): materialise the
        authoritative weights into the model."""
        self.sync_to_model()

    def sync_to_model(self):
        """Average the dp replicas back into model params/buffers."""
        if self._p_rep is None or not getattr(self, "_model_dirty", False):
            return
        self._model_dirty = False
        for p, rep in zip(self._params, self._p_rep):
            p.data = jnp.mean(rep.astype(jnp.float32), axis=0).astype(
                rep.dtype)
        buffers = dict(self.model.named_buffers())
        for n, rep in zip(self._bnames, self._b_rep):
            buffers[n].data = jnp.mean(
                rep.astype(jnp.float32), axis=0).astype(rep.dtype)
