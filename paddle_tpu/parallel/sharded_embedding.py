"""ShardedEmbedding — the parameter-server replacement for huge tables.

Reference analog: the PSCore sparse table stack
(paddle/fluid/distributed/table/common_sparse_table.cc,
memory_sparse_table.cc) serving embeddings too large for one device, and
``paddle.static.nn.sparse_embedding``.  TPU-native re-architecture
(SURVEY §7): instead of RPC lookups against parameter servers, the table's
ROWS are sharded over a mesh axis — each chip holds ``vocab / n`` rows in
its own HBM — and the lookup is a shard-local gather + ``psum``, riding
ICI instead of DCN.  Optionally the table (and its optimizer slots, which
inherit the placement) lives in host memory (``offload='pinned_host'``),
the analog of the reference's SSD/heterogeneous PS tiers.

Row-sharded lookup (runs inside the SPMD train step, mesh axis ``axis``):
each shard gathers the rows it owns (out-of-shard ids clamp to row 0 and
mask to zero) and a psum assembles the full result — the collective the
reference implements as prefetch + RPC (distributed/parameter_prefetch.cc).

Gradient: the psum-of-masked-gathers formulation makes the weight's
gradient a scatter-add of ONLY the touched rows on the owning shard —
SelectedRows semantics realized by sharding (eager single-chip code gets
real SelectedRows grads via ``sparse=True`` embedding + lazy optimizers).
"""
from __future__ import annotations

import warnings

import jax
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec

from ..distributed.mesh import get_mesh
from ..nn import functional as F
from ..nn import initializer as I
from ..nn.layer_base import Layer
from .tp_layers import set_placement


def _row_sharded_lookup(w, ids, mesh, axis):
    """Shard-local gather + psum over ``axis``; differentiable (shard_map
    has full AD support), grads land as shard-local scatter-adds."""
    from ..core.jax_compat import shard_map

    n = mesh.shape[axis]
    rows_per = w.shape[0] // n

    def f(w_shard, ids_rep):
        idx = jax.lax.axis_index(axis)
        local = ids_rep - idx * rows_per
        ok = (local >= 0) & (local < rows_per)
        safe = jnp.clip(local, 0, rows_per - 1)
        out = jnp.take(w_shard, safe, axis=0)
        out = out * ok[..., None].astype(out.dtype)
        return jax.lax.psum(out, axis)

    return shard_map(
        f, mesh=mesh,
        in_specs=(PartitionSpec(axis), PartitionSpec()),
        out_specs=PartitionSpec())(w, ids)


class ShardedEmbedding(Layer):
    """Embedding whose rows are sharded over a mesh axis.

    Args:
        num_embeddings / embedding_dim: table shape.
        axis: mesh axis to shard rows over (default 'dp': capacity
            sharding like ZeRO-3, every data rank owns vocab/n rows).
        offload: None or 'pinned_host' — keep the table (and, via
            placement inheritance, its optimizer slots) in host memory.
        sparse: eager mode uses SelectedRows grads (sparse=True lookup).
    """

    def __init__(self, num_embeddings: int, embedding_dim: int,
                 axis: str = "dp", offload=None, sparse: bool = True,
                 weight_attr=None, name=None):
        super().__init__()
        self._num_embeddings = num_embeddings
        self._embedding_dim = embedding_dim
        self._axis = axis
        self._sparse = sparse
        mesh = get_mesh()
        if (mesh is not None and axis in mesh.shape
                and num_embeddings % mesh.shape[axis] != 0):
            raise ValueError(
                f"num_embeddings ({num_embeddings}) must be divisible by "
                f"mesh axis '{axis}' size ({mesh.shape[axis]}) — otherwise "
                f"the table would silently replicate onto every chip; pad "
                f"the vocab to a multiple")
        self.weight = self.create_parameter(
            [num_embeddings, embedding_dim], attr=weight_attr,
            default_initializer=I.Normal(0.0, 0.02))
        set_placement(self.weight, axis)
        if offload:
            self._try_offload(offload)

    def _try_offload(self, kind: str):
        """Host-memory placement (reference analog: the PS SSD tier /
        heterogeneous PS).  Needs a TPU runtime with memory_kinds; on
        other backends the table stays in device memory."""
        try:
            mesh = get_mesh()
            if mesh is not None and self._axis in mesh.shape:
                s = NamedSharding(mesh, PartitionSpec(self._axis),
                                  memory_kind=kind)
            else:
                dev = jax.devices()[0]
                s = jax.sharding.SingleDeviceSharding(dev, memory_kind=kind)
            self.weight.data = jax.device_put(self.weight.data, s)
        except Exception as e:  # pragma: no cover - backend-dependent
            warnings.warn(f"host offload unavailable on this backend "
                          f"({type(e).__name__}: {e}); table stays in "
                          f"device memory")

    def forward(self, ids):
        mesh = get_mesh()
        arr = ids.data if hasattr(ids, "data") else ids
        traced = isinstance(arr, jax.core.Tracer)
        if (mesh is not None and self._axis in mesh.shape
                and mesh.shape[self._axis] > 1
                and self._num_embeddings % mesh.shape[self._axis] == 0
                and traced):
            from ..core.dispatch import apply
            return apply(
                lambda w, i: _row_sharded_lookup(w, i, mesh, self._axis),
                self.weight, ids, op_name="sharded_embedding")
        return F.embedding(ids, self.weight, sparse=self._sparse)

    def extra_repr(self):
        return (f"{self._num_embeddings}, {self._embedding_dim}, "
                f"axis={self._axis!r}")
