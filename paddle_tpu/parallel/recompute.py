"""Activation recompute (gradient checkpointing).

Reference: RecomputeOptimizer (recompute_optimizer.py:18) →
``_append_backward_ops_with_checkpoints_`` (fluid/backward.py:725) which
re-emits forward ops in the backward program.

TPU-native: ``jax.checkpoint`` (rematerialisation) on the wrapped segment —
XLA re-runs the segment in the backward pass, trading FLOPs for HBM
exactly like the reference's checkpoint mechanism."""
from __future__ import annotations

import jax

from ..core import autograd, dispatch
from ..core.tensor import Tensor
from ..jit.bind import bind, param_list


def recompute(function, *args, **kwargs):
    """paddle.distributed.fleet.utils.recompute parity.

    ``function`` may be a Layer or a Tensor-level callable; its forward is
    evaluated under jax.checkpoint so residuals are rematerialised in the
    backward sweep."""
    from ..nn.layer_base import Layer

    preserve = kwargs.pop("preserve_rng_state", True)
    if isinstance(function, Layer):
        layer = function
        fn = layer.forward
        params = param_list(layer)
    else:
        layer = getattr(function, "__self__", None)
        layer = layer if isinstance(layer, Layer) else None
        fn = function
        params = param_list(layer) if layer else []

    tensors = [a for a in args if isinstance(a, Tensor)]
    statics = [a for a in args if not isinstance(a, Tensor)]
    n_p = len(params)

    @jax.checkpoint
    def pure_fn(*arrays):
        p_arr = list(arrays[:n_p])
        in_arr = arrays[n_p:]
        it = iter(in_arr)
        rebuilt = [Tensor(next(it)) if isinstance(a, Tensor) else a
                   for a in args]
        with autograd.no_grad():
            if layer is not None:
                with bind(layer, p_arr):
                    out = fn(*rebuilt, **kwargs)
            else:
                out = fn(*rebuilt, **kwargs)
        return jax.tree.map(
            lambda t: t.data if isinstance(t, Tensor) else t, out,
            is_leaf=lambda x: isinstance(x, Tensor))

    return dispatch.apply(pure_fn, *params, *tensors, op_name="recompute")


def recompute_sequential(ctx, functions, *args):
    """Sequentially recompute a list of layers (paddle incubate parity)."""
    out = args
    for f in functions:
        out = recompute(f, *(out if isinstance(out, tuple) else (out,)))
    return out
