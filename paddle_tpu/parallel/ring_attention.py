"""Ring attention — sequence/context parallelism over an ICI ring.

The reference has NO long-context support (SURVEY §5.7: verified absent);
this is the capability-parity-plus item the TPU build adds natively.

Design (blockwise ring attention): the sequence is sharded over the 'sp'
mesh axis.  Each device holds its Q block permanently and circulates K/V
blocks around the ring with ``lax.ppermute`` (one hop per step, overlapping
the next hop's transfer with the current block's attention math).  Partial
attention results merge with the numerically-stable online-softmax
(log-sum-exp) rule, so the result is EXACTLY standard attention on the
full sequence.

Causal masking uses the *block* offset of the K/V shard currently held, so
each device does the same work pattern (no load imbalance beyond the mask).
"""
from __future__ import annotations

import math
from typing import Optional

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec
from ..core.jax_compat import axis_size as _axis_size
from ..core.jax_compat import shard_map

from ..core.dispatch import apply
from ..core.tensor import Tensor
from ..distributed.mesh import SP_AXIS, ensure_mesh


def _block_attn(q, k, v, scale, mask):
    """One Q-block × K-block attention with running-softmax stats.

    q: [B, Lq, H, D], k/v: [B, Lk, H, D]; returns (out_unnorm, lse, m) where
    out_unnorm = exp(s - m) @ v, m = rowmax, lse = log sum exp(s - m)."""
    s = jnp.einsum("blhd,bshd->bhls", q, k) * scale
    if mask is not None:
        s = jnp.where(mask, s, -jnp.inf)
    m = jnp.max(s, axis=-1, keepdims=True)
    m_safe = jnp.where(jnp.isfinite(m), m, 0.0)
    p = jnp.exp(s - m_safe)
    p = jnp.where(jnp.isfinite(s), p, 0.0)
    l = jnp.sum(p, axis=-1, keepdims=True)
    o = jnp.einsum("bhls,bshd->blhd", p, v)
    return o, l, m_safe, m


def ring_attention_per_device(q, k, v, axis_name: str, is_causal: bool,
                              scale: Optional[float] = None):
    """Per-device ring attention body (call inside shard_map).

    q/k/v: local shards [B, L_local, H, D].  Returns [B, L_local, H, D]."""
    B, Lq, H, D = q.shape
    scale = scale if scale is not None else 1.0 / math.sqrt(D)
    S = _axis_size(axis_name)
    my = jax.lax.axis_index(axis_name)
    perm = [(i, (i + 1) % S) for i in range(S)]

    q_pos = my * Lq + jnp.arange(Lq)           # global positions of my Q

    def step(carry, r):
        k_blk, v_blk, o, l, m = carry
        src = (my - r) % S                      # whose K/V I hold at round r
        if is_causal:
            k_pos = src * Lq + jnp.arange(Lq)
            mask = (q_pos[:, None] >= k_pos[None, :])[None, None]
        else:
            mask = None
        o_b, l_b, m_safe_b, m_b = _block_attn(q, k_blk, v_blk, scale, mask)
        # online-softmax merge of (o, l, m) with block stats
        new_m = jnp.maximum(m, m_b)
        new_m_safe = jnp.where(jnp.isfinite(new_m), new_m, 0.0)
        alpha = jnp.exp(jnp.where(jnp.isfinite(m), m - new_m_safe, -jnp.inf))
        alpha = jnp.where(jnp.isfinite(m), alpha, 0.0)
        beta = jnp.exp(jnp.where(jnp.isfinite(m_b), m_safe_b - new_m_safe,
                                 -jnp.inf))
        beta = jnp.where(jnp.isfinite(m_b), beta, 0.0)
        # stats are [B, H, Lq, 1]; o is [B, Lq, H, D] → swap H/Lq axes
        o = (o * jnp.swapaxes(alpha, 1, 2)
             + o_b * jnp.swapaxes(beta, 1, 2))
        l = l * alpha + l_b * beta
        # rotate K/V to the next device (overlaps with next block's math)
        k_nxt = jax.lax.ppermute(k_blk, axis_name, perm)
        v_nxt = jax.lax.ppermute(v_blk, axis_name, perm)
        return (k_nxt, v_nxt, o, l, new_m), None

    o0 = jnp.zeros((B, Lq, H, D), q.dtype)
    l0 = jnp.zeros((B, H, Lq, 1), q.dtype)
    m0 = jnp.full((B, H, Lq, 1), -jnp.inf, q.dtype)
    (_, _, o, l, m), _ = jax.lax.scan(
        step, (k, v, o0, l0, m0), jnp.arange(S))
    denom = jnp.swapaxes(jnp.maximum(l, 1e-20), 1, 2)  # → [B, Lq, H, 1]
    return o / denom


def _flash_eligible(q) -> bool:
    from ..core.flags import get_flag
    from ..ops.pallas.flash_attention import flash_attention_supported
    if not get_flag("use_pallas_kernels"):
        return False
    shape = tuple(q.shape)  # the per-device local shard shape
    return flash_attention_supported(shape, shape, q.dtype)


def ring_attention_per_device_flash(q, k, v, axis_name: str, is_causal: bool,
                                    scale: Optional[float] = None):
    """Ring attention whose per-block math is the Pallas flash kernel.

    Each round attends my Q block against the circulating K/V block with
    the fused kernel (normalized output + logsumexp), then merges rounds
    with logsumexp weights.  Causality rides the kernel's *global position
    offsets*: q_off = my·L, k_off = src·L — rounds holding earlier shards
    are fully visible, later shards fully masked, the diagonal causal,
    all with one kernel (differentiable through the scan)."""
    from ..ops.pallas.flash_attention import flash_attention_block
    B, Lq, H, D = q.shape
    scale = scale if scale is not None else 1.0 / math.sqrt(D)
    S = _axis_size(axis_name)
    my = jax.lax.axis_index(axis_name)
    perm = [(i, (i + 1) % S) for i in range(S)]
    qt = jnp.swapaxes(q, 1, 2)                 # [B, H, L, D]
    q_off = (my * Lq).astype(jnp.float32).reshape(1, 1)

    def step(carry, r):
        k_blk, v_blk, o, lse = carry
        src = (my - r) % S
        if is_causal:
            k_off = (src * Lq).astype(jnp.float32).reshape(1, 1)
        else:
            # every position visible: put K "infinitely in the past"
            k_off = jnp.full((1, 1), -1e9, jnp.float32)
        o_b, lse_b = flash_attention_block(
            qt, jnp.swapaxes(k_blk, 1, 2), jnp.swapaxes(v_blk, 1, 2),
            q_off, k_off, scale)
        lse_new = jnp.logaddexp(lse, lse_b)               # [B, H, Lq]
        finite = jnp.isfinite(lse_new)
        w_old = jnp.where(finite, jnp.exp(lse - lse_new), 0.0)
        w_new = jnp.where(finite, jnp.exp(lse_b - lse_new), 0.0)
        o = o * w_old[..., None] + o_b.astype(jnp.float32) * w_new[..., None]
        k_nxt = jax.lax.ppermute(k_blk, axis_name, perm)
        v_nxt = jax.lax.ppermute(v_blk, axis_name, perm)
        return (k_nxt, v_nxt, o, lse_new), None

    o0 = jnp.zeros((B, H, Lq, D), jnp.float32)
    lse0 = jnp.full((B, H, Lq), -jnp.inf, jnp.float32)
    (_, _, o, _), _ = jax.lax.scan(step, (k, v, o0, lse0), jnp.arange(S))
    return jnp.swapaxes(o, 1, 2).astype(q.dtype)


def ring_attention(q, k, v, is_causal=True, mesh=None,
                   axis_name: str = SP_AXIS):
    """Tensor-level ring attention: q/k/v [B, L, H, D] with L sharded over
    the 'sp' axis.  Exact attention over the full sequence.  Per-block math
    uses the Pallas flash kernel when eligible (long local blocks)."""
    mesh = mesh or ensure_mesh()

    def _ra(qa, ka, va):
        n = mesh.shape[axis_name]
        local = qa.shape[1] // n
        use_flash = _flash_eligible(
            jax.ShapeDtypeStruct((qa.shape[0], local, qa.shape[2],
                                  qa.shape[3]), qa.dtype))
        body = (ring_attention_per_device_flash if use_flash
                else ring_attention_per_device)
        spec = PartitionSpec(None, axis_name, None, None)
        fn = shard_map(
            lambda a, b, c: body(a, b, c, axis_name, is_causal),
            mesh=mesh, in_specs=(spec, spec, spec), out_specs=spec,
            check_vma=False)
        return fn(qa, ka, va)

    return apply(_ra, q, k, v, op_name="ring_attention")


def reference_attention(q, k, v, is_causal=True):
    """Single-device oracle for tests."""
    def _attn(qa, ka, va):
        D = qa.shape[-1]
        s = jnp.einsum("blhd,bshd->bhls", qa, ka) / math.sqrt(D)
        if is_causal:
            L, Sk = qa.shape[1], ka.shape[1]
            mask = jnp.tril(jnp.ones((L, Sk), bool))
            s = jnp.where(mask[None, None], s, -jnp.inf)
        w = jax.nn.softmax(s, axis=-1)
        return jnp.einsum("bhls,bshd->blhd", w, va)
    return apply(_attn, q, k, v, op_name="reference_attention")
