"""Pipeline parallelism.

Reference: PipelineTrainer + SectionWorker (reference: trainer.h:328,
section_worker.cc:115-165 — F-then-B and 1F1B microbatch schedules;
program splitting in fluid/optimizer.py:3954 `_split_program`; inter-stage
tensors via send_v2/recv_v2 collective ops).

TPU-native design: stages are SPMD over a 'pp' mesh axis.  Each device
executes the SAME stage function with ITS stage's parameters (stage params
stacked on a leading axis and sharded over 'pp'); activations move between
neighbouring stages with ``lax.ppermute`` (the send_v2/recv_v2 analog, but
compiler-scheduled over ICI).  The fill-drain schedule is a ``lax.scan``
over M + S - 1 ticks, so forward AND backward pipeline in one compiled
program — differentiating the scan yields the reverse schedule
automatically (the 1F1B interleaving the reference hand-codes in
section_worker.cc:128-165 is here XLA's latency-hiding scheduler's job).

Requirement (same as the reference's section programs): all stages must be
shape-uniform — activation shape in == activation shape out (true for
transformer blocks).
"""
from __future__ import annotations

from typing import Callable, List, Sequence

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec
from jax import shard_map

from ..core import autograd
from ..core.tensor import Tensor
from ..distributed.mesh import PP_AXIS, ensure_mesh
from ..jit.bind import bind, param_list
from ..nn.layer_base import Layer


class PipelineStage(Layer):
    """Marker container for one stage (uniform structure across stages)."""

    def __init__(self, block: Layer):
        super().__init__()
        self.block = block

    def forward(self, x):
        return self.block(x)


class Pipeline(Layer):
    """A sequence of shape-uniform stages.

    Eager/single-chip: runs stages sequentially (reference F-then-B
    degenerate case).  Use :func:`pipelined_fn` to obtain the SPMD
    microbatched execution over the 'pp' mesh axis.
    """

    def __init__(self, stages: Sequence[Layer], num_microbatches: int = 1):
        super().__init__()
        from ..nn.layer.container import LayerList
        self.stages = LayerList(list(stages))
        self.num_microbatches = num_microbatches

    def forward(self, x):
        for s in self.stages:
            x = s(x)
        return x


def stack_stage_params(stages: Sequence[Layer]):
    """Stack per-stage parameter arrays along a new leading 'stage' axis.

    All stages must have identical parameter structure (the reference makes
    the same uniformity assumption when splitting programs into sections).
    Returns (stacked_arrays: list, n_params_per_stage)."""
    per_stage = [[p.data for p in param_list(s)] for s in stages]
    n = len(per_stage[0])
    for ps in per_stage:
        assert len(ps) == n, "pipeline stages must be structurally uniform"
    stacked = [jnp.stack([ps[i] for ps in per_stage], axis=0)
               for i in range(n)]
    return stacked, n


def pipelined_fn(stage_layer: Layer, n_stages: int, num_microbatches: int,
                 mesh=None, pp_axis: str = PP_AXIS):
    """Build a pure function running `stage_layer` as an S-stage pipeline.

    Returns ``fn(stacked_params, x)`` where ``stacked_params`` are stage
    params stacked on axis 0 (shard over 'pp') and ``x`` is the full batch
    [B, ...]; B is split into ``num_microbatches``.  Output: [B, ...] after
    all S stages.
    """
    mesh = mesh or ensure_mesh()
    S = n_stages
    M = num_microbatches
    template = stage_layer
    n_params = len(param_list(template))

    def stage_apply(p_arrs, x):
        with autograd.no_grad():
            with bind(template, list(p_arrs)):
                out = template(Tensor(x))
        return out.data if isinstance(out, Tensor) else out

    def per_device(*args):
        stacked_local = args[:n_params]   # each [1, ...]: my stage's params
        x = args[n_params]                # full batch (replicated)
        my_params = [a[0] for a in stacked_local]
        idx = jax.lax.axis_index(pp_axis)
        mb = x.reshape(M, x.shape[0] // M, *x.shape[1:])
        act_shape = mb.shape[1:]
        T = M + S - 1

        def tick(carry, t):
            buf = carry
            # stage 0 ingests microbatch t (clamped); others take the ring
            take = jnp.clip(t, 0, M - 1)
            inject = jax.lax.dynamic_index_in_dim(mb, take, 0,
                                                  keepdims=False)
            inp = jnp.where(idx == 0, inject, buf)
            y = stage_apply(my_params, inp)
            # pass activation to the next stage (ring; last->first unused)
            nxt = jax.lax.ppermute(
                y, pp_axis, [(i, (i + 1) % S) for i in range(S)])
            # last stage's output for microbatch t-(S-1)
            out_t = jnp.where(idx == S - 1, y, jnp.zeros_like(y))
            return nxt, out_t

        _, outs = jax.lax.scan(tick, jnp.zeros(act_shape, x.dtype),
                               jnp.arange(T))
        # keep ticks S-1..T-1 (the M valid last-stage outputs), broadcast
        # from the last stage to all (psum over the zero-elsewhere buffer)
        valid = outs[S - 1:]
        valid = jax.lax.psum(valid, pp_axis)
        return valid.reshape(M * mb.shape[1], *act_shape[1:])

    in_specs = tuple([PartitionSpec(pp_axis)] * n_params
                     + [PartitionSpec()])
    out_specs = PartitionSpec()

    def fn(stacked_params, x):
        sm = shard_map(per_device, mesh=mesh, in_specs=in_specs,
                       out_specs=out_specs, check_vma=False)
        return sm(*stacked_params, x)

    return fn


def pipeline_train_fn(stage_layer: Layer, head_fn: Callable, n_stages: int,
                      num_microbatches: int, mesh=None,
                      pp_axis: str = PP_AXIS):
    """fn(stacked_params, head_params..., x, y) -> scalar loss, for use
    inside jax.value_and_grad.  ``head_fn(out_arrays, y)`` computes the
    loss from pipeline output (pure jnp)."""
    fwd = pipelined_fn(stage_layer, n_stages, num_microbatches, mesh,
                       pp_axis)

    def fn(stacked_params, x, y):
        out = fwd(stacked_params, x)
        return head_fn(out, y)

    return fn
