"""Pipeline parallelism.

Reference: PipelineTrainer + SectionWorker (reference: trainer.h:328,
section_worker.cc:115-165 — F-then-B and 1F1B microbatch schedules;
program splitting in fluid/optimizer.py:3954 `_split_program`; inter-stage
tensors via send_v2/recv_v2 collective ops).

TPU-native design: stages are SPMD over a 'pp' mesh axis.  Each device
executes the SAME stage function with ITS stage's parameters (stage params
stacked on a leading axis and sharded over 'pp'); activations move between
neighbouring stages with ``lax.ppermute`` (the send_v2/recv_v2 analog, but
compiler-scheduled over ICI).  The fill-drain schedule is a ``lax.scan``
over M + S - 1 ticks, so forward AND backward pipeline in one compiled
program — differentiating the scan yields the reverse schedule
automatically.

Activation-memory discipline (measured in tests/test_pipeline_memory.py):
differentiating the scan stores residuals for every tick, so per-device
backward memory is O(M) in the microbatch count — what each tick STORES is
the lever.  With ``remat=True`` (default) the stage/embed/head bodies are
``jax.checkpoint``-ed, so a tick stores only its carry (ONE microbatch
activation at the stage boundary) and recomputes layer internals in the
backward: O(M · |mb activation|) total, a factor-of-depth below the
unrematted scan's O(M · |all layer internals|).  This is the same
recompute-in-backward trade the reference's 1F1B + recompute combination
makes (section_worker.cc:128-165 interleaves backward to hold O(S)
in-flight microbatches; its per-microbatch store is the full section's
internals unless recompute is also on — for stages deeper than ~2 layers
and the usual M ≈ 2S, rematted-scan stores LESS than unrematted 1F1B).

Memory/layout discipline (round-3 redesign):
- the microbatch INPUT stream is sharded over 'pp' round-robin (microbatch
  t lives on rank t mod S); each tick the owner psum-broadcasts one
  microbatch to stage 0 — per-device input storage is O(batch/S), and the
  in-flight state is O(microbatch), never O(batch);
- the OUTPUT stream is collected the same way (each rank keeps the
  microbatches it owns), so outputs are born 'pp'-sharded instead of
  being psum-replicated;
- with a 'dp' axis in the mesh the batch dim of every stream is
  additionally dp-sharded: each data-parallel group runs its own pipeline
  (the reference's dp x pp grid, fleet meta-parallel);
- optionally non-uniform FIRST/LAST stages: an embedding applied at
  injection (stage 0) and a head applied at collection (stage S-1) — the
  reference's first/last section programs with their own params.

Requirement (same as the reference's middle sections): the S repeated
stages must be shape-uniform — activation shape in == out.
"""
from __future__ import annotations

from typing import Callable, List, Optional, Sequence

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec
from ..core.jax_compat import shard_map

from ..core import autograd
from ..core.tensor import Tensor
from ..distributed.mesh import DP_AXIS, PP_AXIS, ensure_mesh
from ..jit.bind import bind, param_list
from ..nn.layer_base import Layer


class PipelineStage(Layer):
    """Marker container for one stage (uniform structure across stages)."""

    def __init__(self, block: Layer):
        super().__init__()
        self.block = block

    def forward(self, x):
        return self.block(x)


class Pipeline(Layer):
    """A sequence of shape-uniform stages.

    Eager/single-chip: runs stages sequentially (reference F-then-B
    degenerate case).  Use :func:`pipelined_fn` to obtain the SPMD
    microbatched execution over the 'pp' mesh axis.
    """

    def __init__(self, stages: Sequence[Layer], num_microbatches: int = 1):
        super().__init__()
        from ..nn.layer.container import LayerList
        self.stages = LayerList(list(stages))
        self.num_microbatches = num_microbatches

    def forward(self, x):
        for s in self.stages:
            x = s(x)
        return x


def stack_stage_params(stages: Sequence[Layer]):
    """Stack per-stage parameter arrays along a new leading 'stage' axis.

    All stages must have identical parameter structure (the reference makes
    the same uniformity assumption when splitting programs into sections).
    Returns (stacked_arrays: list, n_params_per_stage)."""
    per_stage = [[p.data for p in param_list(s)] for s in stages]
    n = len(per_stage[0])
    for ps in per_stage:
        assert len(ps) == n, "pipeline stages must be structurally uniform"
    stacked = [jnp.stack([ps[i] for ps in per_stage], axis=0)
               for i in range(n)]
    return stacked, n


def _apply_layer(template: Layer, p_arrs, x):
    with autograd.no_grad():
        with bind(template, list(p_arrs)):
            out = template(Tensor(x))
    return out.data if isinstance(out, Tensor) else out


def pipelined_fn(stage_layer: Layer, n_stages: int, num_microbatches: int,
                 mesh=None, pp_axis: str = PP_AXIS,
                 dp_axis: Optional[str] = None,
                 embed_layer: Optional[Layer] = None,
                 head_layer: Optional[Layer] = None,
                 remat: bool = True):
    """Build a pure function running ``stage_layer`` as an S-stage pipeline.

    Returns ``fn(stacked_params, x[, embed_params][, head_params])``:
    ``stacked_params`` are stage params stacked on axis 0 (sharded over
    'pp'); ``x`` is the batch [B, ...] (dp-sharded when ``dp_axis`` is in
    the mesh), split into ``num_microbatches`` (a multiple of S).
    ``embed_layer``/``head_layer`` make the first/last stages non-uniform
    (their params ride replicated).  Output: [B, ...] after embed → S
    stages → head.

    ``remat=True`` checkpoints the stage/embed/head bodies so the scan's
    backward stores one microbatch boundary activation per tick instead of
    every layer internal (see module docstring; the reference's recompute
    + 1F1B combination, section_worker.cc + recompute_optimizer.py).
    """
    mesh = mesh or ensure_mesh()
    S = n_stages
    M = num_microbatches
    # round-robin stream layout [S, Q]; when S doesn't divide M the tail
    # slots are zero-padding that is never injected or collected
    Q = (M + S - 1) // S
    template = stage_layer
    n_params = len(param_list(template))
    n_embed = len(param_list(embed_layer)) if embed_layer else 0
    n_head = len(param_list(head_layer)) if head_layer else 0
    use_dp = dp_axis is not None and dp_axis in mesh.shape

    def per_device(*args):
        stage_local = args[:n_params]          # [1, ...] my stage's params
        my_stream = args[n_params][0]          # [Q, mb, ...] my microbatches
        rest = args[n_params + 1:]
        e_params = rest[:n_embed]
        h_params = rest[n_embed:n_embed + n_head]
        my_params = [a[0] for a in stage_local]
        idx = jax.lax.axis_index(pp_axis)
        T = M + S - 1

        def inject(t):
            """Owner rank (t mod S) broadcasts microbatch t to the ring;
            storage stays sharded, the wire carries ONE microbatch."""
            slot = t // S
            cand = jax.lax.dynamic_index_in_dim(my_stream, slot, 0,
                                                keepdims=False)
            mine = (idx == t % S)
            masked = jnp.where(mine, cand,
                               jnp.zeros_like(cand)
                               if jnp.issubdtype(cand.dtype, jnp.floating)
                               else cand * 0)
            return jax.lax.psum(masked, pp_axis)

        maybe_remat = jax.checkpoint if remat else (lambda f: f)
        stage_apply = maybe_remat(
            lambda p, a: _apply_layer(template, p, a))
        embed_apply = maybe_remat(
            lambda p, a: _apply_layer(embed_layer, p, a))
        head_apply = maybe_remat(
            lambda p, a: _apply_layer(head_layer, p, a))

        def first_stage_in(mb_in):
            if embed_layer is not None:
                return embed_apply(e_params, mb_in)
            return mb_in

        def last_stage_out(y):
            if head_layer is not None:
                return head_apply(h_params, y)
            return y

        # probe shapes (abstract): activation and collected-output element
        act0 = jax.eval_shape(
            lambda m: first_stage_in(m),
            jax.ShapeDtypeStruct(my_stream.shape[1:], my_stream.dtype))
        y0 = jax.eval_shape(
            lambda a: stage_apply(my_params, a), act0)
        out0 = jax.eval_shape(lambda a: last_stage_out(a), y0)

        def tick(carry, t):
            buf, out_stream = carry
            mb_in = inject(jnp.clip(t, 0, M - 1))
            cand_act = first_stage_in(mb_in)
            inp = jnp.where(idx == 0, cand_act, buf)
            y = stage_apply(my_params, inp)
            nxt = jax.lax.ppermute(
                y, pp_axis, [(i, (i + 1) % S) for i in range(S)])
            # collect: last stage's tick-t output is microbatch t-(S-1);
            # its owner rank stores it (stream stays 'pp'-sharded)
            tp = t - (S - 1)
            tq = jnp.clip(tp, 0, M - 1)
            h_out = last_stage_out(y)
            yb = jax.lax.psum(
                jnp.where(idx == S - 1, h_out, jnp.zeros_like(h_out)),
                pp_axis)
            write = (tp >= 0) & (idx == tq % S)
            updated = jax.lax.dynamic_update_index_in_dim(
                out_stream, yb, tq // S, 0)
            out_stream = jnp.where(write, updated, out_stream)
            return (nxt, out_stream), None

        buf0 = jnp.zeros(act0.shape, act0.dtype)
        outs0 = jnp.zeros((Q,) + out0.shape, out0.dtype)
        (_, out_stream), _ = jax.lax.scan(tick, (buf0, outs0),
                                          jnp.arange(T))
        return out_stream[None]                # [1, Q, mb, ...]

    stream_spec = PartitionSpec(pp_axis, None,
                                dp_axis if use_dp else None)
    in_specs = tuple([PartitionSpec(pp_axis)] * n_params
                     + [stream_spec]
                     + [PartitionSpec()] * (n_embed + n_head))
    out_specs = stream_spec

    def fn(stacked_params, x, embed_params=(), head_params=()):
        B = x.shape[0]
        assert B % M == 0, f"batch {B} not divisible by microbatches {M}"
        mb = B // M
        if Q * S != M:  # pad the stream's tail slots (never injected)
            pad = jnp.zeros((Q * S - M, mb, *x.shape[1:]), x.dtype)
            xp = jnp.concatenate(
                [x.reshape(M, mb, *x.shape[1:]), pad], axis=0)
        else:
            xp = x.reshape(M, mb, *x.shape[1:])
        # round-robin stream layout: stream[r, q] = microbatch q*S + r
        xs = xp.reshape(Q, S, mb, *x.shape[1:]).swapaxes(0, 1)
        sm = shard_map(per_device, mesh=mesh, in_specs=in_specs,
                       out_specs=out_specs, check_vma=False)
        out = sm(*stacked_params, xs, *embed_params, *head_params)
        # [S, Q, mb, ...] -> [B, ...] undoing the round-robin layout
        out = out.swapaxes(0, 1)               # [Q, S, mb, ...]
        out = out.reshape(Q * S * mb, *out.shape[3:])
        return out[:M * mb]

    return fn


def pipeline_train_fn(stage_layer: Layer, head_fn: Callable, n_stages: int,
                      num_microbatches: int, mesh=None,
                      pp_axis: str = PP_AXIS, dp_axis=None,
                      embed_layer=None, head_layer=None, remat: bool = True):
    """fn(stacked_params, x, y, ...) -> scalar loss, for use inside
    jax.value_and_grad.  ``head_fn(out_arrays, y)`` computes the loss from
    pipeline output (pure jnp)."""
    fwd = pipelined_fn(stage_layer, n_stages, num_microbatches, mesh,
                       pp_axis, dp_axis=dp_axis, embed_layer=embed_layer,
                       head_layer=head_layer, remat=remat)

    def fn(stacked_params, x, y, embed_params=(), head_params=()):
        out = fwd(stacked_params, x, embed_params, head_params)
        return head_fn(out, y)

    return fn
