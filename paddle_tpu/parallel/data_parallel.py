"""DataParallel wrapper.

Reference: ``paddle.DataParallel`` (fluid/dygraph/parallel.py:323) backed by
the C++ Reducer (imperative/reducer.cc: gradient bucketing + fused
allreduce) and NCCLParallelContext.

TPU-native: under SPMD compilation the gradient allreduce falls out of
GSPMD when the batch is sharded over 'dp' — there is nothing to bucket
(XLA fuses collectives itself).  This wrapper therefore:
- in eager mode: passthrough (single-controller sees the global batch)
- exposes ``scale_loss``/``apply_collective_grads`` as the documented
  no-ops (SURVEY §7 step 6: kept for API compatibility)
- carries comm_buffer_size/last_comm_buffer_size knobs for parity.
"""
from __future__ import annotations

from ..nn.layer_base import Layer


class DataParallel(Layer):
    def __init__(self, layers, strategy=None, comm_buffer_size=25,
                 last_comm_buffer_size=1, find_unused_parameters=False,
                 group=None):
        super().__init__()
        self._layers = layers
        self.add_sublayer("_layers", layers)
        self.find_unused_parameters = find_unused_parameters

    def forward(self, *inputs, **kwargs):
        return self._layers(*inputs, **kwargs)

    def scale_loss(self, loss):
        """No-op on TPU: loss scaling by nranks is folded into the mean over
        the dp-sharded batch (reference: parallel.py:572)."""
        return loss

    def apply_collective_grads(self):
        """No-op: grad psum is inserted by GSPMD (reference:
        parallel.py:581)."""
        return

    def state_dict(self, *args, **kwargs):
        return self._layers.state_dict(*args, **kwargs)

    def set_state_dict(self, state_dict, *args, **kwargs):
        return self._layers.set_state_dict(state_dict, *args, **kwargs)
