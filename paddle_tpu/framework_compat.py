"""Framework-level API-parity pieces.

Reference surfaces re-homed for TPU: Place classes (fluid/framework.py —
device handles users pass to executors/DataLoaders), dygraph mode toggles
(fluid/framework.py enable_dygraph:
this build is dygraph-first, static via paddle.enable_static), CUDA RNG
state shims (the TPU analog is paddle.seed's key), printoptions, and
paddle.flops (hapi/dynamic_flops.py)."""
from __future__ import annotations

import numpy as np


class _Place:
    def __init__(self, device_id: int = 0):
        self.device_id = device_id

    def __repr__(self):
        return f"{type(self).__name__}({self.device_id})"

    def __eq__(self, other):
        return type(self) is type(other) and \
            self.device_id == other.device_id


class CPUPlace(_Place):
    pass


class TPUPlace(_Place):
    pass


class CUDAPlace(_Place):
    """Accepted for API compatibility: CUDA code ported to this framework
    runs on the TPU (there is no CUDA runtime here); the Place carries
    the device ordinal like the reference's."""


class CUDAPinnedPlace(_Place):
    """Maps to host ('pinned_host') memory placement on TPU."""


class XPUPlace(_Place):
    pass


# -- dygraph mode (fluid/framework.py:enable_dygraph) ---------------------
_dygraph = True


def enable_dygraph(place=None):
    global _dygraph
    _dygraph = True


def disable_dygraph():
    global _dygraph
    _dygraph = False


def in_dygraph_mode() -> bool:
    return _dygraph


# -- RNG state shims (the reference's cuda Generator state) ---------------
def get_cuda_rng_state():
    """TPU analog: the global PRNG state (core/rng.py seed + counter)."""
    from .core import rng
    g = rng.default_generator()
    return [np.asarray([g._seed, g._counter], np.int64)]


def set_cuda_rng_state(state):
    from .core import rng
    g = rng.default_generator()
    seed, counter = (int(v) for v in np.asarray(state[0]))
    g.manual_seed(seed)
    g._counter = counter


def get_cudnn_version():
    """No cuDNN on TPU — None, like reference CPU builds."""
    return None


def set_printoptions(precision=None, threshold=None, edgeitems=None,
                     sci_mode=None, linewidth=None):
    """reference: tensor/to_string.py set_printoptions — Tensor repr goes
    through numpy, so numpy's printoptions are the single knob."""
    kw = {}
    if precision is not None:
        kw["precision"] = precision
    if threshold is not None:
        kw["threshold"] = threshold
    if edgeitems is not None:
        kw["edgeitems"] = edgeitems
    if linewidth is not None:
        kw["linewidth"] = linewidth
    if sci_mode is not None:
        kw["suppress"] = not sci_mode
    np.set_printoptions(**kw)


def create_parameter(shape, dtype="float32", name=None, attr=None,
                     is_bias=False, default_initializer=None):
    """Top-level paddle.create_parameter (fluid/layers/tensor.py:70)."""
    import jax.numpy as jnp

    from .core.tensor import Parameter
    from .nn import initializer as I
    init = default_initializer or (
        I.Constant(0.0) if is_bias else I.XavierNormal())
    data = jnp.zeros(shape, dtype=jnp.dtype(dtype))
    p = Parameter(data, name=name)
    init(p)
    return p


def flops(net, input_size, custom_ops=None, print_detail=False) -> int:
    """paddle.flops (reference: hapi/dynamic_flops.py): multiply-add count
    of a forward pass, via forward hooks on conv/linear layers."""
    from . import nn
    from .core import autograd
    from .core.tensor import Tensor

    counts = {}
    handles = []

    def hook(name, kind):
        def fn(layer, inputs, outputs):
            o = outputs[0] if isinstance(outputs, (list, tuple)) \
                else outputs
            # MAC convention matches the reference (dynamic_flops.py
            # count_convNd:122 / count_linear): one multiply-add = 1 op,
            # +1 per output element when a bias exists
            if kind == "conv":
                w = layer.weight
                out_elems = int(np.prod(o.shape))
                per_out = int(np.prod(w.shape[1:]))
                bias_ops = 1 if layer.bias is not None else 0
                counts[name] = counts.get(name, 0) + out_elems * (per_out + bias_ops)
            elif kind == "linear":
                w = layer.weight
                out_rows = int(np.prod(o.shape)) // o.shape[-1]
                counts[name] = counts.get(name, 0) + out_rows * int(np.prod(w.shape))
            return outputs
        return fn

    for name, sub in net.named_sublayers():
        if isinstance(sub, (nn.Conv1D, nn.Conv2D, nn.Conv3D)):
            handles.append(sub.register_forward_post_hook(
                hook(name, "conv")))
        elif isinstance(sub, nn.Linear):
            handles.append(sub.register_forward_post_hook(
                hook(name, "linear")))
        elif custom_ops and type(sub) in custom_ops:
            cnt = custom_ops[type(sub)]
            handles.append(sub.register_forward_post_hook(
                lambda l, i, o, _n=name, _c=cnt: counts.__setitem__(
                    _n, _c(l, i, o)) or o))
    x = Tensor(np.zeros([d if d else 1 for d in input_size], np.float32))
    was = net.training
    net.eval()
    try:
        with autograd.no_grad():
            net(x)
    finally:
        if was:
            net.train()
        for h in handles:
            h.remove()
    total = sum(counts.values())
    if print_detail:
        for k, v in counts.items():
            print(f"{k}: {v:,}")
        print(f"Total FLOPs: {total:,}")
    return total
