"""Multi-model serving control plane: registry, WFQ/quotas, elasticity.

One server, many models.  The per-model building blocks already exist —
``name=`` engine labels with per-engine monitor mirrors, queue-full /
deadline backpressure, digest-verified hot swap, supervised replicas —
this module is the layer above them (the reference analog is Paddle's
standalone inference deployment stack, one Config/AnalysisPredictor per
model, grown into a runtime-mutable registry):

- :class:`ModelRegistry` — load/unload/alias models at runtime.  Each
  model owns its own :class:`~paddle_tpu.serving.InferenceEngine` and/or
  :class:`~paddle_tpu.serving.GenerationEngine` (its own queue, its own
  dispatcher, its own pages) plus an optional per-model
  :class:`~paddle_tpu.serving.WeightWatcher` for rollouts.  Request
  routing is by model name or alias; an unknown name raises
  :class:`UnknownModel` (the HTTP layer maps it to a clean 404).
  Lifecycle: ``loading -> warming -> ready -> draining -> unloaded``;
  unload removes the name from routing FIRST, then drains through the
  engines' existing ``drain()``/``close()`` contracts — accepted
  requests finish, generation page pools come back fully reclaimed.
- **Weighted fair queuing** across models: admission shares one
  ``max_inflight`` pool.  While the pool has headroom every model
  admits freely (work-conserving); once it is saturated a model is
  clamped to its weighted share ``max_inflight * w / sum(w)`` — a hot
  model sheds (``QueueFull``) at its share while a quiet one still
  admits up to its own, so one model can never starve the rest.
- **Per-tenant quotas**: token buckets (``rate`` req/s, ``burst``)
  keyed by tenant id, layered BEFORE the engine queue — an over-quota
  tenant gets :class:`QuotaExceeded` (HTTP 429) without ever touching
  a queue slot, so quota pressure from one tenant is invisible to the
  others' backpressure.
- :class:`ElasticityController` — the SLO burn-rate rules (PR 9,
  :mod:`paddle_tpu.observability.slo`) evaluated per model over the
  per-engine monitor mirrors drive replica counts: sustained burn
  scales a model up through a ``scaler`` callback (see
  :class:`ReplicaSet` for the ServingSupervisor-backed default),
  sustained calm scales it down, and a model still burning at
  ``max_replicas`` triggers a *shed decision* — the registry sheds that
  model's new requests until the windows clear.  Everything is
  observable: ``registry.*`` / ``elasticity.*`` stats and tracer
  events.

See README "Multi-model control plane" for operational semantics.
"""
from __future__ import annotations

import os
import threading
import time
from typing import Callable, Dict, List, Optional, Sequence

from ..core import flags, obs_hook
from ..utils import monitor
from .engine import EngineClosed, InferenceEngine, QueueFull, ServingError

__all__ = ["ModelRegistry", "ModelEntry", "UnknownModel", "QuotaExceeded",
           "ElasticityController", "ReplicaSet"]


class UnknownModel(ServingError):
    """Request routed to a model name/alias the registry does not hold
    (HTTP: a clean 404, never a 500)."""


class QuotaExceeded(ServingError):
    """A tenant exhausted its token bucket (HTTP 429 + Retry-After)."""


def _emit(event: str, **args) -> None:
    trc = obs_hook._tracer
    if trc is not None:
        trc.emit("registry", event, args=args)


class _TokenBucket:
    """Classic token bucket; ``admit`` is called under the registry
    lock, so no internal locking."""

    __slots__ = ("rate", "burst", "tokens", "t")

    def __init__(self, rate: float, burst: float):
        if rate <= 0 or burst < 1:
            raise ValueError("quota needs rate > 0 and burst >= 1")
        self.rate = float(rate)
        self.burst = float(burst)
        self.tokens = float(burst)
        self.t = time.monotonic()

    def admit(self, now: Optional[float] = None) -> bool:
        now = time.monotonic() if now is None else now
        self.tokens = min(self.burst,
                          self.tokens + (now - self.t) * self.rate)
        self.t = now
        if self.tokens >= 1.0:
            self.tokens -= 1.0
            return True
        return False

    def retry_after_s(self) -> float:
        return max(0.0, (1.0 - self.tokens) / self.rate)


class ModelEntry:
    """One registered model: its engines, watcher, routing weight and
    lifecycle state.  Mutated only under the registry lock (state/
    weight/shedding); the engines themselves are internally threadsafe."""

    STATES = ("loading", "warming", "ready", "draining", "unloaded",
              "failed")

    def __init__(self, name: str, *, engine: Optional[InferenceEngine]
                 = None, generation=None, watcher=None,
                 weight: float = 1.0, artifact: Optional[str] = None,
                 state: str = "loading"):
        if engine is None and generation is None:
            raise ValueError(f"model {name!r} needs an InferenceEngine, "
                             f"a GenerationEngine, or both")
        if weight <= 0:
            raise ValueError("weight must be > 0")
        self.name = name
        self.engine = engine
        self.generation = generation
        self.watcher = watcher
        self.weight = float(weight)
        self.artifact = artifact
        self.state = state
        self.shedding = False           # elasticity shed decision
        self.created = time.time()

    @property
    def weights_version(self) -> int:
        for src in (self.engine, self.generation):
            if src is not None:
                return int(getattr(src, "weights_version", 0))
        return 0

    def describe(self, inflight: int = 0) -> dict:
        d = {"state": self.state, "weight": self.weight,
             "weights_version": self.weights_version,
             "inflight": inflight, "shedding": self.shedding,
             "engines": [k for k, v in (("inference", self.engine),
                                        ("generation", self.generation))
                         if v is not None]}
        if self.artifact:
            d["artifact"] = self.artifact
        if self.generation is not None:
            d["page_pool"] = self.generation.stats()["page_pool"]
        return d


class ModelRegistry:
    """Runtime-mutable model routing table + fair admission layer.

    Args:
        max_inflight: the WFQ pool — total in-flight requests across
            all models before weighted shares clamp admission.  None
            disables WFQ (each engine still has its own bounded queue).
        default_model: name served when a request carries no model
            (single-model clients keep working unchanged); defaults to
            the first registered model.
    """

    def __init__(self, *, max_inflight: Optional[int] = None,
                 default_model: Optional[str] = None):
        self._mu = threading.RLock()
        self._models: Dict[str, ModelEntry] = {}
        self._aliases: Dict[str, str] = {}
        self._quotas: Dict[str, _TokenBucket] = {}
        self._inflight: Dict[str, int] = {}
        self._max_inflight = (int(max_inflight)
                              if max_inflight is not None else None)
        self._default = default_model
        self._closed = False

    # -- registration / lifecycle ------------------------------------------
    def register(self, name: str, *, engine: Optional[InferenceEngine]
                 = None, generation=None, watcher=None,
                 weight: float = 1.0, aliases: Sequence[str] = (),
                 artifact: Optional[str] = None,
                 ready: bool = True) -> ModelEntry:
        """Attach pre-built engines under ``name``.  ``ready=False``
        registers the model routable-but-warming (requests answer 503
        through :class:`EngineClosed`) — call :meth:`mark_ready` after
        warmup, exactly like the HTTP readiness split."""
        entry = ModelEntry(name, engine=engine, generation=generation,
                           watcher=watcher, weight=weight,
                           artifact=artifact,
                           state="ready" if ready else "warming")
        with self._mu:
            if self._closed:
                raise EngineClosed("registry is closed")
            if name in self._models or name in self._aliases:
                raise ValueError(f"model name {name!r} already in use")
            self._models[name] = entry
            self._inflight[name] = 0
            for a in aliases:
                self._alias_locked(a, name)
            if self._default is None:
                self._default = name
            n = len(self._models)
        monitor.stat_add("registry.loads")
        monitor.stat_set("registry.models", n)
        _emit("register", model=name, ready=ready,
              aliases=list(aliases))
        return entry

    def load(self, name: str, artifact: str, *,
             weights_dir: Optional[str] = None,
             weights_poll_s: float = 2.0,
             aliases: Sequence[str] = (), weight: float = 1.0,
             warmup: bool = True,
             rest_shapes: Optional[Sequence[Sequence[int]]] = None,
             engine_kwargs: Optional[dict] = None) -> ModelEntry:
        """Load an inference artifact end to end: Predictor -> engine
        (named ``name`` so its stats mirror per-model) -> warmup ->
        ready, with an optional per-model :class:`WeightWatcher` on
        ``weights_dir``.  With ``FLAGS_compile_cache_dir`` set, warmup
        deserializes previously compiled buckets instead of paying XLA
        again.  The name becomes routable only once ready — a load can
        never race traffic into a cold engine."""
        from .. import inference
        kw = dict(engine_kwargs or {})
        kw.setdefault("name", name)
        eng = InferenceEngine(
            inference.create_predictor(inference.Config(artifact)), **kw)
        entry = self.register(name, engine=eng, aliases=aliases,
                              weight=weight, artifact=artifact,
                              ready=False)
        try:
            if warmup:
                eng.warmup(rest_shapes=rest_shapes)
            if weights_dir:
                from .hotswap import WeightWatcher
                entry.watcher = WeightWatcher(
                    weights_dir, engine=eng, poll_s=weights_poll_s,
                    rest_shapes=rest_shapes).start()
        except BaseException:
            with self._mu:
                entry.state = "failed"
            eng.close()
            self._forget(name)
            raise
        self.mark_ready(name)
        return entry

    def mark_ready(self, name: str) -> None:
        with self._mu:
            entry = self._models.get(name)
            if entry is None:
                raise UnknownModel(f"unknown model {name!r}")
            entry.state = "ready"
        _emit("ready", model=name)

    def _alias_locked(self, alias: str, target: str) -> None:
        if target not in self._models:
            raise UnknownModel(f"alias target {target!r} is not a "
                               f"registered model")
        if alias in self._models:
            raise ValueError(f"alias {alias!r} shadows a model name")
        self._aliases[alias] = target

    def alias(self, alias: str, target: str) -> None:
        """Point ``alias`` at ``target`` (create or atomically flip —
        a canary rollout is ``alias("prod", "model-v2")``)."""
        with self._mu:
            self._alias_locked(alias, target)
        monitor.stat_add("registry.alias_flips")
        _emit("alias", alias=alias, target=target)

    def unalias(self, alias: str) -> None:
        with self._mu:
            if self._aliases.pop(alias, None) is None:
                raise UnknownModel(f"unknown alias {alias!r}")
        _emit("unalias", alias=alias)

    def _forget(self, name: str) -> None:
        with self._mu:
            self._models.pop(name, None)
            self._inflight.pop(name, None)
            for a in [a for a, t in self._aliases.items() if t == name]:
                del self._aliases[a]
            if self._default == name:
                self._default = next(iter(self._models), None)
            monitor.stat_set("registry.models", len(self._models))

    def unload(self, name: str, timeout: float = 30.0) -> dict:
        """Remove a model: routing first (new requests get
        :class:`UnknownModel` immediately), then drain + close its
        engines through their existing contracts — every accepted
        request finishes or fails cleanly, no future is stranded, and
        a generation engine's page pool is fully reclaimed before this
        returns.  The watcher stops before the drain so a hot swap can
        never land mid-teardown.  Returns a teardown summary (drained
        flags + final page-pool accounting)."""
        with self._mu:
            entry = self._models.get(name)
            if entry is None:
                raise UnknownModel(f"unknown model {name!r}")
            entry.state = "draining"
        _emit("unload_begin", model=name)
        if entry.watcher is not None:
            entry.watcher.stop()
        summary: dict = {"model": name}
        if entry.engine is not None:
            summary["engine_drained"] = entry.engine.drain(timeout=timeout)
            entry.engine.close()
        if entry.generation is not None:
            summary["generation_drained"] = entry.generation.drain(
                timeout=timeout)
            entry.generation.close()
            pool = entry.generation.stats()["page_pool"]
            summary["page_pool"] = pool
            summary["pages_reclaimed"] = pool["in_use"] == 0
        with self._mu:
            entry.state = "unloaded"
        self._forget(name)
        monitor.stat_add("registry.unloads")
        _emit("unload", model=name, **{k: v for k, v in summary.items()
                                       if k != "model"})
        return summary

    def close(self, timeout: float = 30.0) -> None:
        """Unload every model (drain + close) and refuse further use."""
        with self._mu:
            self._closed = True
            names = list(self._models)
        for n in names:
            try:
                self.unload(n, timeout=timeout)
            except UnknownModel:
                pass        # concurrent unload won the race

    # -- routing & admission -----------------------------------------------
    def resolve(self, model: Optional[str]) -> ModelEntry:
        """Name/alias -> live entry.  Unknown names raise
        :class:`UnknownModel`; a known-but-not-ready model raises
        :class:`EngineClosed` (503: retry, don't 404 — the name exists)."""
        with self._mu:
            name = model or self._default
            if name is None:
                raise UnknownModel("no models registered")
            name = self._aliases.get(name, name)
            entry = self._models.get(name)
            if entry is None:
                monitor.stat_add("registry.unknown_model")
                raise UnknownModel(f"unknown model {model!r}")
            if entry.state != "ready":
                raise EngineClosed(
                    f"model {name!r} is {entry.state}")
            return entry

    def set_quota(self, tenant: str, rate: float,
                  burst: Optional[float] = None) -> None:
        """Cap ``tenant`` at ``rate`` requests/second with a bucket of
        ``burst`` (default: ``max(rate, 1)``).  Tenants without a quota
        are unlimited."""
        with self._mu:
            self._quotas[str(tenant)] = _TokenBucket(
                rate, burst if burst is not None else max(rate, 1.0))

    def clear_quota(self, tenant: str) -> None:
        with self._mu:
            self._quotas.pop(str(tenant), None)

    def set_weight(self, name: str, weight: float) -> None:
        if weight <= 0:
            raise ValueError("weight must be > 0")
        with self._mu:
            entry = self._models.get(name)
            if entry is None:
                raise UnknownModel(f"unknown model {name!r}")
            entry.weight = float(weight)

    def _admit_locked(self, entry: ModelEntry,
                      tenant: Optional[str]) -> None:
        """Quota then WFQ, both under the lock; raising here means the
        request never touched an engine queue."""
        if entry.shedding:
            monitor.stat_add("registry.elasticity_shed")
            raise QueueFull(
                f"model {entry.name!r} is shedding (SLO burn at max "
                f"replicas); retry later")
        if tenant is not None:
            b = self._quotas.get(str(tenant))
            if b is not None and not b.admit():
                monitor.stat_add("registry.quota_shed")
                _emit("quota_shed", model=entry.name, tenant=str(tenant))
                raise QuotaExceeded(
                    f"tenant {tenant!r} over quota ({b.rate:g} req/s, "
                    f"burst {b.burst:g}); retry in "
                    f"{b.retry_after_s():.2f}s")
        if self._max_inflight is not None:
            total = sum(self._inflight.values())
            if total >= self._max_inflight:
                # pool saturated: clamp this model to its weighted
                # share (work-conserving below saturation — the share
                # only binds under contention)
                w_total = sum(e.weight for e in self._models.values()
                              if e.state == "ready") or entry.weight
                share = self._max_inflight * entry.weight / w_total
                if self._inflight[entry.name] + 1 > share:
                    monitor.stat_add("registry.wfq_shed")
                    _emit("wfq_shed", model=entry.name,
                          inflight=self._inflight[entry.name],
                          share=share)
                    raise QueueFull(
                        f"model {entry.name!r} over its weighted fair "
                        f"share ({self._inflight[entry.name]}/"
                        f"{share:.1f} of pool {self._max_inflight})")
        self._inflight[entry.name] += 1
        monitor.stat_set(f"registry.inflight.{entry.name}",
                         self._inflight[entry.name])
        # emitted on the admitting (HTTP handler) thread, so it carries
        # the bound distributed trace id — the admission decision is a
        # node in the request's cross-process span tree
        _emit("admit", model=entry.name,
              tenant=None if tenant is None else str(tenant),
              inflight=self._inflight[entry.name])

    def _release(self, name: str) -> None:
        with self._mu:
            if name in self._inflight and self._inflight[name] > 0:
                self._inflight[name] -= 1
                monitor.stat_set(f"registry.inflight.{name}",
                                 self._inflight[name])

    def infer(self, model: Optional[str], inputs, *,
              tenant: Optional[str] = None,
              deadline_ms: Optional[float] = None):
        """Route one inference request; returns the engine Future.
        Admission order: resolve -> shed flag -> tenant quota -> WFQ
        share -> the engine's own queue (which may still shed
        ``QueueFull`` when ITS bounded queue is full)."""
        entry = self.resolve(model)
        if entry.engine is None:
            raise UnknownModel(
                f"model {entry.name!r} has no inference engine")
        with self._mu:
            self._admit_locked(entry, tenant)
        monitor.stat_add("registry.requests")
        try:
            fut = entry.engine.infer(inputs, deadline_ms=deadline_ms)
        except BaseException:
            self._release(entry.name)
            raise
        fut.add_done_callback(lambda _f: self._release(entry.name))
        return fut

    def infer_sync(self, model: Optional[str], inputs, *,
                   tenant: Optional[str] = None,
                   deadline_ms: Optional[float] = None,
                   timeout: Optional[float] = None):
        return self.infer(model, inputs, tenant=tenant,
                          deadline_ms=deadline_ms).result(timeout)

    def generate(self, model: Optional[str], prompt, *,
                 tenant: Optional[str] = None, **kw):
        """Route one generation request; returns the
        :class:`GenerationStream`.  Same admission ladder as
        :meth:`infer`; the WFQ slot is held until the stream finishes
        (generation is long-lived — that is exactly what the share
        must account for)."""
        entry = self.resolve(model)
        if entry.generation is None:
            raise UnknownModel(
                f"model {entry.name!r} has no generation engine")
        with self._mu:
            self._admit_locked(entry, tenant)
        monitor.stat_add("registry.requests")
        try:
            stream = entry.generation.generate(prompt, **kw)
        except BaseException:
            self._release(entry.name)
            raise
        stream.future.add_done_callback(
            lambda _f: self._release(entry.name))
        return stream

    # -- introspection ------------------------------------------------------
    @property
    def default_model(self) -> Optional[str]:
        with self._mu:
            return self._default

    def set_default(self, name: str) -> None:
        with self._mu:
            if self._aliases.get(name, name) not in self._models:
                raise UnknownModel(f"unknown model {name!r}")
            self._default = name

    def models(self) -> List[str]:
        with self._mu:
            return sorted(self._models)

    def describe(self) -> dict:
        """The ``GET /admin/models`` payload: every model's state,
        version, engines, inflight and weight, plus aliases and the
        default route."""
        with self._mu:
            return {
                "models": {n: e.describe(self._inflight.get(n, 0))
                           for n, e in self._models.items()},
                "aliases": dict(self._aliases),
                "default": self._default,
                "max_inflight": self._max_inflight,
                "quotas": {t: {"rate": b.rate, "burst": b.burst}
                           for t, b in self._quotas.items()},
            }

    def stats(self) -> dict:
        with self._mu:
            return {
                "models": len(self._models),
                "inflight": dict(self._inflight),
                "counters": {k: monitor.get_stat(f"registry.{k}")
                             for k in ("requests", "loads", "unloads",
                                       "alias_flips", "wfq_shed",
                                       "quota_shed", "unknown_model",
                                       "elasticity_shed")},
            }


# --------------------------------------------------------------------------
# SLO-driven elasticity
# --------------------------------------------------------------------------
class ReplicaSet:
    """N supervised replicas of one serving entry, scalable at runtime.

    Each replica is a :class:`~paddle_tpu.distributed.supervisor.
    ServingSupervisor` (child process + health probes + backoff
    restarts) run on its own thread; ``scale_to(n)`` spawns or stops
    supervisors to match.  ``factory(index)`` must return an UNSTARTED
    supervisor — the set owns ``run()``/``stop()``.  This is the
    default muscle behind :class:`ElasticityController`'s ``scaler``
    callback for process-per-replica deployments; in-process tests use
    a plain callable instead."""

    def __init__(self, factory: Callable[[int], object],
                 name: str = "model"):
        self._factory = factory
        self.name = name
        self._mu = threading.Lock()
        self._replicas: List[tuple] = []    # (supervisor, thread)

    @property
    def count(self) -> int:
        with self._mu:
            return len(self._replicas)

    def scale_to(self, n: int) -> int:
        """Spawn/stop supervisors until ``count == n``; returns the new
        count.  Scale-down stops the newest replica first (oldest keeps
        the warmest cache)."""
        n = max(0, int(n))
        with self._mu:
            while len(self._replicas) < n:
                idx = len(self._replicas)
                sup = self._factory(idx)
                th = threading.Thread(
                    target=sup.run,
                    name=f"replica-{self.name}-{idx}", daemon=True)
                th.start()
                self._replicas.append((sup, th))
            while len(self._replicas) > n:
                sup, th = self._replicas.pop()
                sup.stop()
                th.join(timeout=10.0)
            return len(self._replicas)

    def stop(self) -> None:
        self.scale_to(0)

    def describe(self) -> dict:
        """Per-replica control-plane view: supervisor readiness, the
        replica's base URL (derived from its health probe URL) and its
        restart count — ``GET /admin/fleet`` merges this with a live
        scrape of each URL."""
        from urllib.parse import urlparse
        with self._mu:
            replicas = []
            for i, (sup, th) in enumerate(self._replicas):
                info = {
                    "index": i,
                    "supervisor": getattr(sup, "name", None),
                    "alive": th.is_alive(),
                    "ready": getattr(sup, "ready", None),
                    "restarts": len(getattr(sup, "exit_history", ())
                                    or ()),
                    "url": None,
                }
                hu = getattr(sup, "health_url", None)
                if hu:
                    u = urlparse(hu)
                    info["url"] = f"{u.scheme or 'http'}://{u.netloc}"
                replicas.append(info)
            return {"name": self.name, "count": len(replicas),
                    "replicas": replicas}


class ElasticityController:
    """SLO burn rates -> per-model replica counts and shed decisions.

    Per ready model, a rule set from ``rules_for(name)`` (default: p99
    latency against ``objective_ms`` over that model's per-engine
    mirror ``serving.engine.<name>.latency_ms``) is evaluated by its
    own :class:`~paddle_tpu.observability.slo.SLOMonitor` each
    :meth:`poll`:

    - burn >= ``scale_up_burn`` for ``breach_polls`` consecutive polls
      scales the model up one replica (to ``max_replicas``) through
      ``scaler(name, desired)``, then holds through ``cooldown_s``;
    - burn <= ``scale_down_burn`` for ``clear_polls`` polls scales it
      down one (to ``min_replicas``);
    - still breaching at ``max_replicas``: the *shed decision* — the
      registry sheds that model's new requests (``QueueFull``) until
      the burn clears, protecting every other model's objectives.

    Observable: ``elasticity.scale_up/scale_down/shed/recover``
    counters, ``elasticity.<model>.{desired_replicas,burn}`` gauges and
    ``elasticity`` tracer events.  ``poll(now=)`` is injectable for
    deterministic tests; :meth:`start` runs it on a daemon thread."""

    def __init__(self, registry: ModelRegistry,
                 rules_for: Optional[Callable[[str], list]] = None, *,
                 scaler: Optional[Callable[[str, int], None]] = None,
                 objective_ms: float = 250.0, window: float = 30.0,
                 min_count: int = 8,
                 min_replicas: int = 1, max_replicas: int = 4,
                 scale_up_burn: float = 1.0, scale_down_burn: float = 0.5,
                 breach_polls: int = 2, clear_polls: int = 3,
                 cooldown_s: float = 30.0, poll_s: float = 2.0):
        if min_replicas < 0 or max_replicas < max(min_replicas, 1):
            raise ValueError("need 0 <= min_replicas <= max_replicas "
                             "and max_replicas >= 1")
        self.registry = registry
        self.scaler = scaler
        self.min_replicas = int(min_replicas)
        self.max_replicas = int(max_replicas)
        self.scale_up_burn = float(scale_up_burn)
        self.scale_down_burn = float(scale_down_burn)
        self.breach_polls = int(breach_polls)
        self.clear_polls = int(clear_polls)
        self.cooldown_s = float(cooldown_s)
        self.poll_s = float(poll_s)
        if rules_for is None:
            from ..observability.slo import SLORule

            def rules_for(name: str):
                return [SLORule(f"serving.engine.{name}.latency_ms",
                                objective_ms, window=window,
                                quantile=0.99, min_count=min_count,
                                name=f"{name}_p99_latency_ms")]
        self._rules_for = rules_for
        self._mu = threading.Lock()
        self._monitors: Dict[str, object] = {}
        self._state: Dict[str, dict] = {}   # per-model control state
        self._thread: Optional[threading.Thread] = None
        self._stop = threading.Event()

    def _emit(self, event: str, **args) -> None:
        trc = obs_hook._tracer
        if trc is not None:
            trc.emit("elasticity", event, args=args)

    def _collect_incident(self, name: str, burn: float) -> None:
        spool = flags.get_flag("obs_spool_dir")
        if not spool:
            return
        try:
            from ..observability import fleet as _fleet
            _fleet.collect_fleet_bundle(
                os.path.join(spool, f"incident_shed_{name}"),
                reason=f"registry.shed:{name}",
                extra={"model": name, "burn": round(burn, 3)})
        except Exception:   # telemetry must never break the control loop
            pass

    def _model_state(self, name: str) -> dict:
        return self._state.setdefault(name, {
            "desired": self.min_replicas, "breach": 0, "clear": 0,
            "cooldown_until": 0.0})

    def _scale(self, name: str, st: dict, desired: int,
               now: float) -> None:
        st["desired"] = desired
        st["cooldown_until"] = now + self.cooldown_s
        st["breach"] = st["clear"] = 0
        monitor.stat_set(f"elasticity.{name}.desired_replicas", desired)
        if self.scaler is not None:
            self.scaler(name, desired)

    def poll(self, now: Optional[float] = None) -> dict:
        """One control-loop evaluation over every ready model; returns
        ``{model: {burn, desired, shedding, breached}}``.  ``now``
        (monotonic seconds) feeds the SLO windows AND the cooldown
        clock, so tests drive time explicitly."""
        import math
        now = time.monotonic() if now is None else float(now)
        out: Dict[str, dict] = {}
        with self.registry._mu:
            entries = {n: e for n, e in self.registry._models.items()
                       if e.state == "ready"}
        with self._mu:
            for name in list(self._monitors):
                if name not in entries:     # unloaded: drop its loop
                    del self._monitors[name]
                    self._state.pop(name, None)
            for name, entry in entries.items():
                from ..observability.slo import SLOMonitor
                mon = self._monitors.get(name)
                if mon is None:
                    mon = self._monitors[name] = SLOMonitor(
                        self._rules_for(name))
                status = mon.poll(now=now)
                burns = [r["burn"] for r in status["rules"]
                         if isinstance(r["burn"], (int, float))]
                burn = max(burns) if burns else 0.0
                breached = bool(status["breached"])
                st = self._model_state(name)
                monitor.stat_set(
                    f"elasticity.{name}.burn",
                    round(burn, 6) if math.isfinite(burn) else 1e12)
                in_cooldown = now < st["cooldown_until"]
                if burn >= self.scale_up_burn:
                    st["breach"] += 1
                    st["clear"] = 0
                    if (st["breach"] >= self.breach_polls
                            and not in_cooldown):
                        if st["desired"] < self.max_replicas:
                            self._scale(name, st, st["desired"] + 1, now)
                            monitor.stat_add("elasticity.scale_up")
                            self._emit("scale_up", model=name,
                                       desired=st["desired"],
                                       burn=round(burn, 3))
                        elif not entry.shedding:
                            # at max capacity and still burning: shed
                            entry.shedding = True
                            monitor.stat_add("elasticity.shed")
                            self._emit("shed", model=name,
                                       burn=round(burn, 3))
                            # a shed decision is a registry incident:
                            # when the fleet is spooling, capture every
                            # process's black box for the post-mortem
                            self._collect_incident(name, burn)
                elif burn <= self.scale_down_burn:
                    st["clear"] += 1
                    st["breach"] = 0
                    if entry.shedding:
                        entry.shedding = False
                        monitor.stat_add("elasticity.recover")
                        self._emit("recover", model=name)
                    if (st["clear"] >= self.clear_polls
                            and not in_cooldown
                            and st["desired"] > self.min_replicas):
                        self._scale(name, st, st["desired"] - 1, now)
                        monitor.stat_add("elasticity.scale_down")
                        self._emit("scale_down", model=name,
                                   desired=st["desired"])
                else:       # between thresholds: hysteresis band
                    st["breach"] = st["clear"] = 0
                out[name] = {"burn": burn, "desired": st["desired"],
                             "shedding": entry.shedding,
                             "breached": breached}
        return out

    def status(self) -> dict:
        with self._mu:
            return {n: dict(st) for n, st in self._state.items()}

    def start(self) -> "ElasticityController":
        if self._thread is not None:
            raise RuntimeError("controller already started")
        self._stop.clear()

        def loop():
            while not self._stop.wait(self.poll_s):
                try:
                    self.poll()
                except Exception:   # registry churn mid-poll: retry next
                    pass

        self._thread = threading.Thread(target=loop, name="elasticity",
                                        daemon=True)
        self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=10.0)
            self._thread = None
