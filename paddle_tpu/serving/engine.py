"""Concurrent dynamic-batching inference engine over the AOT Predictor.

Design (the TPU-serving recipe — batch coalescing into a small set of
precompiled shapes, accelerator kept saturated while requests queue;
cf. Ragged Paged Attention, PAPERS.md):

- Callers enqueue requests (``infer`` returns a
  :class:`concurrent.futures.Future`); a single dispatcher thread pops
  waiting requests, coalesces them into one micro-batch of at most
  ``max_batch_size`` rows, pads the batch dimension up to the smallest
  declared bucket, and runs the Predictor ONCE for the whole batch —
  after :meth:`InferenceEngine.warmup` the hot path always hits the AOT
  compile cache (zero recompiles).
- Robustness is built in, not bolted on: a bounded queue that sheds
  load when full (:class:`QueueFull`), per-request deadlines that
  expire in-queue without ever occupying a batch slot
  (:class:`DeadlineExceeded`), dispatch retries (inference is pure, so
  a flaked dispatch re-runs safely), and graceful ``drain()`` /
  ``close()`` that finish in-flight work and never strand a future.
- ``fault.point("serving.enqueue")`` / ``fault.point("serving.dispatch")``
  hooks let chaos tests (testing/chaos.py serving scenario) flake the
  admission and dispatch paths deterministically.
- Self-healing rails: every dispatched batch stamps the supervised
  heartbeat (``obs_hook._heartbeat`` — one None-check when
  unsupervised, the same pattern the Executor uses for training
  supervision), and :meth:`InferenceEngine.swap_predictor` commits a
  prepared replacement predictor under the engine lock at a batch
  boundary — the zero-downtime weight hot swap
  (:mod:`paddle_tpu.serving.hotswap` owns the polling/verify side).
"""
from __future__ import annotations

import collections
import itertools
import threading
import time
from concurrent.futures import Future
from typing import Dict, List, Optional, Sequence, Union

import numpy as np

from ..core import flags, obs_hook
from ..testing import fault
from ..utils import monitor

__all__ = ["InferenceEngine", "ServingError", "QueueFull",
           "DeadlineExceeded", "EngineClosed"]


class ServingError(RuntimeError):
    """Base class for engine-raised request failures."""


class QueueFull(ServingError):
    """Load shed: the bounded request queue was full at admission."""


class DeadlineExceeded(ServingError):
    """The request's deadline expired while it waited in the queue."""


class EngineClosed(ServingError):
    """The engine is draining or closed; no new requests are accepted."""


_REQUEST_IDS = itertools.count(1)   # process-wide request correlation ids


class _Request:
    __slots__ = ("arrays", "rows", "future", "deadline", "t_enq", "rid",
                 "trace")

    def __init__(self, arrays, rows, deadline):
        self.arrays = arrays
        self.rows = rows
        self.future: Future = Future()
        self.deadline = deadline            # monotonic seconds, or None
        self.t_enq = time.monotonic()
        self.rid = next(_REQUEST_IDS)
        self.trace: Optional[str] = None    # distributed trace id, if the
                                            # admitting thread carried one


def _safe_set_result(fut: Future, value) -> None:
    try:
        fut.set_result(value)
    except Exception:       # cancelled by the caller: nothing to deliver
        pass


def _safe_set_exception(fut: Future, exc: BaseException) -> None:
    try:
        fut.set_exception(exc)
    except Exception:
        pass


def _mirrored_add(base: str, prefix, suffix: str, v=1) -> None:
    """One engine counter: the process aggregate under ``base`` plus
    the per-engine mirror under ``prefix`` when the engine is named —
    the single mirroring rule both engine classes share."""
    monitor.stat_add(base + suffix, v)
    if prefix is not None:
        monitor.stat_add(prefix + suffix, v)


def _mirrored_observe(base: str, prefix, suffix: str, v) -> None:
    monitor.stat_observe(base + suffix, v)
    if prefix is not None:
        monitor.stat_observe(prefix + suffix, v)


class InferenceEngine:
    """Dynamic-batching front for a :class:`paddle_tpu.inference.Predictor`.

    Args:
        predictor: a loaded Predictor (the engine becomes its only
            caller; the Predictor itself is single-threaded).
        max_batch_size: coalesced-batch row capacity; also the largest
            admissible request.
        batch_timeout_ms: how long the dispatcher waits for more
            requests after the first one arrives before launching a
            partial batch.
        max_queue: bounded queue capacity (requests, not rows); a full
            queue sheds new arrivals with :class:`QueueFull`.
        default_deadline_ms: in-queue deadline applied to requests that
            don't carry their own (None = wait forever).
        buckets: batch capacities to pad to, e.g. ``[1, 2, 4, 8]``;
            default powers of two up to ``max_batch_size``.  ``warmup``
            AOT-compiles exactly these shapes.
        dispatch_retries: re-runs of a failed batch before its requests
            are failed (default ``FLAGS_serving_dispatch_retries``).
        name: engine label for multi-model processes.  When set, the
            engine's monitor stats mirror under
            ``serving.engine.<name>.*`` (in addition to the process
            aggregate ``serving.*``), tracer events carry it, and the
            HTTP layer labels the Prometheus gauges
            ``paddle_tpu_serving_engine_*{engine="<name>"}``.
    """

    def __init__(self, predictor, max_batch_size: int = 32,
                 batch_timeout_ms: float = 2.0, max_queue: int = 256,
                 default_deadline_ms: Optional[float] = None,
                 buckets: Optional[Sequence[int]] = None,
                 dispatch_retries: Optional[int] = None,
                 name: Optional[str] = None):
        if max_batch_size < 1:
            raise ValueError("max_batch_size must be >= 1")
        if max_queue < 1:
            raise ValueError("max_queue must be >= 1")
        self._pred = predictor
        self.name = str(name) if name else None
        self._stat_prefix = (f"serving.engine.{self.name}."
                             if self.name else None)
        self._input_names = list(predictor.get_input_names())
        meta = getattr(predictor, "_meta", {}) or {}
        self._in_dtypes = [np.dtype(d) for d in meta.get("in_dtypes", [])] \
            or None
        self._in_shapes = meta.get("in_shapes")
        # non-batch dims per input, when the artifact declares them
        # statically — lets admission reject a mis-shaped request instead
        # of letting it poison a coalesced batch
        self._rest_shapes: Optional[List[tuple]] = None
        if self._in_shapes:
            try:
                self._rest_shapes = [tuple(int(d) for d in s[1:])
                                     for s in self._in_shapes]
            except (TypeError, ValueError):
                pass    # symbolic non-batch dims: validated by XLA only
        self._max_batch = int(max_batch_size)
        self._batch_timeout = max(0.0, float(batch_timeout_ms)) / 1000.0
        self._max_queue = int(max_queue)
        self._default_deadline = (float(default_deadline_ms) / 1000.0
                                  if default_deadline_ms is not None
                                  else None)
        self._retries = (flags.get_flag("serving_dispatch_retries")
                         if dispatch_retries is None
                         else int(dispatch_retries))
        if buckets is None:
            buckets = []
            b = 1
            while b < self._max_batch:
                buckets.append(b)
                b <<= 1
            buckets.append(self._max_batch)
        self._buckets = sorted(set(int(b) for b in buckets))
        if not self._buckets or self._buckets[0] < 1:
            raise ValueError("buckets must be positive")
        if self._buckets[-1] > self._max_batch:
            raise ValueError(
                f"bucket {self._buckets[-1]} exceeds max_batch_size="
                f"{self._max_batch}; it could never fill and every "
                f"batch would pad past the declared row capacity")
        if self._buckets[-1] < self._max_batch:
            self._buckets.append(self._max_batch)

        self._cv = threading.Condition(threading.Lock())
        self._queue: collections.deque = collections.deque()
        self._queued_rows = 0
        self._queued_deadlines = 0      # requests in queue with a deadline
        self._inflight = False
        self._inflight_reqs: List[_Request] = []
        self._draining = False
        self._closing = False
        self._closed = False
        self._paused = False            # testing hook: pause()/resume()
        self._pred_mu = threading.Lock()
        self._warm_variants: Optional[int] = None
        self._weights_version = 0       # last hot-swapped snapshot step
        # which outputs carry the batch dim: warmup observes it across
        # bucket sizes; the artifact's symbolic out_avals are the
        # fallback; None = per-batch shape heuristic
        self._out_mask: Optional[List[bool]] = getattr(
            predictor, "batched_output_mask", lambda: None)()
        self._c: Dict[str, Union[int, float]] = collections.defaultdict(int)
        self._occ_sum = 0.0
        # per-engine histogram registry: two engines in one process (or
        # a monitor.stat_reset() in a test) must not cross-contaminate
        # /metrics latency percentiles; global monitor mirrors remain
        self._reg = monitor.StatRegistry()
        self._thread = threading.Thread(target=self._dispatch_loop,
                                        name="serving-dispatcher",
                                        daemon=True)
        self._thread.start()

    # -- per-engine metrics ------------------------------------------------
    def _madd(self, suffix: str, v=1) -> None:
        """Count ``serving.<suffix>`` — and mirror it under this
        engine's ``serving.engine.<name>.`` prefix when labelled, so a
        multi-model process can tell its engines apart."""
        _mirrored_add("serving.", self._stat_prefix, suffix, v)

    def _mobs(self, suffix: str, v) -> None:
        _mirrored_observe("serving.", self._stat_prefix, suffix, v)

    def _ev(self, **args) -> dict:
        """Tracer event args, engine-labelled when the engine is."""
        if self.name is not None:
            args["engine"] = self.name
        return args

    # -- admission ---------------------------------------------------------
    def _normalize(self, inputs) -> List[np.ndarray]:
        if isinstance(inputs, dict):
            try:
                inputs = [inputs[n] for n in self._input_names]
            except KeyError as e:
                raise ValueError(f"missing input {e.args[0]!r}; expected "
                                 f"{self._input_names}") from None
        elif isinstance(inputs, np.ndarray) or not isinstance(
                inputs, (list, tuple)):
            inputs = [inputs]
        if len(inputs) != len(self._input_names):
            raise ValueError(f"expected {len(self._input_names)} inputs "
                             f"{self._input_names}, got {len(inputs)}")
        arrays = []
        for i, a in enumerate(inputs):
            dt = self._in_dtypes[i] if self._in_dtypes else None
            arrays.append(np.asarray(a, dtype=dt))
        rows = {a.shape[0] for a in arrays if a.ndim >= 1}
        if len(rows) != 1 or any(a.ndim < 1 for a in arrays):
            raise ValueError(
                "every input must carry a shared leading batch dim; got "
                f"shapes {[a.shape for a in arrays]}")
        n = rows.pop()
        if self._rest_shapes is not None:
            for a, rest, name in zip(arrays, self._rest_shapes,
                                     self._input_names):
                if a.shape[1:] != rest:
                    raise ValueError(
                        f"input {name!r} has per-row shape "
                        f"{tuple(a.shape[1:])}, expected {rest}")
        if n < 1:
            raise ValueError("empty request (leading dim 0)")
        if n > self._max_batch:
            raise ValueError(
                f"request of {n} rows exceeds max_batch_size="
                f"{self._max_batch}; split it client-side")
        return arrays

    def infer(self, inputs, deadline_ms: Optional[float] = None) -> Future:
        """Enqueue one request; returns a Future resolving to the output
        list (host numpy arrays, leading dim = the request's rows).

        Raises :class:`QueueFull` (shed), :class:`EngineClosed`, or
        ``ValueError`` (malformed request) synchronously.
        """
        arrays = self._normalize(inputs)
        n = arrays[0].shape[0]
        fault.point("serving.enqueue", f"rows={n}")
        deadline = None
        dl_s = (float(deadline_ms) / 1000.0 if deadline_ms is not None
                else self._default_deadline)
        if dl_s is not None:
            deadline = time.monotonic() + dl_s
        req = _Request(arrays, n, deadline)
        with self._cv:
            if self._closing or self._closed or self._draining:
                raise EngineClosed("engine is draining or closed")
            if len(self._queue) >= self._max_queue:
                # dead slots must not shed live traffic: requests whose
                # deadline lapsed while the dispatcher was mid-batch
                # still sit in the queue until the next sweep — sweep
                # here (lock already held) before deciding to shed
                self._expire_locked()
            if len(self._queue) >= self._max_queue:
                self._c["shed"] += 1
                self._madd("shed")
                trc = obs_hook._tracer
                if trc is not None:
                    trc.emit("serving", "shed",
                             args=self._ev(rid=req.rid, rows=n))
                raise QueueFull(
                    f"queue full ({self._max_queue} requests); retry with "
                    f"backoff")
            self._queue.append(req)
            self._queued_rows += req.rows
            if req.deadline is not None:
                self._queued_deadlines += 1
            self._c["requests"] += 1
            self._madd("requests")
            self._cv.notify_all()
        trc = obs_hook._tracer
        if trc is not None:
            # the admitting thread's distributed trace context (bound by
            # the HTTP front-end) sticks to the request so the scheduler
            # thread's dispatch event can carry it too
            req.trace = trc.current_trace()
            trc.emit("serving", "enqueue",
                     args=self._ev(rid=req.rid, rows=n))
        return req.future

    def infer_sync(self, inputs, deadline_ms: Optional[float] = None,
                   timeout: Optional[float] = None):
        """Blocking :meth:`infer`; returns the output list."""
        return self.infer(inputs, deadline_ms=deadline_ms).result(timeout)

    # -- dispatcher --------------------------------------------------------
    def _expire_one_locked(self, r: _Request, now: float) -> None:
        self._queued_rows -= r.rows
        self._queued_deadlines -= 1
        self._c["deadline_expired"] += 1
        self._madd("deadline_expired")
        trc = obs_hook._tracer
        if trc is not None:
            trc.emit("serving", "deadline_expired",
                     args=self._ev(rid=r.rid,
                                   waited_ms=(now - r.t_enq) * 1000.0))
        _safe_set_exception(r.future, DeadlineExceeded(
            f"deadline expired after "
            f"{(now - r.t_enq) * 1000:.1f} ms in queue"))

    def _expire_locked(self) -> None:
        """Drop queued requests whose deadline has passed (they never
        occupy a batch slot).  Caller holds the lock.  O(1) when no
        queued request carries a deadline — the steady-state hot path."""
        if not self._queue or not self._queued_deadlines:
            return
        now = time.monotonic()
        alive = collections.deque()
        for r in self._queue:
            if r.deadline is not None and now > r.deadline:
                self._expire_one_locked(r, now)
            else:
                alive.append(r)
        self._queue = alive

    def _next_batch(self) -> Optional[List[_Request]]:
        """Block until a batch is ready; None when closed and drained."""
        with self._cv:
            while True:
                self._expire_locked()
                if self._closing and not self._queue:
                    return None
                if self._queue and not self._paused:
                    break
                # timed wait only to sweep in-queue deadlines the
                # dispatcher can't pop (paused); every other state
                # change (enqueue/resume/close) notifies — an idle
                # engine sleeps instead of polling at 20 Hz
                self._cv.wait(0.05 if self._queued_deadlines else None)
            # Wait for the batch to fill.  The budget runs from the
            # OLDEST request's enqueue, not from now: time a request
            # already waited while the previous batch executed counts,
            # so a saturated engine dispatches back-to-back with zero
            # idle wait and batch_timeout_ms bounds per-request queue
            # delay, not per-batch fill time.
            t_full = self._queue[0].t_enq + self._batch_timeout
            while not (self._closing or self._draining or self._paused):
                self._expire_locked()
                if not self._queue:     # everything expired: start over
                    return []
                if self._queued_rows >= self._max_batch:
                    break
                now = time.monotonic()
                t_full = self._queue[0].t_enq + self._batch_timeout
                if now >= t_full:
                    break
                self._cv.wait(min(t_full - now, 0.05))
            if self._paused:
                return []
            batch: List[_Request] = []
            rows = 0
            now = time.monotonic()
            while self._queue:
                r = self._queue[0]
                if r.deadline is not None and now > r.deadline:
                    self._queue.popleft()
                    self._expire_one_locked(r, now)
                    continue
                if rows + r.rows > self._max_batch:
                    break
                self._queue.popleft()
                self._queued_rows -= r.rows
                if r.deadline is not None:
                    self._queued_deadlines -= 1
                batch.append(r)
                rows += r.rows
            if batch:
                self._inflight = True
                self._inflight_reqs = batch
            return batch

    def _bucket_for(self, rows: int) -> int:
        for b in self._buckets:
            if b >= rows:
                return b
        return self._buckets[-1]

    def _execute(self, batch: List[_Request]) -> None:
        rows = sum(r.rows for r in batch)
        target = self._bucket_for(rows)
        feeds = []
        for i in range(len(self._input_names)):
            a = np.concatenate([r.arrays[i] for r in batch], axis=0)
            if target > rows:
                pad = np.zeros((target - rows,) + a.shape[1:],
                               dtype=a.dtype)
                a = np.concatenate([a, pad], axis=0)
            feeds.append(a)
        last_exc: Optional[BaseException] = None
        outs = None
        t_disp = time.perf_counter()
        for attempt in range(self._retries + 1):
            try:
                fault.point("serving.dispatch",
                            f"rows={rows}", f"attempt={attempt}")
                with self._pred_mu:
                    outs = self._pred.run(feeds)
                last_exc = None
                break
            except Exception as e:          # pure inference: retry whole
                last_exc = e                # batch on any dispatch fault
                self._c["dispatch_errors"] += 1
                self._madd("dispatch_errors")
                if attempt < self._retries:
                    self._c["dispatch_retries"] += 1
                    self._madd("dispatch_retries")
        t_done = time.perf_counter()
        # supervised liveness: one beat per dispatched batch (success OR
        # failure — the signal is "the dispatch loop makes progress",
        # not "requests succeed"); a single None-check when unsupervised
        hb = obs_hook._heartbeat
        if hb is not None:
            hb.beat(int(self._c["batches"]) + 1)
        exp = obs_hook._export
        if exp is not None:
            exp.tick()
        trc = obs_hook._tracer
        if trc is not None:
            # one typed event per coalesced dispatch, correlated to the
            # member requests by id (and to their distributed traces,
            # when the admitting threads carried any)
            traces = sorted({r.trace for r in batch if r.trace})
            trc.emit("serving", "dispatch", ts=t_disp,
                     dur=t_done - t_disp,
                     args=self._ev(rids=[r.rid for r in batch],
                                   rows=rows, bucket=target,
                                   attempts=attempt + 1,
                                   ok=last_exc is None,
                                   **({"traces": traces} if traces
                                      else {})))
        if last_exc is not None:
            for r in batch:
                _safe_set_exception(r.future, last_exc)
            self._c["failed"] += len(batch)
            self._madd("failed", len(batch))
            return
        host = [np.asarray(o) for o in outs]    # one device sync per batch
        # perf observatory: per-engine dispatch anatomy + the device-
        # memory sampler cadence (one None-check when off).  Measured
        # AFTER the host sync above — predictor outputs are async jax
        # arrays, so a pre-sync stamp would time the dispatch submit
        # (~0) instead of the batch's actual device wall
        p = obs_hook._perf
        if p is not None:
            p.serving_step(self.name, "dispatch",
                           time.perf_counter() - t_disp)
        mask = self._out_mask
        batched = [h.ndim >= 1
                   and (mask[j] if mask is not None and j < len(mask)
                        else h.shape[0] == target)
                   for j, h in enumerate(host)]
        now = time.monotonic()
        off = 0
        for r in batch:
            # every request gets its OWN arrays (incl. non-batched
            # outputs): resolved futures must never alias each other
            res = [h[off:off + r.rows].copy() if b else h.copy()
                   for h, b in zip(host, batched)]
            off += r.rows
            _safe_set_result(r.future, res)
            lat_ms = (now - r.t_enq) * 1000.0
            self._reg.observe("latency_ms", lat_ms)
            self._mobs("latency_ms", lat_ms)
        with self._cv:      # stats() snapshots under this lock; keep
            self._c["responses"] += len(batch)   # its view consistent
            self._c["batches"] += 1
            self._c["rows"] += rows
            self._c["padded_rows"] += target - rows
            self._occ_sum += rows / target
        self._madd("batches")
        self._madd("rows", rows)
        self._madd("padded_rows", target - rows)
        self._mobs("batch_occupancy", rows / target)
        self._mobs("requests_per_batch", len(batch))

    def _dispatch_loop(self) -> None:
        while True:
            batch = self._next_batch()
            if batch is None:
                return
            if not batch:           # paused, or everything expired
                continue
            try:
                self._execute(batch)
            except Exception as e:  # defense in depth: the dispatcher
                # thread must survive ANYTHING (a dead dispatcher
                # strands every future); fail the batch cleanly instead
                for r in batch:
                    _safe_set_exception(r.future, e)
                self._c["failed"] += len(batch)
                self._madd("failed", len(batch))
            finally:
                with self._cv:
                    self._inflight = False
                    self._inflight_reqs = []
                    self._cv.notify_all()

    # -- warmup / lifecycle ------------------------------------------------
    def _bucket_feeds(self, rest_shapes: Optional[Sequence[Sequence[int]]]):
        """Yield ``(bucket, feeds)`` zero-feeds for every bucket —
        shared by :meth:`warmup` (this engine's predictor, under the
        engine lock) and :meth:`prewarm_predictor` (a replacement
        predictor, no lock needed: it is not serving yet)."""
        if rest_shapes is None:
            if self._in_shapes is None:
                raise ValueError("artifact metadata lacks input shapes; "
                                 "pass rest_shapes=[shape_without_batch,...]")
            try:
                rest_shapes = [tuple(int(d) for d in s[1:])
                               for s in self._in_shapes]
            except (TypeError, ValueError):
                raise ValueError(
                    "artifact has symbolic non-batch dims; pass concrete "
                    "rest_shapes=[shape_without_batch, ...]") from None
        dtypes = self._in_dtypes or [np.float32] * len(self._input_names)
        for b in self._buckets:
            yield b, [np.zeros((b,) + tuple(rs), dtype=dt)
                      for rs, dt in zip(rest_shapes, dtypes)]

    def warmup(self, rest_shapes: Optional[Sequence[Sequence[int]]] = None
               ) -> int:
        """AOT-compile every bucket so the serve path never compiles.

        ``rest_shapes`` — per-input shapes *minus* the batch dim; derived
        from the artifact metadata when its non-batch dims are static.
        Returns the number of compiled variants after warmup (the
        baseline for ``recompiles_after_warmup``)."""
        out_shapes = {}
        with self._pred_mu:
            for b, feeds in self._bucket_feeds(rest_shapes):
                outs = self._pred.run(feeds)
                out_shapes[b] = [tuple(np.shape(o)) for o in outs]
        if len(out_shapes) >= 2:
            # observed ground truth: an output carries the batch dim iff
            # its leading dim tracked the bucket size across warmup runs
            # (beats any shape-coincidence heuristic at serve time)
            n_out = min(len(s) for s in out_shapes.values())
            self._out_mask = [
                all(len(s[j]) >= 1 and s[j][0] == b
                    for b, s in out_shapes.items())
                for j in range(n_out)]
        self._warm_variants = self._pred.num_compiled_variants()
        return self._warm_variants

    # -- zero-downtime weight hot swap -------------------------------------
    def prewarm_predictor(self, pred,
                          rest_shapes: Optional[Sequence[Sequence[int]]]
                          = None) -> int:
        """Warm a *replacement* predictor on every bucket WITHOUT
        touching the serving one — runs entirely off the dispatch path
        (no engine lock: ``pred`` has no other caller yet), so a hot
        swap commits an already-compiled predictor and the serve path
        never compiles.  Returns its compiled-variant count.

        Raises if the replacement disagrees with this engine's input
        signature (names / per-row shapes / dtypes) — the pre-commit
        rejection path for a mismatched artifact."""
        names = list(pred.get_input_names())
        if names != self._input_names:
            raise ValueError(
                f"replacement artifact has inputs {names}, engine serves "
                f"{self._input_names}")
        for b, feeds in self._bucket_feeds(rest_shapes):
            pred.run(feeds)
        return pred.num_compiled_variants()

    def swap_predictor(self, pred, version: int):
        """Commit a prepared (loaded + digest-verified + prewarmed)
        predictor as the serving weights.  The commit is one pointer
        write under the engine's predictor lock — the batch boundary:
        an in-flight batch finishes on the old weights, the next batch
        runs on the new ones, nothing drains and nothing recompiles
        (``prewarm_predictor`` already compiled every bucket).

        Returns the replaced predictor — the caller's rollback handle
        (swap it back if a later stage of a multi-engine swap fails).
        """
        with self._cv:
            if self._closing or self._closed:
                raise EngineClosed("engine is draining or closed")
        with self._pred_mu:
            old = self._pred
            self._pred = pred
            # the new predictor's variants are the new warm baseline —
            # recompiles_after_warmup stays 0 across a clean swap
            self._warm_variants = pred.num_compiled_variants()
            self._weights_version = int(version)
        with self._cv:
            self._c["weight_swaps"] += 1
        self._madd("weight_swaps")
        trc = obs_hook._tracer
        if trc is not None:
            trc.emit("serving", "weights_swap",
                     args=self._ev(version=int(version)))
        return old

    @property
    def weights_version(self) -> int:
        return self._weights_version

    def pause(self) -> None:
        """Testing hook: hold the dispatcher (no new batch starts)."""
        with self._cv:
            self._paused = True
            self._cv.notify_all()

    def resume(self) -> None:
        with self._cv:
            self._paused = False
            self._cv.notify_all()

    def drain(self, timeout: Optional[float] = None) -> bool:
        """Stop admission, finish everything queued and in flight.
        Returns True when fully drained within ``timeout``."""
        deadline = (time.monotonic() + timeout) if timeout is not None \
            else None
        with self._cv:
            self._draining = True
            self._paused = False    # a paused engine could never empty
            self._cv.notify_all()
            while self._queue or self._inflight:
                wait = 0.05
                if deadline is not None:
                    wait = min(wait, deadline - time.monotonic())
                    if wait <= 0:
                        return False
                self._cv.wait(wait)
        return True

    def close(self, timeout: float = 10.0) -> None:
        """Graceful shutdown: drain, stop the dispatcher, and fail any
        request that could not be served — no future is ever stranded.

        ``timeout`` is a hard deadline on the whole method, measured
        from entry: time spent contending for the engine lock counts
        against the dispatcher join, so a dispatcher wedged in a
        faulted dispatch (e.g. the ``serving.dispatch`` fault point
        with ``action=sleep``) can never hold ``close`` past its
        budget — the wedged batch's futures are failed and the thread
        is abandoned (it is a daemon and exits on its next state
        check)."""
        deadline = time.monotonic() + max(0.0, float(timeout))
        with self._cv:
            if self._closed:
                return
            self._draining = True
            self._closing = True
            self._paused = False        # a paused engine must still close
            self._cv.notify_all()
        self._thread.join(max(0.0, deadline - time.monotonic()))
        with self._cv:
            self._closed = True
            # only on join timeout / wedged dispatcher: fail everything
            # still queued AND the popped in-flight batch — a future must
            # never be stranded, even when the predictor hangs
            stranded = list(self._queue)
            self._queue.clear()
            self._queued_rows = 0
            self._queued_deadlines = 0
            if self._thread.is_alive():
                stranded += [r for r in self._inflight_reqs
                             if not r.future.done()]
            for r in stranded:
                _safe_set_exception(r.future, EngineClosed(
                    "engine closed before the request was served"))
            if stranded:
                self._c["closed_stranded"] += len(stranded)
            self._cv.notify_all()
        if stranded:
            self._madd("closed_stranded", len(stranded))

    def __enter__(self):
        return self

    def __exit__(self, *exc_info):
        self.close()
        return False

    # -- observability -----------------------------------------------------
    @property
    def buckets(self) -> List[int]:
        return list(self._buckets)

    def stats(self) -> Dict[str, object]:
        """Engine state + counters + latency percentiles (the payload
        behind the HTTP ``/metrics`` endpoint)."""
        with self._cv:
            state = ("closed" if self._closed else
                     "draining" if self._draining else
                     "paused" if self._paused else "running")
            c = dict(self._c)
            queue_depth = len(self._queue)
            queued_rows = self._queued_rows
            inflight = self._inflight
            occ_sum = self._occ_sum
        batches = c.get("batches", 0)
        rows = c.get("rows", 0)
        padded = c.get("padded_rows", 0)
        variants = self._pred.num_compiled_variants()
        return {
            "state": state,
            "engine": self.name,
            "queue_depth": queue_depth,
            "queued_rows": queued_rows,
            "inflight": inflight,
            "max_batch_size": self._max_batch,
            "max_queue": self._max_queue,
            "batch_timeout_ms": self._batch_timeout * 1000.0,
            "buckets": list(self._buckets),
            "counters": {k: c.get(k, 0) for k in (
                "requests", "responses", "batches", "rows", "padded_rows",
                "shed", "deadline_expired", "failed", "dispatch_errors",
                "dispatch_retries", "weight_swaps", "closed_stranded")},
            "weights_version": self._weights_version,
            "mean_batch_occupancy": (occ_sum / batches) if batches else 0.0,
            "padding_waste": (padded / (rows + padded))
            if (rows + padded) else 0.0,
            "requests_per_batch": (c.get("responses", 0) / batches)
            if batches else 0.0,
            "latency_ms": self._reg.histogram_summary("latency_ms"),
            "compiled_variants": variants,
            "warm_variants": self._warm_variants,
            "recompiles_after_warmup": (
                variants - self._warm_variants
                if self._warm_variants is not None else None),
        }
