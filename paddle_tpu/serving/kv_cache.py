"""Paged KV cache: a preallocated page pool + per-sequence page tables.

The TPU-native answer to ragged generative sequence lengths (Ragged
Paged Attention, PAPERS.md): instead of one contiguous KV buffer per
sequence (whose shape changes as the sequence grows, retracing XLA), the
cache is a single preallocated pool of fixed-size pages

    k_pool/v_pool: [num_layers, 1 + num_pages, page_size, heads, dim]

and every sequence owns an int32 *page table* mapping its logical pages
to physical pool slots.  All shapes are static, so one compiled decode
step serves any mix of sequence lengths; growing a sequence means
appending a page index to its table — data changes, shapes never do.

Physical page 0 is the **scratch page**: it is never allocated, and
idle decode slots point their whole table at it, so the static-shape
scatter of new K/V (which always writes every slot's row) lands
harmlessly there instead of corrupting a live sequence's pages.

Allocation is host-side and O(1) amortized (a LIFO free list).  The
:class:`~paddle_tpu.serving.generation.GenerationEngine` reserves a
sequence's worst-case page count at admission, which makes mid-flight
pool exhaustion impossible by construction — accounting invariants
(``in_use + available == num_pages``, pool drained back to zero) are
what the chaos/smoke gates assert.
"""
from __future__ import annotations

import math
from typing import List, Optional, Sequence, Tuple

import jax.numpy as jnp

__all__ = ["PagePool", "KVCacheConfig", "write_token", "write_prompt",
           "pages_needed"]


class KVCacheConfig:
    """Static geometry of a paged KV cache."""

    __slots__ = ("num_layers", "num_kv_heads", "head_dim", "page_size",
                 "num_pages", "max_context", "dtype")

    def __init__(self, num_layers: int, num_kv_heads: int, head_dim: int,
                 page_size: int = 16, num_pages: int = 256,
                 max_context: int = 512, dtype=jnp.float32):
        if page_size < 1 or num_pages < 1:
            raise ValueError("page_size and num_pages must be >= 1")
        if max_context < 1:
            raise ValueError("max_context must be >= 1")
        self.num_layers = int(num_layers)
        self.num_kv_heads = int(num_kv_heads)
        self.head_dim = int(head_dim)
        self.page_size = int(page_size)
        self.num_pages = int(num_pages)
        self.max_context = int(max_context)
        self.dtype = dtype

    @property
    def pages_per_seq(self) -> int:
        """Page-table width: logical pages covering ``max_context``."""
        return -(-self.max_context // self.page_size)

    def pages_for(self, tokens: int) -> int:
        """Physical pages a sequence of ``tokens`` total tokens needs."""
        return max(1, -(-int(tokens) // self.page_size))

    def to_dict(self) -> dict:
        return {s: (str(self.dtype) if s == "dtype" else getattr(self, s))
                for s in self.__slots__}


class PagePool:
    """Device-resident K/V page pool + host-side free-list allocator.

    The device arrays (``kv = (k_pool, v_pool)``) are *owned by the
    caller's compiled step* — the pool object only hands out/reclaims
    page indices and tracks accounting.  ``kv`` is threaded functionally
    through jitted prefill/decode calls; :meth:`reset_kv` rebuilds the
    zero state (tests / engine restart)."""

    def __init__(self, config: KVCacheConfig):
        self.config = config
        c = config
        # +1: physical page 0 is the never-allocated scratch page
        self._shape = (c.num_layers, 1 + c.num_pages, c.page_size,
                       c.num_kv_heads, c.head_dim)
        # LIFO free list: hottest (most recently freed) pages reused
        # first, which keeps the working set of a churning slot compact
        self._free: List[int] = list(range(c.num_pages, 0, -1))
        self._in_use = 0
        self.kv: Tuple[jnp.ndarray, jnp.ndarray] = self.reset_kv()

    def reset_kv(self) -> Tuple[jnp.ndarray, jnp.ndarray]:
        self.kv = (jnp.zeros(self._shape, self.config.dtype),
                   jnp.zeros(self._shape, self.config.dtype))
        return self.kv

    # -- accounting --------------------------------------------------------
    @property
    def num_pages(self) -> int:
        return self.config.num_pages

    @property
    def available(self) -> int:
        return len(self._free)

    @property
    def in_use(self) -> int:
        return self._in_use

    def utilization(self) -> float:
        return self._in_use / self.config.num_pages

    # -- allocation --------------------------------------------------------
    def alloc(self, n: int) -> Optional[List[int]]:
        """Take ``n`` pages, or None (and take nothing) if short — an
        all-or-nothing grant so admission can never half-reserve."""
        n = int(n)
        if n < 0:
            raise ValueError("alloc(n) needs n >= 0")
        if n > len(self._free):
            return None
        pages = [self._free.pop() for _ in range(n)]
        self._in_use += n
        return pages

    def free(self, pages: Sequence[int]) -> None:
        for p in pages:
            p = int(p)
            if p < 1 or p > self.config.num_pages:
                raise ValueError(f"page {p} is not an allocatable index")
            if p in self._free:
                raise ValueError(f"double free of page {p}")
        self._free.extend(int(p) for p in pages)
        self._in_use -= len(pages)
        assert self._in_use >= 0, "page accounting went negative"


def write_token(pool, layer, vals, page_table, positions):
    """Scatter one new K (or V) row per sequence into its page.

    pool: [L, N, page, H, D]; layer: int; vals: [S, H, D]; page_table:
    [S, P] int32; positions: [S] int32 (0-based logical position being
    written).  Idle slots' tables point at scratch page 0, so the
    unconditional static-shape scatter stays safe.  Returns the updated
    pool.

    The layer index rides INSIDE the scatter (one fused
    ``pool.at[layer, pid, off]`` update of S rows) — slicing the layer
    out and writing it back would round-trip the whole layer through
    memory on every step, which is exactly the copy traffic the paged
    layout exists to avoid (donated pools update in place)."""
    page = pool.shape[2]
    logical = positions // page                       # [S]
    pid = jnp.take_along_axis(page_table, logical[:, None], axis=1)[:, 0]
    off = positions % page
    return pool.at[layer, pid, off].set(vals)


def write_prompt(pool, layer, vals, page_table, length):
    """Scatter a whole prompt's K (or V) rows for ONE sequence.

    pool: [L, N, page, H, D]; layer: int; vals: [T, H, D] (rows past
    ``length`` are padding); page_table: [P] int32; length: int32
    scalar.  Padding rows are redirected to scratch page 0.  Returns
    the updated pool (one fused scatter — see :func:`write_token`)."""
    T = vals.shape[0]
    page = pool.shape[2]
    pos = jnp.arange(T, dtype=jnp.int32)
    pid = page_table[pos // page]
    pid = jnp.where(pos < length, pid, 0)             # pad -> scratch
    off = pos % page
    return pool.at[layer, pid, off].set(vals)


def pages_needed(prompt_len: int, max_new_tokens: int,
                 page_size: int) -> int:
    """Worst-case pages a request can touch (admission reservation)."""
    total = int(prompt_len) + int(max_new_tokens)
    return max(1, math.ceil(total / int(page_size)))
