"""paddle_tpu.serving — a concurrent dynamic-batching inference engine.

The reference ships a standalone inference stack
(paddle/fluid/inference/api/analysis_predictor.h) that serves one caller
per Predictor.  This subsystem turns the AOT
:class:`~paddle_tpu.inference.Predictor` into a production-shaped
engine (ROADMAP north star: "serve heavy traffic from millions of
users"):

- :class:`InferenceEngine` — bounded request queue, a dispatcher thread
  that coalesces waiting requests into micro-batches padded to a small
  set of precompiled bucket sizes (zero recompiles after warmup),
  futures-based API, queue-full load shedding, per-request in-queue
  deadlines, graceful ``drain()``/``close()``.
- :class:`GenerationEngine` — token-level continuous batching for
  generative traffic over a paged KV cache
  (:mod:`paddle_tpu.serving.kv_cache`): prefill/decode phase split,
  admission into free decode slots between steps, eviction + page
  reclamation on finish/expiry, streaming token futures
  (:class:`GenerationStream`), zero steady-state recompiles.
  :class:`PagedDecoderLM` is the reference model for the paged decode
  contract.
- :mod:`paddle_tpu.serving.http` — stdlib ``ThreadingHTTPServer``
  front-end (``/predict``, ``/generate`` with chunked token streaming,
  ``/healthz`` with a liveness/readiness split, ``/metrics``) plus a
  keep-alive client helper that rides through supervised replica
  restarts; ``tools/serve.py`` is the CLI entry point.
- :mod:`paddle_tpu.serving.hotswap` — zero-downtime weight hot swap:
  :func:`publish_weights` packages serving payloads into a
  digest-verified :class:`~paddle_tpu.utils.checkpoint.SnapshotStore`
  snapshot; :class:`WeightWatcher` polls the store and commits new
  weights into live engines at batch/step boundaries with zero
  recompiles and no drain (corrupt snapshots rejected, partial
  multi-engine applies rolled back).
- :mod:`paddle_tpu.serving.registry` — the multi-model control plane:
  :class:`ModelRegistry` loads/unloads/aliases models at runtime (each
  with its own engine(s) + watcher), routes requests by name with
  weighted fair queuing across models and per-tenant quotas, and
  :class:`ElasticityController` turns SLO burn rates into per-model
  replica scaling (:class:`ReplicaSet`) and shed decisions.
"""
from .engine import (DeadlineExceeded, EngineClosed,  # noqa: F401
                     InferenceEngine, QueueFull, ServingError)
from .generation import (GenerationEngine, GenerationError,  # noqa: F401
                         GenerationStream)
from .hotswap import WeightWatcher, publish_weights  # noqa: F401
from .kv_cache import KVCacheConfig, PagePool  # noqa: F401
from .models import PagedDecoderLM  # noqa: F401
from .registry import (ElasticityController, ModelEntry,  # noqa: F401
                       ModelRegistry, QuotaExceeded, ReplicaSet,
                       UnknownModel)
from .http import Client, ServingServer  # noqa: F401

__all__ = ["InferenceEngine", "ServingError", "QueueFull",
           "DeadlineExceeded", "EngineClosed", "ServingServer", "Client",
           "GenerationEngine", "GenerationError", "GenerationStream",
           "KVCacheConfig", "PagePool", "PagedDecoderLM",
           "WeightWatcher", "publish_weights",
           "ModelRegistry", "ModelEntry", "UnknownModel",
           "QuotaExceeded", "ElasticityController", "ReplicaSet"]
