"""paddle_tpu.serving — a concurrent dynamic-batching inference engine.

The reference ships a standalone inference stack
(paddle/fluid/inference/api/analysis_predictor.h) that serves one caller
per Predictor.  This subsystem turns the AOT
:class:`~paddle_tpu.inference.Predictor` into a production-shaped
engine (ROADMAP north star: "serve heavy traffic from millions of
users"):

- :class:`InferenceEngine` — bounded request queue, a dispatcher thread
  that coalesces waiting requests into micro-batches padded to a small
  set of precompiled bucket sizes (zero recompiles after warmup),
  futures-based API, queue-full load shedding, per-request in-queue
  deadlines, graceful ``drain()``/``close()``.
- :mod:`paddle_tpu.serving.http` — stdlib ``ThreadingHTTPServer``
  front-end (``/predict``, ``/healthz``, ``/metrics``) plus a tiny
  client helper; ``tools/serve.py`` is the CLI entry point.
"""
from .engine import (DeadlineExceeded, EngineClosed,  # noqa: F401
                     InferenceEngine, QueueFull, ServingError)
from .http import Client, ServingServer  # noqa: F401

__all__ = ["InferenceEngine", "ServingError", "QueueFull",
           "DeadlineExceeded", "EngineClosed", "ServingServer", "Client"]
