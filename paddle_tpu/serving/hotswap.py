"""Zero-downtime weight hot swap: SnapshotStore -> serving engines.

The producer side already exists: training (or an offline exporter)
publishes digest-verified snapshots through
:class:`~paddle_tpu.utils.checkpoint.SnapshotStore` — the PR-12 async
step-cadence publisher.  This module is the consumer side:

- :func:`publish_weights` packages serving payloads into a store
  snapshot: ``serving_artifact`` (the ``jit.save`` artifact bytes —
  the :class:`~paddle_tpu.inference.Predictor` bakes weights into the
  StableHLO at export, so new inference weights ARE a new artifact)
  and/or ``serving_params`` (a flat name->array dict for
  :meth:`GenerationEngine.swap_weights`).
- :class:`WeightWatcher` polls ``store.latest_snapshot()`` (one meta
  read — no payload I/O) and, on a new version: loads +
  sha256-verifies the payloads, builds and prewarms a replacement
  predictor, uploads generation params — ALL off the dispatch thread —
  then commits both engines at their batch/step boundaries.  In-flight
  work finishes on the old weights; nothing drains, nothing recompiles.

Failure semantics (the chaos gate):

- a corrupt or partial snapshot is **rejected** before anything is
  applied (``serving.swap.rejected``) and pinned so it is not retried;
- a failure applying to the second engine after the first committed
  **rolls back** the first (``serving.swap.rolled_back``) — the
  replica never serves two versions across engines;
- a clean commit counts ``serving.swap.applied`` and advances
  ``weights_version`` everywhere it is surfaced (``/healthz``, engine
  stats, compile records, Prometheus).
"""
from __future__ import annotations

import os
import shutil
import tempfile
import threading
import warnings
from typing import Dict, Optional, Sequence

import numpy as np

from ..core import obs_hook
from ..utils import monitor

__all__ = ["WeightWatcher", "publish_weights",
           "ARTIFACT_PAYLOAD", "PARAMS_PAYLOAD"]

ARTIFACT_PAYLOAD = "serving_artifact"   # jit.save bytes (uint8 arrays)
PARAMS_PAYLOAD = "serving_params"       # flat name -> array dict


class _StateDict:
    """Adapter: a plain dict as a SnapshotStore-savable object (the
    store's encode path requires ``state_dict()``)."""

    def __init__(self, d: Dict[str, object]):
        self._d = dict(d)

    def state_dict(self) -> Dict[str, object]:
        return self._d


def _read_bytes(path: str) -> np.ndarray:
    with open(path, "rb") as f:
        return np.frombuffer(f.read(), dtype=np.uint8)


def publish_weights(store, version: int,
                    artifact_prefix: Optional[str] = None,
                    params: Optional[Dict[str, object]] = None,
                    extra_suffixes: Sequence[str] = (".pdiparams",)
                    ) -> dict:
    """Publish one serving-weights snapshot (synchronous, digested).

    ``artifact_prefix`` — a ``jit.save`` output prefix; its
    ``.pdmodel`` bytes (plus any ``extra_suffixes`` sidecars that
    exist) ride the snapshot as uint8 arrays under
    ``serving_artifact``.  ``params`` — a flat name->array dict under
    ``serving_params``.  Returns the published meta entry."""
    objects = {}
    if artifact_prefix is not None:
        blobs = {"pdmodel": _read_bytes(artifact_prefix + ".pdmodel")}
        for suf in extra_suffixes:
            p = artifact_prefix + suf
            if os.path.exists(p):
                blobs[suf.lstrip(".")] = _read_bytes(p)
        objects[ARTIFACT_PAYLOAD] = _StateDict(blobs)
    if params is not None:
        objects[PARAMS_PAYLOAD] = _StateDict(
            {k: np.asarray(v) for k, v in params.items()})
    if not objects:
        raise ValueError("publish_weights needs an artifact_prefix "
                         "and/or params")
    store.save(0, objects, step=int(version), kind="step")
    return store.latest_snapshot()


class WeightWatcher:
    """Polls a :class:`SnapshotStore` and hot-swaps serving weights.

    Args:
        store: the snapshot store to watch (or its directory path).
        engine: an :class:`InferenceEngine` fed by ``serving_artifact``
            payloads (may be None).
        generation: a :class:`GenerationEngine` fed by
            ``serving_params`` payloads (may be None).
        poll_s: meta-poll cadence of the background thread.
        rest_shapes: forwarded to
            :meth:`InferenceEngine.prewarm_predictor` when the artifact
            metadata lacks static shapes.

    Use :meth:`start`/:meth:`stop` for the background loop, or call
    :meth:`check_once` directly for deterministic (test) driving —
    both run the entire load/verify/build/prewarm pipeline on the
    calling/watcher thread, never on an engine's dispatch thread.
    """

    def __init__(self, store, engine=None, generation=None,
                 poll_s: float = 1.0,
                 rest_shapes: Optional[Sequence[Sequence[int]]] = None):
        if isinstance(store, str):
            from ..utils.checkpoint import SnapshotStore
            store = SnapshotStore(store)
        if engine is None and generation is None:
            raise ValueError("WeightWatcher needs at least one engine")
        self.store = store
        self.engine = engine
        self.generation = generation
        self.poll_s = float(poll_s)
        self._rest_shapes = rest_shapes
        self.version = 0                    # last applied
        self.last_rejected: Optional[int] = None
        self.last_error: Optional[str] = None
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    # -- lifecycle ---------------------------------------------------------
    def start(self) -> "WeightWatcher":
        if self._thread is None or not self._thread.is_alive():
            self._stop.clear()
            self._thread = threading.Thread(
                target=self._loop, name="weight-watcher", daemon=True)
            self._thread.start()
        return self

    def stop(self, timeout: float = 10.0) -> None:
        self._stop.set()
        t = self._thread
        if t is not None:
            t.join(timeout)

    def _loop(self) -> None:
        while not self._stop.wait(self.poll_s):
            try:
                self.check_once()
            except Exception as e:      # a broken store must not kill
                self.last_error = f"{type(e).__name__}: {e}"
                monitor.stat_add("serving.swap.errors")

    # -- the swap pipeline -------------------------------------------------
    def _emit(self, name: str, **args) -> None:
        trc = obs_hook._tracer
        if trc is not None:
            trc.emit("serving", name, args=args)

    def _reject(self, version: int, why: str) -> None:
        self.last_rejected = version
        self.last_error = why
        monitor.stat_add("serving.swap.rejected")
        self._emit("swap_rejected", version=version, why=why)
        warnings.warn(f"weight swap of version {version} rejected: "
                      f"{why}; still serving version {self.version}")

    def _build_predictor(self, blobs: Dict[str, object]):
        """Artifact bytes -> a loaded, bucket-prewarmed Predictor (not
        yet serving — prewarm compiles every bucket so the later commit
        recompiles nothing)."""
        from ..inference import Config, create_predictor
        tmp = tempfile.mkdtemp(prefix="hotswap_")
        try:
            for name, arr in blobs.items():
                suffix = "pdmodel" if name == "pdmodel" else name
                with open(os.path.join(tmp, f"model.{suffix}"), "wb") \
                        as f:
                    f.write(np.asarray(arr, dtype=np.uint8).tobytes())
            pred = create_predictor(Config(os.path.join(tmp, "model")))
            self.engine.prewarm_predictor(pred, self._rest_shapes)
            return pred
        finally:
            # the artifact is fully resident after load; the temp files
            # are only a transport format
            shutil.rmtree(tmp, ignore_errors=True)

    def check_once(self) -> Optional[int]:
        """One poll: returns the newly applied version, or None (no new
        snapshot / rejected).  Safe to call concurrently with traffic —
        everything heavy happens off the dispatch threads."""
        snap = self.store.latest_snapshot()
        if snap is None:
            return None
        version = int(snap.get("step") or snap.get("epoch") or 0)
        if version <= self.version or version == self.last_rejected:
            return None
        digests = snap.get("digests") or {}
        wanted = []
        if self.engine is not None \
                and f"{ARTIFACT_PAYLOAD}.pdparams" in digests:
            wanted.append(ARTIFACT_PAYLOAD)
        if self.generation is not None \
                and f"{PARAMS_PAYLOAD}.pdparams" in digests:
            wanted.append(PARAMS_PAYLOAD)
        if not wanted:      # not a serving snapshot (e.g. a training
            return None     # checkpoint sharing the store): skip quietly
        expected = [ARTIFACT_PAYLOAD] * (self.engine is not None) \
            + [PARAMS_PAYLOAD] * (self.generation is not None)
        if wanted != expected:
            self._reject(version,
                         f"partial snapshot: has {wanted}, replica "
                         f"serves engines needing {expected}")
            return None
        payloads = self.store.load_payloads(wanted, snap)
        if payloads is None:    # digest mismatch / missing / undecodable
            self._reject(version, "payload failed digest verification")
            return None

        # build + prewarm everything BEFORE committing anything
        pred = None
        if self.engine is not None:
            try:
                pred = self._build_predictor(payloads[ARTIFACT_PAYLOAD])
            except Exception as e:
                self._reject(version, f"artifact rejected: "
                             f"{type(e).__name__}: {e}")
                return None

        old_pred = old_version = None
        if pred is not None:
            old_version = self.engine.weights_version
            old_pred = self.engine.swap_predictor(pred, version)
        if self.generation is not None:
            try:
                self.generation.swap_weights(
                    payloads[PARAMS_PAYLOAD], version)
            except Exception as e:
                if old_pred is not None:
                    # the replica must never serve two versions: undo
                    # the inference commit (the old predictor is still
                    # warm — this swap also recompiles nothing)
                    self.engine.swap_predictor(old_pred, old_version)
                    monitor.stat_add("serving.swap.rolled_back")
                    self._emit("swap_rolled_back", version=version,
                               restored=old_version)
                self._reject(version, f"generation apply failed: "
                             f"{type(e).__name__}: {e}")
                return None
        self.version = version
        self.last_error = None
        monitor.stat_add("serving.swap.applied")
        self._emit("swap_applied", version=version)
        return version
