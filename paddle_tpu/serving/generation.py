"""Continuous batching: a token-level scheduler over a paged KV cache.

:class:`GenerationEngine` is the generative counterpart of
:class:`~paddle_tpu.serving.engine.InferenceEngine` (ROADMAP item 3,
"LLM serving at user scale").  Where the inference engine coalesces
whole fixed-shape requests, generation is scheduled *per token*:

- **Prefill/decode phase split.**  An admitted request's prompt is run
  once through a bucket-compiled ``prefill`` (dense causal attention,
  K/V scattered into freshly reserved pages) producing its first token;
  afterwards the sequence lives in a *slot* of the in-flight decode
  batch, where ONE compiled ``decode`` step of static shape
  ``[num_slots]`` advances every active sequence a token at a time over
  the paged cache (:mod:`paddle_tpu.serving.kv_cache`).
- **Continuous batching.**  The scheduler admits queued requests into
  free slots *between decode steps* and evicts finished / expired
  sequences the moment they end, freeing their pages — decode slots are
  recycled mid-flight, never waiting for a whole batch to finish.
- **Static shapes, zero steady-state recompiles.**  Every compiled
  entry point is AOT-lowered (``jit(...).lower(...).compile()``) at
  :meth:`warmup`; the serve path only ever *calls* precompiled
  executables, so ``recompiles_after_warmup`` is structurally zero.
  Raggedness lives in page tables and length vectors, not in shapes.
- **Context-width bucketing.**  The reference paged-attention gather
  is O(page-table width); compiling one decode variant per power-of-two
  table width and picking the narrowest that covers the longest
  *active* sequence keeps the step O(live context), not O(engine max
  context) — a dense per-request cache must pay worst-case provisioning
  on every token (the raggedness tax the paged layout removes; the
  Pallas ragged kernel tier will remove the remaining bucket padding).
- **Determinism.**  A sequence's tokens depend only on its own prompt,
  seed, and temperature: per-row computation is independent of batch
  composition, page placement is invisible through the page table, and
  sampling keys are derived from (seed, position) — so continuous
  batching is bitwise-reproducible regardless of admission order (the
  chaos gate asserts this).

Robustness mirrors the inference engine: bounded queue with
:class:`~paddle_tpu.serving.engine.QueueFull` shedding, in-queue AND
mid-generation deadlines (:class:`DeadlineExceeded` evicts a decoding
sequence and frees its pages), decode-step retries around
``fault.point("serving.decode_step")`` (the step is functional over the
pool — injected flakes fire before dispatch, so a retry is safe), and
``drain()``/``close()`` that never strand a future or leak a page.

**Weights as arguments, not constants.**  The model parameters ride
every compiled entry point as its FIRST argument (a real device-array
pytree) instead of being closure-captured and baked into the HLO as
constants.  That one signature choice is what makes the zero-downtime
weight hot swap (:meth:`GenerationEngine.swap_weights`) a pure pointer
replacement: new arrays of identical shape/dtype slot into the already
compiled executables with ZERO recompiles, committed by the scheduler
between decode steps so every sequence's next token comes from exactly
one weights version.  When no swap is pending the steady-state cost is
a single attribute check at the top of the scheduler loop.
"""
from __future__ import annotations

import collections
import hashlib
import queue
import threading
import time
from concurrent.futures import Future
from typing import Dict, List, Optional, Sequence, Union

import jax
import jax.numpy as jnp
import numpy as np

from ..core import flags, obs_hook
from ..testing import fault
from ..utils import monitor
from .engine import (DeadlineExceeded, EngineClosed, QueueFull,
                     ServingError, _REQUEST_IDS, _mirrored_add,
                     _mirrored_observe, _safe_set_exception,
                     _safe_set_result)
from .kv_cache import KVCacheConfig, PagePool, pages_needed

__all__ = ["GenerationEngine", "GenerationStream", "GenerationError"]


class GenerationError(ServingError):
    """A sequence failed mid-generation (decode retries exhausted)."""


_DONE = object()        # stream sentinel: clean end of tokens


class GenerationStream:
    """Handle for one generation request.

    Tokens arrive incrementally via iteration (:meth:`__iter__` /
    :meth:`tokens`); the full list lands on :attr:`future` when the
    sequence finishes.  Errors (deadline, shed at eviction, decode
    failure) raise from both the iterator and ``result()``."""

    def __init__(self, sid: int):
        self.sid = sid
        self.future: Future = Future()
        self._q: "queue.Queue" = queue.Queue()
        self.finish_reason: Optional[str] = None    # "eos"|"length"|...

    def __iter__(self):
        return self.tokens()

    def tokens(self, timeout: Optional[float] = None):
        """Yield token ids as the scheduler produces them."""
        while True:
            item = self._q.get(timeout=timeout)
            if item is _DONE:
                return
            if isinstance(item, BaseException):
                raise item
            yield item

    def result(self, timeout: Optional[float] = None) -> List[int]:
        """Block for the complete token list."""
        return self.future.result(timeout)

    # scheduler-side helpers ------------------------------------------------
    def _push(self, tok: int) -> None:
        self._q.put(tok)

    def _finish(self, tokens: List[int], reason: str) -> None:
        # resolve the future BEFORE the queue sentinel: a consumer that
        # drains tokens() and immediately calls result(0) must never
        # race a not-yet-resolved future
        self.finish_reason = reason
        _safe_set_result(self.future, list(tokens))
        self._q.put(_DONE)

    def _fail(self, exc: BaseException, reason: str) -> None:
        self.finish_reason = reason
        _safe_set_exception(self.future, exc)
        self._q.put(exc)


class _Sequence:
    """One admitted-or-queued generation request (scheduler-private)."""

    __slots__ = ("prompt", "max_new", "eos_id", "temperature", "seed",
                 "deadline", "t_enq", "t_first", "sid", "stream", "pages",
                 "slot", "tokens", "last_token", "position", "trace")

    def __init__(self, prompt, max_new, eos_id, temperature, seed,
                 deadline):
        self.prompt = prompt                  # np.int32 [T]
        self.max_new = max_new
        self.eos_id = eos_id
        self.temperature = temperature
        self.seed = seed
        self.deadline = deadline              # monotonic seconds or None
        self.t_enq = time.monotonic()
        self.t_first: Optional[float] = None
        self.sid = next(_REQUEST_IDS)
        self.stream = GenerationStream(self.sid)
        self.pages: List[int] = []
        self.slot: Optional[int] = None
        self.tokens: List[int] = []           # generated (no prompt)
        self.last_token = 0
        self.position = 0                     # total tokens in cache
        self.trace: Optional[str] = None      # distributed trace id, if
                                              # the admitting thread had one


class GenerationEngine:
    """Continuous-batching generative decode over a paged KV cache.

    Args:
        model: paged decode contract — attributes ``num_layers`` /
            ``num_kv_heads`` / ``head_dim`` (KV geometry), methods
            ``prefill(tokens[T], length, kv, page_table[P])`` ->
            ``(logits[V], kv)`` and ``decode(tokens[S], positions[S],
            kv, page_tables[S, P])`` -> ``(logits[S, V], kv)`` (see
            :class:`~paddle_tpu.serving.models.PagedDecoderLM`).
        num_slots: static decode-batch width (in-flight sequences).
        page_size: tokens per KV page.
        max_context: per-sequence token capacity (prompt + generated).
        num_pages: physical pool size; defaults to full occupancy
            (``num_slots * pages_per_seq``) so admission can never be
            page-starved below slot capacity.
        prompt_buckets: prompt pad lengths to precompile (each is one
            AOT variant); default powers of two up to ``max_context``.
        max_queue / default_deadline_ms: as on ``InferenceEngine``.
        decode_retries: decode-step re-runs before the in-flight batch
            is failed (default ``FLAGS_serving_decode_retries``).
        donate_kv: thread the KV pool through compiled steps with
            buffer donation (in-place pool updates).  Injected
            ``serving.decode_step`` faults fire before dispatch, so
            those retries are always safe; a failure raised by the
            executable itself is NOT replayed under donation (the
            inputs may be invalidated) — the in-flight batch is failed
            and the pool rebuilt instead.
        name: engine label for multi-model processes (same contract as
            ``InferenceEngine``): monitor stats mirror under
            ``serving.engine.<name>.decode.*``, tracer events carry
            it, and the HTTP layer labels the Prometheus gauges.
    """

    def __init__(self, model, num_slots: int = 8, page_size: int = 16,
                 max_context: int = 256,
                 num_pages: Optional[int] = None,
                 prompt_buckets: Optional[Sequence[int]] = None,
                 max_queue: int = 256,
                 default_deadline_ms: Optional[float] = None,
                 decode_retries: Optional[int] = None,
                 donate_kv: bool = True,
                 name: Optional[str] = None):
        if num_slots < 1:
            raise ValueError("num_slots must be >= 1")
        self._model = model
        self.name = str(name) if name else None
        self._stat_prefix = (f"serving.engine.{self.name}.decode."
                             if self.name else None)
        self._slots_n = int(num_slots)
        cfg = KVCacheConfig(
            num_layers=model.num_layers, num_kv_heads=model.num_kv_heads,
            head_dim=model.head_dim, page_size=page_size,
            num_pages=(int(num_pages) if num_pages is not None
                       else self._slots_n *
                       -(-int(max_context) // int(page_size))),
            max_context=max_context)
        self.config = cfg
        self._pool = PagePool(cfg)
        self._P = cfg.pages_per_seq
        if prompt_buckets is None:
            prompt_buckets, b = [], 8
            while b < cfg.max_context:
                prompt_buckets.append(b)
                b <<= 1
            prompt_buckets.append(cfg.max_context)
        self._prompt_buckets = sorted({int(b) for b in prompt_buckets})
        if self._prompt_buckets[0] < 1 \
                or self._prompt_buckets[-1] > cfg.max_context:
            raise ValueError("prompt buckets must lie in "
                             f"[1, {cfg.max_context}]")
        # page-table width buckets for the decode step: powers of two up
        # to the full per-sequence table
        self._ctx_buckets, b = [], 1
        while b < self._P:
            self._ctx_buckets.append(b)
            b <<= 1
        self._ctx_buckets.append(self._P)
        self._max_queue = int(max_queue)
        self._default_deadline = (float(default_deadline_ms) / 1000.0
                                  if default_deadline_ms is not None
                                  else None)
        self._retries = (flags.get_flag("serving_decode_retries")
                         if decode_retries is None
                         else int(decode_retries))
        self._donate = bool(donate_kv)

        # Pallas tier: install the paged-attention decode kernel behind
        # the ops.attention hook when the tier is active (TPU, or the
        # explicit FLAGS_pallas_interpret opt-in) and nothing is
        # registered yet — the compiled decode step then resolves to
        # gather-free VMEM-resident attention through
        # paged_attention_select; the shape gate still owns the final
        # per-shape decision, so misaligned models stay on the
        # reference tier untouched
        from ..ops import attention as _attn
        from ..ops.pallas.support import tier_enabled
        if tier_enabled() and _attn._PALLAS_KERNEL is None:
            from ..ops.pallas.paged_attention import register
            register()

        # scheduler state (slots touched only by the scheduler thread)
        self._slots: List[Optional[_Sequence]] = [None] * self._slots_n
        self._tables = np.zeros((self._slots_n, self._P), np.int32)
        # device mirrors of slot state that changes only at admission/
        # eviction — uploaded once per change, not once per decode step
        # (tables keyed by context-bucket width)
        self._tables_dev: Dict[int, object] = {}
        self._temps = np.zeros((self._slots_n,), np.float32)
        self._temps_dev = None
        self._any_sampling = False
        self._zero_keys = jnp.zeros((self._slots_n, 2), jnp.uint32)
        self._cv = threading.Condition(threading.Lock())
        self._queue: collections.deque = collections.deque()
        self._queued_deadlines = 0
        self._draining = False
        self._closing = False
        self._closed = False
        self._paused = False
        self._stepping = False          # a decode/prefill is in flight

        # compiled executables: (kind, bucket) -> AOT executable.
        # _trace_lock serialises lower()+compile(): the traced step fns
        # rebind self._model.params for the duration of the trace, so
        # two concurrent traces (warmup on the caller's thread vs a
        # serve-path miss on the scheduler) would clobber each other's
        # binding and bake concrete weights into the jaxpr as constants
        # — a corrupt executable with the wrong input arity.
        self._trace_lock = threading.Lock()
        self._execs: Dict[tuple, object] = {}
        self._compile_count = 0
        self._warm_variants: Optional[int] = None
        self._serial = f"gen-{id(self):x}"
        # serving weights, device-resident, passed as the first argument
        # of every compiled entry point (see module docstring): a hot
        # swap replaces this dict wholesale between decode steps
        self._params_dev: Dict[str, object] = {
            k: jnp.asarray(v) for k, v in model.params.items()}
        self._weights_version = 0
        self._pending_swap = None   # (params_dev, version) staged by
        #                             swap_weights, committed by _loop

        self._c: Dict[str, Union[int, float]] = collections.defaultdict(int)
        self._occ_sum = 0.0
        self._reg = monitor.StatRegistry()
        self._thread = threading.Thread(target=self._loop,
                                        name="generation-scheduler",
                                        daemon=True)
        self._thread.start()

    # -- admission ---------------------------------------------------------
    def generate(self, prompt, max_new_tokens: int = 32,
                 eos_id: Optional[int] = None, temperature: float = 0.0,
                 seed: int = 0,
                 deadline_ms: Optional[float] = None) -> GenerationStream:
        """Enqueue one prompt; returns a :class:`GenerationStream`.

        ``temperature=0`` decodes greedily; ``temperature>0`` samples
        with a key derived from ``(seed, position)`` — deterministic for
        fixed arguments regardless of batching.  Raises
        :class:`QueueFull` / :class:`EngineClosed` / ``ValueError``
        synchronously."""
        prompt = np.asarray(prompt, np.int32).reshape(-1)
        if prompt.size < 1:
            raise ValueError("prompt must carry at least one token")
        max_new = int(max_new_tokens)
        if max_new < 1:
            raise ValueError("max_new_tokens must be >= 1")
        total = prompt.size + max_new
        if total > self.config.max_context:
            raise ValueError(
                f"prompt ({prompt.size}) + max_new_tokens ({max_new}) "
                f"= {total} exceeds max_context={self.config.max_context}")
        if prompt.size > self._prompt_buckets[-1]:
            raise ValueError(
                f"prompt of {prompt.size} tokens exceeds the largest "
                f"prompt bucket {self._prompt_buckets[-1]}")
        need = pages_needed(prompt.size, max_new, self.config.page_size)
        if need > self._pool.num_pages:
            raise ValueError(
                f"request needs {need} pages, pool holds only "
                f"{self._pool.num_pages}")
        fault.point("serving.generate", f"prompt={prompt.size}")
        deadline = None
        dl_s = (float(deadline_ms) / 1000.0 if deadline_ms is not None
                else self._default_deadline)
        if dl_s is not None:
            deadline = time.monotonic() + dl_s
        seq = _Sequence(prompt, max_new, eos_id, float(temperature),
                        int(seed), deadline)
        trc = obs_hook._tracer
        if trc is not None:
            # the admitting thread's distributed trace context (bound
            # by the HTTP front-end) sticks to the sequence so the
            # scheduler thread's prefill/decode events correlate to it
            seq.trace = trc.current_trace()
        with self._cv:
            if self._closing or self._closed or self._draining:
                raise EngineClosed("engine is draining or closed")
            if len(self._queue) >= self._max_queue:
                self._expire_queued_locked()
            if len(self._queue) >= self._max_queue:
                self._c["shed"] += 1
                self._madd("shed")
                self._emit("gen_shed", sid=seq.sid)
                raise QueueFull(
                    f"generation queue full ({self._max_queue}); retry "
                    f"with backoff")
            self._queue.append(seq)
            if seq.deadline is not None:
                self._queued_deadlines += 1
            self._c["requests"] += 1
            self._madd("requests")
            self._cv.notify_all()
        self._emit("gen_enqueue", sid=seq.sid, prompt=int(prompt.size),
                   max_new=max_new)
        return seq.stream

    def generate_sync(self, prompt, timeout: Optional[float] = None,
                      **kw) -> List[int]:
        """Blocking :meth:`generate`; returns the full token list."""
        return self.generate(prompt, **kw).result(timeout)

    # -- observability helpers ---------------------------------------------
    def _emit(self, name: str, **args) -> None:
        trc = obs_hook._tracer
        if trc is not None:
            if self.name is not None:
                args["engine"] = self.name
            trc.emit("serving", name, args=args)

    def _madd(self, suffix: str, v=1) -> None:
        """Count ``serving.decode.<suffix>`` — mirrored under this
        engine's ``serving.engine.<name>.decode.`` prefix when
        labelled (the multi-model registry's per-engine view)."""
        _mirrored_add("serving.decode.", self._stat_prefix, suffix, v)

    def _mobs(self, suffix: str, v) -> None:
        _mirrored_observe("serving.decode.", self._stat_prefix,
                          suffix, v)

    # -- compiled entry points ---------------------------------------------
    def _select_tokens(self, logits, temps, keys):
        """[N, V] logits -> [N] int32 tokens (greedy or sampled).

        Sampling is a counter-based Gumbel-max draw: per-(seed,
        position, vocab-index) uniforms from a murmur3-style integer
        mix, so a sequence's draws depend only on its own request state
        (never the PRNG impl, the slot index, or batch composition) —
        the bitwise-reproducibility contract."""
        greedy = jnp.argmax(logits, axis=-1).astype(jnp.int32)
        V = logits.shape[-1]
        idx = jnp.arange(1, V + 1, dtype=jnp.uint32)[None, :]
        x = (keys[:, 0:1] * jnp.uint32(0x9E3779B1)
             ^ keys[:, 1:2] * jnp.uint32(0x85EBCA77)
             ^ idx * jnp.uint32(0xC2B2AE3D))
        x = x ^ (x >> 16)
        x = x * jnp.uint32(0x7FEB352D)
        x = x ^ (x >> 15)
        x = x * jnp.uint32(0x846CA68B)
        x = x ^ (x >> 16)
        u = (x >> 8).astype(jnp.float32) * (1.0 / (1 << 24))
        g = -jnp.log(-jnp.log(jnp.clip(u, 1e-7, 1.0 - 1e-7)))
        scaled = logits / jnp.maximum(temps, 1e-6)[:, None] + g
        sampled = jnp.argmax(scaled, axis=-1).astype(jnp.int32)
        return jnp.where(temps > 0, sampled, greedy)

    def _decode_step_fn(self, params, k_pool, v_pool, tokens, positions,
                        tables, temps, keys):
        # `params` rides the executable as a real argument so a weight
        # hot swap is an array replacement, never a recompile; the model
        # reads self.params, so bind the traced pytree for the trace
        saved = self._model.params
        self._model.params = params
        try:
            logits, (k_pool, v_pool) = self._model.decode(
                tokens, positions, (k_pool, v_pool), tables)
        finally:
            self._model.params = saved
        toks = self._select_tokens(logits, temps, keys)
        return k_pool, v_pool, toks

    def _prefill_fn(self, params, k_pool, v_pool, tokens, length, table,
                    temp, key):
        saved = self._model.params
        self._model.params = params
        try:
            logits, (k_pool, v_pool) = self._model.prefill(
                tokens, length, (k_pool, v_pool), table)
        finally:
            self._model.params = saved
        tok = self._select_tokens(logits[None], temp[None], key[None])[0]
        return k_pool, v_pool, tok

    def _get_exec(self, kind: str, bucket: int):
        key = (kind, bucket)
        ex = self._execs.get(key)
        if ex is not None:
            return ex
        with self._trace_lock:
            ex = self._execs.get(key)
            if ex is not None:
                return ex
            c = self.config
            f32, i32 = jnp.float32, jnp.int32
            pool_aval = jax.ShapeDtypeStruct(self._pool.kv[0].shape, f32)

            def aval(shape, dt):
                return jax.ShapeDtypeStruct(shape, dt)

            # params (arg 0) are never donated: the old weights must
            # stay alive through a hot swap's in-flight step; the KV
            # pool (args 1, 2) keeps its in-place donation
            donate = (1, 2) if self._donate else ()
            params_avals = {k: jax.ShapeDtypeStruct(v.shape, v.dtype)
                            for k, v in self._params_dev.items()}
            if kind == "decode":
                # bucket = page-table width (context bucket), so the
                # gather is O(live context), not O(max_context)
                S = self._slots_n
                fn = jax.jit(self._decode_step_fn, donate_argnums=donate)
                lowered = fn.lower(params_avals, pool_aval, pool_aval,
                                   aval((S,), i32),
                                   aval((S,), i32),
                                   aval((S, bucket), i32),
                                   aval((S,), f32),
                                   aval((S, 2), jnp.uint32))
            else:
                fn = jax.jit(self._prefill_fn, donate_argnums=donate)
                lowered = fn.lower(params_avals, pool_aval, pool_aval,
                                   aval((bucket,), i32),
                                   aval((), i32), aval((self._P,), i32),
                                   aval((), f32),
                                   aval((2,), jnp.uint32))
            # persistent AOT cache: the key is the hash of the lowered
            # module itself — exact program content, so two models that
            # trace identically share the executable while ANY model/
            # geometry difference (head count, sampling change) misses.
            # weights_version is deliberately NOT in the key: params
            # ride as runtime arguments, a hot swap reuses the same
            # executable.  The trace above is cheap; the .compile() is
            # what a warm cold start skips.
            from ..core import compile_cache
            ex, cache_prov = compile_cache.cached_compile("generation", {
                "kind": kind, "bucket": bucket, "donate": donate,
                "module": hashlib.sha256(
                    lowered.as_text().encode()).hexdigest(),
            }, lowered.compile)
            self._execs[key] = ex
            self._compile_count += 1
            from ..observability import record_compile
            record_compile("generation", self._serial, {
                "kind": kind, "bucket": bucket,
                "slots": self._slots_n, "pages": c.num_pages,
                "page_size": c.page_size,
                "weights_version": self._weights_version,
            }, note="warmup" if self._warm_variants is None
                    else "serve-path miss",
                cache=cache_prov)
            return ex

    def warmup(self) -> int:
        """AOT-compile every decode context bucket and prompt bucket.
        Returns the compiled-variant count (baseline for
        ``recompiles_after_warmup``)."""
        for b in self._ctx_buckets:
            self._get_exec("decode", b)
        for b in self._prompt_buckets:
            self._get_exec("prefill", b)
        self._warm_variants = self._compile_count
        return self._warm_variants

    # -- zero-downtime weight hot swap -------------------------------------
    def swap_weights(self, params, version: int,
                     timeout: Optional[float] = 30.0) -> int:
        """Atomically replace the decode weights at a step boundary.

        Validates the new parameter pytree against the serving one
        (same names, shapes, dtypes — the compiled executables are
        shape-specialized, so a mismatch is REJECTED with
        ``ValueError``, never recompiled), uploads it to the device
        entirely off the scheduler thread, then stages it for the
        scheduler to commit between decode steps: every in-flight
        sequence finishes its current token on the old weights and
        produces its next one on the new — no drain, no recompile, and
        each emitted token is attributable to exactly one version.

        Blocks until the commit (or ``timeout`` → ``TimeoutError``);
        returns the committed version.  Call from any thread except the
        scheduler's."""
        new = {k: jnp.asarray(v) for k, v in params.items()}
        cur = self._params_dev
        if set(new) != set(cur):
            diff = sorted(set(cur) ^ set(new))
            raise ValueError(
                f"weight swap rejected: parameter set differs from the "
                f"serving weights (mismatched: {diff})")
        for k in sorted(new):
            if (tuple(new[k].shape) != tuple(cur[k].shape)
                    or new[k].dtype != cur[k].dtype):
                raise ValueError(
                    f"weight swap rejected: param {k!r} is "
                    f"{tuple(new[k].shape)}/{new[k].dtype}, executables "
                    f"compiled for {tuple(cur[k].shape)}/{cur[k].dtype}")
        for a in new.values():      # finish the device upload HERE, off
            getattr(a, "block_until_ready", lambda: None)()  # the loop
        with self._cv:
            if self._closing or self._closed:
                raise EngineClosed("engine is draining or closed")
            if self._pending_swap is not None:
                raise ServingError("a weight swap is already pending")
            self._pending_swap = (new, int(version))
            self._cv.notify_all()
            ok = self._cv.wait_for(
                lambda: self._pending_swap is None or self._closing
                or self._closed, timeout)
            if self._pending_swap is not None:
                self._pending_swap = None       # unstage: never commit
                if not ok:                      # a swap after our bail
                    raise TimeoutError(
                        f"weight swap not committed within {timeout}s")
                raise EngineClosed("engine closed before the swap "
                                   "committed")
        return int(version)

    def _commit_swap_locked(self) -> None:
        """Scheduler-side commit (caller holds the lock, between
        steps): one pointer write, then wake the staging thread."""
        params, version = self._pending_swap
        self._pending_swap = None
        self._params_dev = params
        self._weights_version = version
        self._c["weight_swaps"] += 1
        self._madd("weight_swaps")
        self._emit("gen_weights_swap", version=version)
        self._cv.notify_all()

    @property
    def weights_version(self) -> int:
        return self._weights_version

    # -- scheduler ---------------------------------------------------------
    def _expire_queued_locked(self) -> None:
        if not self._queue or not self._queued_deadlines:
            return
        now = time.monotonic()
        alive = collections.deque()
        for s in self._queue:
            if s.deadline is not None and now > s.deadline:
                self._queued_deadlines -= 1
                self._c["deadline_expired"] += 1
                self._madd("deadline_expired")
                self._emit("gen_deadline_expired", sid=s.sid, where="queue")
                s.stream._fail(DeadlineExceeded(
                    f"deadline expired after "
                    f"{(now - s.t_enq) * 1000:.1f} ms in queue"),
                    "deadline")
            else:
                alive.append(s)
        self._queue = alive

    def _active(self) -> List[_Sequence]:
        return [s for s in self._slots if s is not None]

    def _admit_locked(self) -> List[_Sequence]:
        """Move queued requests into free slots while pages last."""
        admitted = []
        now = time.monotonic()
        for i in range(self._slots_n):
            if self._slots[i] is not None or not self._queue:
                continue
            head = self._queue[0]
            if head.deadline is not None and now > head.deadline:
                # lapsed while queued: expire instead of admitting
                self._expire_queued_locked()
                if not self._queue:
                    break
                head = self._queue[0]
            need = pages_needed(head.prompt.size, head.max_new,
                                self.config.page_size)
            pages = self._pool.alloc(need)
            if pages is None:       # pool starved: wait for an eviction
                break
            self._queue.popleft()
            if head.deadline is not None:
                self._queued_deadlines -= 1
            head.pages = pages
            head.slot = i
            self._slots[i] = head
            row = np.zeros((self._P,), np.int32)
            row[:len(pages)] = pages
            self._tables[i] = row
            self._temps[i] = head.temperature
            self._tables_dev.clear()
            self._temps_dev = None
            admitted.append(head)
            self._c["admitted"] += 1
            self._c["pages_allocated"] += need
            self._madd("admitted")
        return admitted

    def _evict_locked(self, seq: _Sequence) -> None:
        """Free a sequence's slot + pages (future/stream already
        resolved by the caller)."""
        i = seq.slot
        if i is not None and self._slots[i] is seq:
            self._slots[i] = None
            self._tables[i] = 0
            self._temps[i] = 0.0
            self._tables_dev.clear()
            self._temps_dev = None
        if seq.pages:
            self._pool.free(seq.pages)
            self._c["pages_freed"] += len(seq.pages)
            seq.pages = []
        seq.slot = None
        self._cv.notify_all()

    def _finish(self, seq: _Sequence, reason: str,
                exc: Optional[BaseException] = None) -> None:
        now = time.monotonic()
        with self._cv:
            self._evict_locked(seq)
            if exc is None:
                self._c["finished"] += 1
            else:
                self._c["failed"] += 1
        if exc is None:
            seq.stream._finish(seq.tokens, reason)
            self._madd("finished")
            lat = (now - seq.t_enq) * 1000.0
            self._reg.observe("latency_ms", lat)
            self._mobs("latency_ms", lat)
            if seq.t_first is not None and len(seq.tokens) > 1:
                tpot = ((now - seq.t_first) * 1000.0
                        / (len(seq.tokens) - 1))
                self._reg.observe("tpot_ms", tpot)
        else:
            seq.stream._fail(exc, reason)
            self._madd("failed")
        self._emit("gen_finish", sid=seq.sid, reason=reason,
                   tokens=len(seq.tokens))

    def _emit_token(self, seq: _Sequence, tok: int) -> bool:
        """Record one generated token; True when the sequence is done."""
        now = time.monotonic()
        if seq.t_first is None:
            seq.t_first = now
            self._reg.observe("ttft_ms", (now - seq.t_enq) * 1000.0)
            self._mobs("ttft_ms", (now - seq.t_enq) * 1000.0)
        seq.tokens.append(tok)
        seq.last_token = tok
        seq.stream._push(tok)
        self._c["tokens"] += 1     # monitor mirror batched by the caller
        if seq.eos_id is not None and tok == seq.eos_id:
            self._finish(seq, "eos")
            return True
        if len(seq.tokens) >= seq.max_new:
            self._finish(seq, "length")
            return True
        return False

    def _sample_key(self, seq: _Sequence) -> np.ndarray:
        # raw threefry key data from (seed, position): any uint32 pair
        # is a valid key, and this one depends only on request-local
        # state — never on slot index or batch composition
        return np.array([seq.seed & 0xFFFFFFFF, seq.position],
                        np.uint32)

    def _run_exec(self, kind: str, bucket: int, args) -> tuple:
        """Call a precompiled executable with decode-retry semantics.

        Pre-dispatch failures (the injected fault point) always retry —
        the inputs are untouched.  A failure raised by the executable
        itself is NOT replayed when the KV pool was donated: the input
        buffers may already be invalidated, and a replay would read
        dead arrays.  The caller recovers via :meth:`_fail_active`."""
        ex = self._get_exec(kind, bucket)
        last: Optional[BaseException] = None
        for attempt in range(self._retries + 1):
            try:
                fault.point("serving.decode_step", kind,
                            f"attempt={attempt}")
            except Exception as e:      # pre-dispatch: always retryable
                last = e
                self._c["decode_errors"] += 1
                self._madd("errors")
                if attempt < self._retries:
                    self._c["decode_retries"] += 1
                    self._madd("retries")
                continue
            try:
                return ex(*args)
            except Exception as e:
                last = e
                self._c["decode_errors"] += 1
                self._madd("errors")
                if self._donate:
                    break               # donated inputs may be dead
                if attempt < self._retries:
                    self._c["decode_retries"] += 1
                    self._madd("retries")
        raise GenerationError(
            f"{kind} failed after {self._retries + 1} attempts: "
            f"{type(last).__name__}: {last}") from last

    def _fail_active(self, exc: BaseException) -> None:
        """A compiled step failed: fail every in-flight sequence, free
        their pages, and (under donation) rebuild the KV pool — the
        failed call may have invalidated the donated buffers, and no
        surviving sequence's cache can be trusted through them."""
        for s in list(self._active()):
            self._finish(s, "error", exc)
        if self._donate:
            self._pool.reset_kv()

    def _prefill(self, seq: _Sequence) -> None:
        c = self.config
        bucket = next(b for b in self._prompt_buckets
                      if b >= seq.prompt.size)
        toks = np.zeros((bucket,), np.int32)
        toks[:seq.prompt.size] = seq.prompt
        t0 = time.perf_counter()
        k_pool, v_pool = self._pool.kv
        try:
            k_pool, v_pool, tok = self._run_exec(
                "prefill", bucket,
                (self._params_dev, k_pool, v_pool, jnp.asarray(toks),
                 jnp.int32(seq.prompt.size),
                 jnp.asarray(self._tables[seq.slot]),
                 jnp.float32(seq.temperature),
                 jnp.asarray(self._sample_key(seq))))
        except GenerationError as e:
            self._fail_active(e)
            return
        self._pool.kv = (k_pool, v_pool)
        self._c["prefills"] += 1
        self._c["prefill_tokens"] += int(seq.prompt.size)
        self._madd("prefills")
        self._madd("prefill_tokens", int(seq.prompt.size))
        self._madd("tokens")
        self._emit("gen_prefill", sid=seq.sid, bucket=bucket,
                   dur_ms=(time.perf_counter() - t0) * 1000.0)
        hb = obs_hook._heartbeat
        if hb is not None:
            hb.beat(int(self._c["prefills"]))
        seq.position = int(seq.prompt.size) + 1
        self._emit_token(seq, int(tok))

    def _decode_step(self) -> None:
        S = self._slots_n
        tokens = np.zeros((S,), np.int32)
        positions = np.zeros((S,), np.int32)
        sampling = False
        active = []
        for i, s in enumerate(self._slots):
            if s is None:
                continue
            active.append(s)
            tokens[i] = s.last_token
            positions[i] = s.position - 1      # where this token's KV goes
            sampling = sampling or s.temperature > 0
        if not active:
            return
        if sampling:
            keys = np.zeros((S, 2), np.uint32)
            for s in active:
                keys[s.slot] = self._sample_key(s)
            keys = jnp.asarray(keys)
        else:       # greedy batch: keys are dead inputs, skip the upload
            keys = self._zero_keys
        # narrowest context bucket covering the longest active sequence
        page = self.config.page_size
        p_need = -(-max(s.position for s in active) // page)
        p_b = next(b for b in self._ctx_buckets if b >= p_need)
        tables = self._tables_dev.get(p_b)
        if tables is None:
            tables = jnp.asarray(
                np.ascontiguousarray(self._tables[:, :p_b]))
            self._tables_dev[p_b] = tables
        if self._temps_dev is None:
            self._temps_dev = jnp.asarray(self._temps)
        t0 = time.perf_counter()
        k_pool, v_pool = self._pool.kv
        try:
            k_pool, v_pool, toks = self._run_exec(
                "decode", p_b,
                (self._params_dev, k_pool, v_pool, jnp.asarray(tokens),
                 jnp.asarray(positions), tables,
                 self._temps_dev, keys))
        except GenerationError as e:
            self._fail_active(e)
            return
        self._pool.kv = (k_pool, v_pool)
        toks = np.asarray(toks)
        occ = len(active) / S
        self._c["decode_steps"] += 1
        self._occ_sum += occ
        self._madd("steps")
        self._mobs("ctx_pages", p_b)
        self._mobs("slot_occupancy", occ)
        self._mobs("page_util", self._pool.utilization())
        step_s = time.perf_counter() - t0
        self._reg.observe("step_ms", step_s * 1000.0)
        self._mobs("step_ms", step_s * 1000.0)
        # supervised liveness: one beat per decode step (one None-check
        # when unsupervised — the engine heartbeat contract)
        hb = obs_hook._heartbeat
        if hb is not None:
            hb.beat(int(self._c["decode_steps"]))
        # fleet telemetry: ride the same cadence (one None-check when
        # not spooling, a time comparison when no interval has passed)
        exp = obs_hook._export
        if exp is not None:
            exp.tick()
        # one typed event per decode step, correlated to every slotted
        # sequence (and their distributed traces) — the step a request's
        # token came from is findable on the fleet timeline
        if obs_hook._tracer is not None:
            traces = sorted({s.trace for s in active if s.trace})
            self._emit("gen_decode_step", sids=[s.sid for s in active],
                       n=int(self._c["decode_steps"]),
                       dur_ms=step_s * 1000.0,
                       **({"traces": traces} if traces else {}))
        # perf observatory: decode anatomy + memory sampler cadence
        p = obs_hook._perf
        if p is not None:
            p.serving_step(self.name, "decode_step", step_s)
        emitted = 0
        now = time.monotonic()
        for s in active:
            if s.deadline is not None and now > s.deadline:
                # mid-generation expiry: evict, free pages, fail cleanly
                self._c["deadline_expired"] += 1
                self._madd("deadline_expired")
                self._emit("gen_deadline_expired", sid=s.sid,
                           where="decode")
                self._finish(s, "deadline", DeadlineExceeded(
                    f"deadline expired mid-generation after "
                    f"{len(s.tokens)} tokens"))
                continue
            s.position += 1
            self._emit_token(s, int(toks[s.slot]))
            emitted += 1
        if emitted:
            self._madd("tokens", emitted)

    def _loop(self) -> None:
        while True:
            with self._cv:
                # weight hot swap: commit between steps — the ONLY
                # steady-state cost of the swap machinery is this one
                # attribute check when no swap is pending
                if self._pending_swap is not None:
                    self._commit_swap_locked()
                self._expire_queued_locked()
                has_active = any(s is not None for s in self._slots)
                if self._closing and not self._queue and not has_active:
                    return
                if self._paused or (not self._queue and not has_active):
                    # idle (or paused): sleep until an enqueue/resume/
                    # close notifies; poll only to sweep queued deadlines
                    self._cv.wait(
                        0.05 if (self._queued_deadlines or self._paused
                                 or self._closing) else None)
                    continue
                admitted = self._admit_locked()
                if not admitted \
                        and not any(s is not None for s in self._slots):
                    # queued work that cannot be admitted yet (page
                    # starvation) with nothing decoding: don't hot-spin
                    self._cv.wait(0.05)
                    continue
                self._stepping = True
            try:
                for seq in admitted:
                    if seq.slot is not None:    # not already finished
                        self._prefill(seq)
                self._decode_step()
            except Exception as e:      # defense in depth: the scheduler
                # must survive anything — fail in-flight work cleanly
                self._fail_active(GenerationError(
                    f"scheduler error: {type(e).__name__}: {e}"))
            finally:
                with self._cv:
                    self._stepping = False
                    self._cv.notify_all()

    # -- lifecycle ---------------------------------------------------------
    def pause(self) -> None:
        """Testing hook: hold the scheduler between steps."""
        with self._cv:
            self._paused = True
            self._cv.notify_all()

    def resume(self) -> None:
        with self._cv:
            self._paused = False
            self._cv.notify_all()

    def drain(self, timeout: Optional[float] = None) -> bool:
        """Stop admission of new requests, finish everything accepted.
        Returns True when fully drained within ``timeout``."""
        deadline = (time.monotonic() + timeout) if timeout is not None \
            else None
        with self._cv:
            self._draining = True
            self._paused = False
            self._cv.notify_all()
            while (self._queue or self._stepping
                   or any(s is not None for s in self._slots)):
                wait = 0.05
                if deadline is not None:
                    wait = min(wait, deadline - time.monotonic())
                    if wait <= 0:
                        return False
                self._cv.wait(wait)
        return True

    def close(self, timeout: float = 30.0) -> None:
        """Drain, stop the scheduler, fail anything unserved, reclaim
        every page — no stranded future, no leaked page."""
        with self._cv:
            if self._closed:
                return
            self._draining = True
            self._closing = True
            self._paused = False
            self._cv.notify_all()
        self._thread.join(timeout)
        with self._cv:
            self._closed = True
            stranded = list(self._queue)
            self._queue.clear()
            self._queued_deadlines = 0
            inflight = [s for s in self._slots if s is not None]
            if not self._thread.is_alive():
                # scheduler is gone: reclaim in-flight sequences safely
                stranded += inflight
                for s in stranded:
                    self._evict_locked(s)
            else:
                # wedged scheduler: futures must still resolve (pages
                # stay accounted to the wedged step — never guess)
                stranded += inflight
            self._cv.notify_all()
        for s in stranded:
            s.stream._fail(EngineClosed(
                "engine closed before the sequence finished"), "closed")

    def __enter__(self):
        return self

    def __exit__(self, *exc_info):
        self.close()
        return False

    # -- observability -----------------------------------------------------
    @property
    def prompt_buckets(self) -> List[int]:
        return list(self._prompt_buckets)

    @property
    def num_slots(self) -> int:
        return self._slots_n

    @property
    def page_pool(self) -> PagePool:
        return self._pool

    def stats(self) -> Dict[str, object]:
        """Scheduler state + counters + token latency percentiles (the
        ``generation`` block of the HTTP ``/metrics`` payload)."""
        with self._cv:
            state = ("closed" if self._closed else
                     "draining" if self._draining else
                     "paused" if self._paused else "running")
            c = dict(self._c)
            queue_depth = len(self._queue)
            active = sum(1 for s in self._slots if s is not None)
            occ_sum = self._occ_sum
        steps = c.get("decode_steps", 0)
        prefill_toks = c.get("prefill_tokens", 0)
        decode_toks = c.get("tokens", 0)
        return {
            "state": state,
            "engine": self.name,
            "queue_depth": queue_depth,
            "num_slots": self._slots_n,
            "active_slots": active,
            "prompt_buckets": list(self._prompt_buckets),
            "ctx_buckets": list(self._ctx_buckets),
            "page_pool": {
                "num_pages": self._pool.num_pages,
                "page_size": self.config.page_size,
                "in_use": self._pool.in_use,
                "available": self._pool.available,
                "utilization": self._pool.utilization(),
            },
            "max_context": self.config.max_context,
            "counters": {k: c.get(k, 0) for k in (
                "requests", "admitted", "finished", "failed", "shed",
                "deadline_expired", "tokens", "prefills",
                "prefill_tokens", "decode_steps", "decode_errors",
                "decode_retries", "pages_allocated", "pages_freed",
                "weight_swaps")},
            "weights_version": self._weights_version,
            "mean_slot_occupancy": (occ_sum / steps) if steps else 0.0,
            "prefill_decode_ratio": (prefill_toks / decode_toks
                                     if decode_toks else 0.0),
            "latency_ms": self._reg.histogram_summary("latency_ms"),
            "ttft_ms": self._reg.histogram_summary("ttft_ms"),
            "step_ms": self._reg.histogram_summary("step_ms"),
            "compiled_variants": self._compile_count,
            "warm_variants": self._warm_variants,
            "recompiles_after_warmup": (
                self._compile_count - self._warm_variants
                if self._warm_variants is not None else None),
        }
