"""Stdlib HTTP front-end for :class:`~paddle_tpu.serving.InferenceEngine`.

Endpoints (reference analog: the C++ inference demo's HTTP wrappers;
no external web framework — ``http.server.ThreadingHTTPServer`` gives
one thread per connection, which pairs naturally with the engine's
futures: N concurrent connections become N waiting requests that the
dispatcher coalesces into micro-batches):

- ``POST /predict`` — JSON body ``{"inputs": [...], "deadline_ms": N}``
  (inputs: one array, a list of per-input arrays, or a name->array
  dict), or a raw ``.npy`` body (``Content-Type: application/x-npy``,
  single-input models; deadline via the ``X-Deadline-Ms`` header).
  JSON responses carry ``outputs``/``names``/``dtypes``; npy requests
  get the first output back as npy bytes.
- ``GET /healthz`` — 200 while serving, 503 when draining/closed.
- ``GET /metrics`` — content-negotiated.  Default (and any JSON
  Accept): the engine's stats JSON — queue depth, batch occupancy,
  padding waste, request/shed/deadline counters, latency p50/p95/p99.
  When the Accept header asks for ``text/plain`` / OpenMetrics (what a
  Prometheus scraper sends): the full ``observability.prometheus_text``
  exposition — every ``monitor`` stat and histogram in the process plus
  the engine's own gauges under ``paddle_tpu_serving_engine_*``.

Error mapping: shed -> 503 (+Retry-After), deadline -> 504, malformed
-> 400, engine closed -> 503.
"""
from __future__ import annotations

import concurrent.futures
import io
import json
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import List, Optional
from urllib import error as urlerror
from urllib import request as urlrequest

import numpy as np

from .engine import (DeadlineExceeded, EngineClosed, InferenceEngine,
                     QueueFull, ServingError)

__all__ = ["ServingServer", "Client", "serve"]

_NPY = "application/x-npy"


class _Handler(BaseHTTPRequestHandler):
    protocol_version = "HTTP/1.1"

    @property
    def engine(self) -> InferenceEngine:
        return self.server.engine

    def log_message(self, fmt, *args):      # quiet by default
        if getattr(self.server, "verbose", False):
            super().log_message(fmt, *args)

    def _reply(self, code: int, body: bytes, ctype: str = "application/json",
               extra_headers=()):
        self.send_response(code)
        self.send_header("Content-Type", ctype)
        self.send_header("Content-Length", str(len(body)))
        for k, v in extra_headers:
            self.send_header(k, v)
        self.end_headers()
        self.wfile.write(body)

    def _reply_json(self, code: int, obj, extra_headers=()):
        self._reply(code, json.dumps(obj).encode(),
                    extra_headers=extra_headers)

    def _reply_error(self, exc: BaseException):
        kind = type(exc).__name__
        payload = {"error": kind, "message": str(exc)}
        if isinstance(exc, QueueFull):
            self._reply_json(503, payload, [("Retry-After", "0")])
        elif isinstance(exc, (DeadlineExceeded, TimeoutError,
                              concurrent.futures.TimeoutError)):
            # concurrent.futures.TimeoutError is NOT a builtin
            # TimeoutError subclass before Python 3.11
            self._reply_json(504, payload)
        elif isinstance(exc, EngineClosed):
            self._reply_json(503, payload)
        elif isinstance(exc, (ValueError, KeyError, json.JSONDecodeError)):
            self._reply_json(400, payload)
        else:
            self._reply_json(500, payload)

    # -- routes ------------------------------------------------------------
    def do_GET(self):
        path = self.path.split("?", 1)[0]
        if path == "/healthz":
            st = self.engine.stats()["state"]
            self._reply_json(200 if st in ("running", "paused") else 503,
                             {"status": st})
        elif path == "/metrics":
            accept = (self.headers.get("Accept") or "").lower()
            if ("text/plain" in accept or "openmetrics" in accept
                    or "prometheus" in accept):
                from ..observability import prometheus_text
                stats = self.engine.stats()
                gauges = {f"serving_engine_{k}": v
                          for k, v in stats.items()
                          if isinstance(v, (int, float))}
                gauges.update({f"serving_engine_{k}": v
                               for k, v in stats["counters"].items()})
                self._reply(200, prometheus_text(gauges).encode(),
                            ctype="text/plain; version=0.0.4; "
                                  "charset=utf-8")
            else:
                self._reply_json(200, self.engine.stats())
        else:
            self._reply_json(404, {"error": "NotFound", "message": self.path})

    def do_POST(self):
        path = self.path.split("?", 1)[0]
        if path != "/predict":
            self._reply_json(404, {"error": "NotFound",
                                   "message": self.path})
            return
        try:
            n = int(self.headers.get("Content-Length", "0"))
            body = self.rfile.read(n)
            ctype = (self.headers.get("Content-Type") or "").split(";")[0]
            if ctype == _NPY:
                arr = np.load(io.BytesIO(body), allow_pickle=False)
                inputs = [arr]
                deadline_ms = self.headers.get("X-Deadline-Ms")
                deadline_ms = float(deadline_ms) if deadline_ms else None
            else:
                payload = json.loads(body or b"{}")
                if "inputs" not in payload:
                    raise ValueError('body must carry "inputs"')
                inputs = payload["inputs"]
                deadline_ms = payload.get("deadline_ms")
            timeout = self.server.request_timeout
            outs = self.engine.infer_sync(inputs, deadline_ms=deadline_ms,
                                          timeout=timeout)
        except Exception as e:              # noqa: BLE001 - mapped to HTTP
            self._reply_error(e)
            return
        if ctype == _NPY:
            buf = io.BytesIO()
            np.save(buf, outs[0], allow_pickle=False)
            self._reply(200, buf.getvalue(), ctype=_NPY)
        else:
            self._reply_json(200, {
                "outputs": [o.tolist() for o in outs],
                "names": self.engine._pred.get_output_names(),
                "dtypes": [str(o.dtype) for o in outs],
            })


class ServingServer:
    """Threaded HTTP server bound to one engine.

    ``port=0`` picks a free port (read it back via ``.port``).  The
    server owns only the HTTP layer: ``close()`` stops accepting
    connections but leaves the engine to its owner (``tools/serve.py``
    closes both)."""

    def __init__(self, engine: InferenceEngine, host: str = "127.0.0.1",
                 port: int = 8000, request_timeout: float = 60.0,
                 verbose: bool = False):
        self._httpd = ThreadingHTTPServer((host, port), _Handler)
        self._httpd.daemon_threads = True
        self._httpd.engine = engine
        self._httpd.request_timeout = request_timeout
        self._httpd.verbose = verbose
        self._thread: Optional[threading.Thread] = None

    @property
    def host(self) -> str:
        return self._httpd.server_address[0]

    @property
    def port(self) -> int:
        return self._httpd.server_address[1]

    @property
    def url(self) -> str:
        return f"http://{self.host}:{self.port}"

    def start(self) -> "ServingServer":
        self._thread = threading.Thread(target=self._httpd.serve_forever,
                                        name="serving-http", daemon=True)
        self._thread.start()
        return self

    def serve_forever(self) -> None:
        self._httpd.serve_forever()

    def close(self) -> None:
        self._httpd.shutdown()
        self._httpd.server_close()
        if self._thread is not None:
            self._thread.join(5.0)

    def __enter__(self):
        return self.start()

    def __exit__(self, *exc_info):
        self.close()
        return False


def serve(engine: InferenceEngine, host: str = "127.0.0.1",
          port: int = 8000, verbose: bool = True) -> None:
    """Blocking convenience: serve until KeyboardInterrupt, then drain."""
    srv = ServingServer(engine, host, port, verbose=verbose)
    try:
        srv.serve_forever()
    except KeyboardInterrupt:
        pass
    finally:
        srv.close()
        engine.close()


class Client:
    """Tiny stdlib client for the HTTP front-end.

    503/504 responses are raised as the matching engine exceptions
    (:class:`QueueFull` / :class:`DeadlineExceeded` / ...), so a caller
    can back off on shed exactly as an in-process caller would."""

    def __init__(self, base_url: str, timeout: float = 60.0):
        self.base_url = base_url.rstrip("/")
        self.timeout = timeout

    def _raise_for(self, e: urlerror.HTTPError):
        try:
            payload = json.loads(e.read().decode() or "{}")
        except Exception:
            payload = {}
        kind = payload.get("error", "")
        msg = payload.get("message", str(e))
        for cls in (QueueFull, DeadlineExceeded, EngineClosed):
            if kind == cls.__name__:
                raise cls(msg) from None
        raise ServingError(f"HTTP {e.code}: {kind or ''} {msg}") from None

    def _post(self, path: str, body: bytes, headers: dict) -> bytes:
        req = urlrequest.Request(self.base_url + path, data=body,
                                 headers=headers, method="POST")
        try:
            with urlrequest.urlopen(req, timeout=self.timeout) as r:
                return r.read()
        except urlerror.HTTPError as e:
            self._raise_for(e)

    def _get_json(self, path: str):
        try:
            with urlrequest.urlopen(self.base_url + path,
                                    timeout=self.timeout) as r:
                return json.loads(r.read().decode())
        except urlerror.HTTPError as e:
            if path == "/healthz":      # 503 healthz still carries status
                try:
                    return json.loads(e.read().decode())
                except Exception:
                    pass
            self._raise_for(e)

    def predict(self, inputs, deadline_ms: Optional[float] = None
                ) -> List[np.ndarray]:
        """JSON round trip; returns host arrays with the server dtypes.

        Wire format (unambiguous by construction): ``inputs`` is ALWAYS
        a list of per-input arrays or a name->array dict.  A bare
        ndarray argument is wrapped as the single input; a bare
        list/tuple argument is interpreted as the per-input list."""
        if isinstance(inputs, dict):
            payload = {k: np.asarray(v).tolist() for k, v in inputs.items()}
        else:
            if isinstance(inputs, np.ndarray) or not isinstance(
                    inputs, (list, tuple)):
                inputs = [inputs]
            payload = [np.asarray(a).tolist() for a in inputs]
        body = {"inputs": payload}
        if deadline_ms is not None:
            body["deadline_ms"] = deadline_ms
        raw = self._post("/predict", json.dumps(body).encode(),
                         {"Content-Type": "application/json"})
        res = json.loads(raw.decode())
        return [np.asarray(o, dtype=np.dtype(dt))
                for o, dt in zip(res["outputs"], res["dtypes"])]

    def predict_npy(self, arr: np.ndarray,
                    deadline_ms: Optional[float] = None) -> np.ndarray:
        buf = io.BytesIO()
        np.save(buf, np.asarray(arr), allow_pickle=False)
        headers = {"Content-Type": _NPY}
        if deadline_ms is not None:
            headers["X-Deadline-Ms"] = str(deadline_ms)
        raw = self._post("/predict", buf.getvalue(), headers)
        return np.load(io.BytesIO(raw), allow_pickle=False)

    def healthz(self) -> dict:
        return self._get_json("/healthz")

    def metrics(self) -> dict:
        return self._get_json("/metrics")

    def metrics_text(self) -> str:
        """Prometheus text exposition (the scraper's view of /metrics)."""
        req = urlrequest.Request(self.base_url + "/metrics",
                                 headers={"Accept": "text/plain"})
        try:
            with urlrequest.urlopen(req, timeout=self.timeout) as r:
                return r.read().decode()
        except urlerror.HTTPError as e:
            self._raise_for(e)
