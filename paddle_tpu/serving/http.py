"""Stdlib HTTP front-end for :class:`~paddle_tpu.serving.InferenceEngine`.

Endpoints (reference analog: the C++ inference demo's HTTP wrappers;
no external web framework — ``http.server.ThreadingHTTPServer`` gives
one thread per connection, which pairs naturally with the engine's
futures: N concurrent connections become N waiting requests that the
dispatcher coalesces into micro-batches):

- ``POST /predict`` — JSON body ``{"inputs": [...], "deadline_ms": N}``
  (inputs: one array, a list of per-input arrays, or a name->array
  dict), or a raw ``.npy`` body (``Content-Type: application/x-npy``,
  single-input models; deadline via the ``X-Deadline-Ms`` header).
  JSON responses carry ``outputs``/``names``/``dtypes``; npy requests
  get the first output back as npy bytes.
- ``POST /generate`` — generative decode through an attached
  :class:`~paddle_tpu.serving.generation.GenerationEngine`.  JSON body
  ``{"prompt": [ids], "max_new_tokens": N, "eos_id": E, "temperature":
  T, "seed": S, "deadline_ms": D, "stream": true|false}``.  With
  ``stream`` (the default) the response is ``application/x-ndjson``
  over chunked transfer-encoding: one ``{"token": id}`` line per
  generated token *as the scheduler produces it*, closed by a
  ``{"done": true, "tokens": [...], "finish_reason": ...}`` summary
  line (errors mid-stream arrive in-band as an ``{"error": ...}``
  line).  ``stream: false`` returns one JSON object at the end.
- ``GET /healthz`` — liveness AND readiness in one probe.  200 only
  when the engine is serving and the server has been marked ready
  (:meth:`ServingServer.mark_ready` — ``tools/serve.py`` and the
  supervised serving entry mark ready only after warmup); 503 with a
  ``Retry-After`` hint during warmup (``"warming"``), drain, and
  close, so supervisors and load balancers rotate a replica out
  BEFORE it stops answering instead of after.  The body always
  carries ``status`` / ``ready`` / ``weights_version`` (the hot-swap
  observable).  With an SLO monitor installed
  (``observability.install_slo_monitor``) each probe also polls the
  rule set: any breached burn-rate rule degrades the reply to 503
  with ``{"status": "degraded", "slo": {reasons...}}`` while the
  engine itself keeps serving — the load-balancer sees the objective,
  not just liveness — and the endpoint recovers to 200 as soon as the
  rolling windows clear.
- ``GET /perf`` — the runtime performance observatory's drift report
  (``observability.perf_report``) plus the last SLO evaluation.
- ``GET /metrics`` — content-negotiated.  Default (and any JSON
  Accept): the engine's stats JSON — queue depth, batch occupancy,
  padding waste, request/shed/deadline counters, latency p50/p95/p99.
  When the Accept header asks for ``text/plain`` / OpenMetrics (what a
  Prometheus scraper sends): the full ``observability.prometheus_text``
  exposition — every ``monitor`` stat and histogram in the process plus
  the engine's own gauges under ``paddle_tpu_serving_engine_*``.

With a :class:`~paddle_tpu.serving.registry.ModelRegistry` attached
(``ServingServer(..., registry=...)``) the server becomes the
multi-model control plane:

- ``/predict`` and ``/generate`` route by model name or alias — the
  JSON ``"model"`` field (or ``X-Model`` header on npy bodies), with
  the registry default when absent so single-model clients keep
  working; tenant attribution rides the ``"tenant"`` field /
  ``X-Tenant`` header and feeds per-tenant quotas.
- ``GET /admin/models`` — every model's state, weights version,
  engines, in-flight count and weight, plus aliases / default /
  quotas (:meth:`ModelRegistry.describe`).
- ``POST /admin/models`` — control actions: ``{"action": "load",
  "name": ..., "artifact": ...}`` (plus optional ``weights_dir``,
  ``aliases``, ``weight``, ``rest_shapes``), ``"unload"``,
  ``"alias"``/``"unalias"``, ``"quota"`` (tenant/rate/burst),
  ``"weight"``, ``"default"``.  Load warms the model before the name
  becomes routable; unload drains through the engines' existing
  contracts and reports page-pool reclamation.

Error mapping: shed -> 503 (+Retry-After), deadline -> 504, malformed
-> 400, engine closed -> 503, unknown model -> 404, tenant over
quota -> 429 (+Retry-After).
"""
from __future__ import annotations

import concurrent.futures
import http.client as httpclient
import io
import json
import random
import re
import threading
import time
import uuid
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Iterator, List, Optional
from urllib.parse import parse_qs, urlsplit

import numpy as np

from ..core import obs_hook
from ..observability import perf as _perf, slo as _slo
from ..utils import monitor
from .engine import (DeadlineExceeded, EngineClosed, InferenceEngine,
                     QueueFull, ServingError)
from .registry import ModelRegistry, QuotaExceeded, UnknownModel

__all__ = ["ServingServer", "Client", "serve"]

_NPY = "application/x-npy"

# distributed trace ids on the wire: 1-64 chars, alnum plus ./_/-.
# Anything else — oversized, control chars, empty — is treated as
# ABSENT (a fresh id is minted), never as an error: a hostile or
# buggy X-Trace-Id header must not be able to fail a request.
_TRACE_ID_RE = re.compile(r"^[A-Za-z0-9][A-Za-z0-9._\-]{0,63}$")


def _mint_trace_id() -> str:
    return uuid.uuid4().hex


def _engine_label(name) -> str:
    """``{engine="<name>"}`` with the value escaped per the Prometheus
    text format (backslash, quote, newline) — an engine name is an
    arbitrary user string and must not break the scrape."""
    if not name:
        return ""
    v = (str(name).replace("\\", r"\\").replace('"', r'\"')
         .replace("\n", r"\n"))
    return f'{{engine="{v}"}}'


class _Handler(BaseHTTPRequestHandler):
    protocol_version = "HTTP/1.1"

    @property
    def engine(self) -> Optional[InferenceEngine]:
        return self.server.engine

    @property
    def generation(self):
        return getattr(self.server, "generation", None)

    @property
    def registry(self) -> Optional[ModelRegistry]:
        return getattr(self.server, "registry", None)

    def log_message(self, fmt, *args):      # quiet by default
        if getattr(self.server, "verbose", False):
            super().log_message(fmt, *args)

    # -- distributed trace context -----------------------------------------
    def _bind_trace(self) -> str:
        """Adopt the caller's ``X-Trace-Id`` (mint a fresh one when the
        header is absent, malformed or oversized — never an error) and
        bind it to this handler thread, so every event emitted while
        handling — admission, enqueue, the engines' stamped copies —
        carries the id.  ``X-Parent-Span`` (the caller's span id)
        becomes the cross-process parent of this process's subtree."""
        raw = self.headers.get("X-Trace-Id")
        tid = raw if (raw and _TRACE_ID_RE.match(raw)) else _mint_trace_id()
        self._trace_id = tid
        parent = self.headers.get("X-Parent-Span")
        if parent is not None and not parent.isdigit():
            parent = None           # span ids are ints; drop garbage
        trc = obs_hook._tracer
        if trc is not None:
            trc.set_trace(tid, parent)
        return tid

    def _unbind_trace(self) -> None:
        trc = obs_hook._tracer
        if trc is not None:
            trc.clear_trace()

    def _reply(self, code: int, body: bytes, ctype: str = "application/json",
               extra_headers=()):
        self.send_response(code)
        self.send_header("Content-Type", ctype)
        self.send_header("Content-Length", str(len(body)))
        tid = getattr(self, "_trace_id", None)
        if tid is not None:         # echo so the caller learns minted ids
            self.send_header("X-Trace-Id", tid)
        for k, v in extra_headers:
            self.send_header(k, v)
        self.end_headers()
        self.wfile.write(body)

    def _reply_json(self, code: int, obj, extra_headers=()):
        self._reply(code, json.dumps(obj).encode(),
                    extra_headers=extra_headers)

    # -- chunked streaming (token streams) ---------------------------------
    def _start_chunked(self, code: int, ctype: str) -> None:
        self.send_response(code)
        self.send_header("Content-Type", ctype)
        self.send_header("Transfer-Encoding", "chunked")
        tid = getattr(self, "_trace_id", None)
        if tid is not None:
            self.send_header("X-Trace-Id", tid)
        self.end_headers()

    def _write_chunk(self, payload: bytes) -> None:
        self.wfile.write(f"{len(payload):X}\r\n".encode() + payload
                         + b"\r\n")

    def _end_chunked(self) -> None:
        # zero-length terminator: the connection stays keep-alive
        self.wfile.write(b"0\r\n\r\n")
        self.wfile.flush()

    def _reply_error(self, exc: BaseException):
        kind = type(exc).__name__
        payload = {"error": kind, "message": str(exc)}
        # ordering: UnknownModel/QuotaExceeded are ServingError
        # subclasses too — match the specific routing errors first
        if isinstance(exc, UnknownModel):
            self._reply_json(404, payload)
        elif isinstance(exc, QuotaExceeded):
            self._reply_json(429, payload, [("Retry-After", "1")])
        elif isinstance(exc, QueueFull):
            self._reply_json(503, payload, [("Retry-After", "0")])
        elif isinstance(exc, (DeadlineExceeded, TimeoutError,
                              concurrent.futures.TimeoutError)):
            # concurrent.futures.TimeoutError is NOT a builtin
            # TimeoutError subclass before Python 3.11
            self._reply_json(504, payload)
        elif isinstance(exc, EngineClosed):
            self._reply_json(503, payload)
        elif isinstance(exc, (ValueError, KeyError, json.JSONDecodeError)):
            self._reply_json(400, payload)
        else:
            self._reply_json(500, payload)

    def _weights_version(self) -> int:
        for src in (self.engine, self.generation):
            if src is not None:
                return int(getattr(src, "weights_version", 0))
        return 0

    # -- routes ------------------------------------------------------------
    def do_GET(self):
        self._bind_trace()
        try:
            self._route_get()
        finally:
            self._unbind_trace()

    def _route_get(self):
        path = self.path.split("?", 1)[0]
        if path == "/admin/fleet":
            self._do_fleet()
            return
        if path == "/admin/models":
            if self.registry is None:
                self._reply_json(501, {"error": "NotImplemented",
                                       "message": "no model registry "
                                                  "attached"})
            else:
                self._reply_json(200, self.registry.describe())
            return
        if path == "/healthz":
            src = self.engine if self.engine is not None else self.generation
            if src is None and self.registry is not None:
                # registry mode: alive while it routes to anything
                st = "running" if self.registry.models() else "empty"
            else:
                st = src.stats()["state"] if src is not None else "empty"
            wv = self._weights_version()
            retry = [("Retry-After", str(getattr(
                self.server, "retry_after_s", 1)))]
            if st not in ("running", "paused"):
                # liveness gone: draining / closing / closed
                self._reply_json(503, {"status": st, "ready": False,
                                       "weights_version": wv}, retry)
                return
            if not getattr(self.server, "ready", True):
                # alive but not yet (re-)warmed: readiness split — the
                # supervisor/load balancer holds traffic, the process
                # is NOT restarted
                self._reply_json(503, {"status": "warming",
                                       "engine_state": st,
                                       "ready": False,
                                       "weights_version": wv}, retry)
                return
            # liveness is fine; with an SLO monitor installed the probe
            # also polls the objectives — any breached burn-rate rule
            # degrades the reply to 503 with the reasons (the load
            # balancer sees the objective, not just liveness) and the
            # endpoint recovers to 200 as soon as the windows clear
            slo = _slo.slo_status()
            if slo.get("status") == "degraded":
                self._reply_json(503, {
                    "status": "degraded", "engine_state": st,
                    "ready": False, "weights_version": wv,
                    "slo": {"breached": slo.get("breached", []),
                            "reasons": slo.get("reasons", [])}}, retry)
            else:
                body = {"status": st, "ready": True,
                        "weights_version": wv}
                if slo.get("installed"):
                    body["slo"] = "ok"
                self._reply_json(200, body)
        elif path == "/perf":
            self._reply_json(200, {"perf": _perf.perf_report(),
                                   "slo": _slo.slo_status(poll=False)})
        elif path == "/metrics":
            accept = (self.headers.get("Accept") or "").lower()
            stats = (self.engine.stats() if self.engine is not None
                     else {"counters": {}})
            gen = self.generation
            if gen is not None:
                stats["generation"] = gen.stats()
            if self.registry is not None:
                stats["registry"] = self.registry.stats()
            if ("text/plain" in accept or "openmetrics" in accept
                    or "prometheus" in accept):
                from ..observability import prometheus_text
                # a named engine labels its gauges
                # (paddle_tpu_serving_engine_*{engine="<name>"}) so a
                # multi-model scrape can tell its engines apart
                ename = (getattr(self.engine, "name", None)
                         if self.engine is not None else None)
                lab = _engine_label(ename)
                gauges = {f"serving_engine_{k}{lab}": v
                          for k, v in stats.items()
                          if isinstance(v, (int, float))}
                gauges.update({f"serving_engine_{k}{lab}": v
                               for k, v in stats["counters"].items()})
                # the self-healing observables: what version this
                # replica serves and whether it should receive traffic
                st = stats.get("state",
                               self.generation.stats()["state"]
                               if self.engine is None and
                               self.generation is not None else "empty")
                if (self.engine is None and self.generation is None
                        and self.registry is not None
                        and self.registry.models()):
                    st = "running"
                ready = (getattr(self.server, "ready", True)
                         and st in ("running", "paused"))
                gauges[f"serving_weights_version{lab}"] = \
                    self._weights_version()
                gauges[f"serving_ready{lab}"] = 1 if ready else 0
                if gen is not None:
                    gs = stats["generation"]
                    gname = getattr(gen, "name", None)
                    glab = _engine_label(gname)
                    gauges.update({f"serving_decode_{k}{glab}": v
                                   for k, v in gs.items()
                                   if isinstance(v, (int, float))})
                    gauges.update({f"serving_decode_{k}{glab}": v
                                   for k, v in gs["counters"].items()})
                    gauges.update({f"serving_decode_pages_{k}{glab}": v
                                   for k, v in gs["page_pool"].items()})
                self._reply(200, prometheus_text(gauges).encode(),
                            ctype="text/plain; version=0.0.4; "
                                  "charset=utf-8")
            else:
                self._reply_json(200, stats)
        else:
            self._reply_json(404, {"error": "NotFound", "message": self.path})

    def do_POST(self):
        tid = self._bind_trace()
        path = self.path.split("?", 1)[0]
        trc = obs_hook._tracer
        sid = None
        if trc is not None and path in ("/generate", "/predict"):
            # the HTTP-accept span: the root of this process's subtree
            # for the request — closed when the response (streaming
            # included) is fully written
            sid = trc.begin_span("http" + path.replace("/", "."),
                                 method="POST", trace=tid)
        try:
            self._route_post()
        finally:
            if sid is not None:
                trc.end_span(sid)
            self._unbind_trace()

    def _route_post(self):
        path = self.path.split("?", 1)[0]
        if path == "/generate":
            self._do_generate()
            return
        if path == "/admin/models":
            self._do_admin()
            return
        if path == "/admin/trace":
            self._do_trace()
            return
        if path != "/predict":
            self._reply_json(404, {"error": "NotFound",
                                   "message": self.path})
            return
        reg = self.registry
        if reg is None and self.engine is None:
            self._reply_json(501, {"error": "NotImplemented",
                                   "message": "no inference engine "
                                              "attached"})
            return
        try:
            n = int(self.headers.get("Content-Length", "0"))
            body = self.rfile.read(n)
            ctype = (self.headers.get("Content-Type") or "").split(";")[0]
            model = self.headers.get("X-Model")
            tenant = self.headers.get("X-Tenant")
            if ctype == _NPY:
                arr = np.load(io.BytesIO(body), allow_pickle=False)
                inputs = [arr]
                deadline_ms = self.headers.get("X-Deadline-Ms")
                deadline_ms = float(deadline_ms) if deadline_ms else None
            else:
                payload = json.loads(body or b"{}")
                if "inputs" not in payload:
                    raise ValueError('body must carry "inputs"')
                inputs = payload["inputs"]
                deadline_ms = payload.get("deadline_ms")
                model = payload.get("model") or model
                tenant = payload.get("tenant") or tenant
            timeout = self.server.request_timeout
            if reg is not None:
                # registry routing: model/alias resolution, shed flag,
                # tenant quota and WFQ share all sit in front of the
                # routed engine's own queue
                eng = reg.resolve(model).engine
                if eng is None:
                    raise UnknownModel(
                        f"model {model!r} has no inference engine")
                outs = reg.infer(model, inputs, tenant=tenant,
                                 deadline_ms=deadline_ms).result(timeout)
            else:
                eng = self.engine
                outs = eng.infer_sync(inputs, deadline_ms=deadline_ms,
                                      timeout=timeout)
        except Exception as e:              # noqa: BLE001 - mapped to HTTP
            self._reply_error(e)
            return
        if ctype == _NPY:
            buf = io.BytesIO()
            np.save(buf, outs[0], allow_pickle=False)
            self._reply(200, buf.getvalue(), ctype=_NPY)
        else:
            self._reply_json(200, {
                "outputs": [o.tolist() for o in outs],
                "names": eng._pred.get_output_names(),
                "dtypes": [str(o.dtype) for o in outs],
            })

    def _do_generate(self):
        import queue as _queue
        reg = self.registry
        gen = self.generation
        if gen is None and reg is None:
            self._reply_json(501, {"error": "NotImplemented",
                                   "message": "no generation engine "
                                              "attached"})
            return
        try:
            n = int(self.headers.get("Content-Length", "0"))
            payload = json.loads(self.rfile.read(n) or b"{}")
            if "prompt" not in payload:
                raise ValueError('body must carry "prompt"')
            stream_mode = bool(payload.get("stream", True))
            kw = {}
            for k in ("max_new_tokens", "eos_id", "temperature", "seed",
                      "deadline_ms"):
                if payload.get(k) is not None:
                    kw[k] = payload[k]
            if reg is not None:
                model = (payload.get("model")
                         or self.headers.get("X-Model"))
                tenant = (payload.get("tenant")
                          or self.headers.get("X-Tenant"))
                s = reg.generate(model, payload["prompt"],
                                 tenant=tenant, **kw)
            else:
                s = gen.generate(payload["prompt"], **kw)
        except Exception as e:          # noqa: BLE001 - mapped to HTTP
            self._reply_error(e)
            return
        timeout = self.server.request_timeout
        if not stream_mode:
            try:
                toks = s.result(timeout=timeout)
            except Exception as e:      # noqa: BLE001 - mapped to HTTP
                self._reply_error(e)
                return
            self._reply_json(200, {"tokens": toks,
                                   "finish_reason": s.finish_reason,
                                   "sid": s.sid})
            return
        # admission succeeded: stream tokens as the scheduler emits
        # them; anything that goes wrong PAST this point arrives
        # in-band (the status line is already on the wire)
        self._start_chunked(200, "application/x-ndjson")
        try:
            try:
                for tok in s.tokens(timeout=timeout):
                    self._write_chunk(
                        json.dumps({"token": int(tok)}).encode() + b"\n")
                summary = {"done": True, "tokens": s.result(0),
                           "finish_reason": s.finish_reason,
                           "sid": s.sid}
                self._write_chunk(json.dumps(summary).encode() + b"\n")
            except Exception as e:      # noqa: BLE001 - sent in-band
                kind = ("TimeoutError" if isinstance(e, _queue.Empty)
                        else type(e).__name__)
                self._write_chunk(json.dumps(
                    {"error": kind, "message": str(e)}).encode() + b"\n")
            self._end_chunked()
        except (BrokenPipeError, ConnectionError):
            pass                        # client went away mid-stream

    def _do_fleet(self):
        """``GET /admin/fleet``: the aggregated per-replica view.  With
        a :class:`~paddle_tpu.observability.fleet.FleetView` attached
        (``ServingServer(..., fleet=...)``), its live scrape of every
        registered replica set; otherwise the spool-level summary, so
        a lone replica with spooling on still answers usefully."""
        try:
            fv = getattr(self.server, "fleet", None)
            if fv is not None:
                self._reply_json(200, fv.snapshot())
                return
            from ..observability import fleet as _fleet
            snap = _fleet.fleet_snapshot()
            self._reply_json(200, {
                "time": snap["time"], "fleet": {},
                "spool": {"procs": sorted(snap["procs"]),
                          "build_skew": snap["build_skew"]}})
        except Exception as e:          # noqa: BLE001 - mapped to HTTP
            self._reply_error(e)

    def _do_trace(self):
        """``POST /admin/trace?secs=N``: capture ``N`` seconds of fleet
        activity (bounded; 0 = everything currently buffered/spooled)
        and return the merged chrome-trace JSON — one lane per process,
        loadable straight into Perfetto."""
        try:
            q = parse_qs(self.path.partition("?")[2])
            secs = float(q.get("secs", ["0"])[0])
        except (TypeError, ValueError):
            self._reply_json(400, {"error": "ValueError",
                                   "message": "secs must be a number"})
            return
        secs = max(0.0, min(secs, 60.0))
        t0 = time.time()
        if secs > 0:
            time.sleep(secs)
        try:
            exp = obs_hook._export
            if exp is not None:
                exp.flush()         # this process's lane must be current
            from ..observability import fleet as _fleet
            trace = _fleet.merged_chrome_trace(
                since_time=t0 if secs > 0 else None)
            self._reply_json(200, trace)
        except Exception as e:          # noqa: BLE001 - mapped to HTTP
            self._reply_error(e)

    def _do_admin(self):
        """``POST /admin/models``: registry control actions.  Missing
        fields map to 400 (KeyError), unknown names to 404, so a fat-
        fingered admin call can never crash the data plane."""
        reg = self.registry
        if reg is None:
            self._reply_json(501, {"error": "NotImplemented",
                                   "message": "no model registry "
                                              "attached"})
            return
        try:
            n = int(self.headers.get("Content-Length", "0"))
            p = json.loads(self.rfile.read(n) or b"{}")
            action = p.get("action")
            if action == "load":
                entry = reg.load(
                    p["name"], p["artifact"],
                    weights_dir=p.get("weights_dir"),
                    aliases=p.get("aliases", ()),
                    weight=float(p.get("weight", 1.0)),
                    warmup=bool(p.get("warmup", True)),
                    rest_shapes=p.get("rest_shapes"),
                    engine_kwargs=p.get("engine_kwargs"))
                self._reply_json(200, {"loaded": p["name"],
                                       "state": entry.state})
            elif action == "unload":
                self._reply_json(200, reg.unload(
                    p["name"], timeout=float(p.get("timeout", 30.0))))
            elif action == "alias":
                reg.alias(p["alias"], p["target"])
                self._reply_json(200, {"alias": p["alias"],
                                       "target": p["target"]})
            elif action == "unalias":
                reg.unalias(p["alias"])
                self._reply_json(200, {"unalias": p["alias"]})
            elif action == "quota":
                reg.set_quota(p["tenant"], float(p["rate"]),
                              p.get("burst"))
                self._reply_json(200, {"tenant": p["tenant"],
                                       "rate": float(p["rate"])})
            elif action == "weight":
                reg.set_weight(p["name"], float(p["weight"]))
                self._reply_json(200, {"model": p["name"],
                                       "weight": float(p["weight"])})
            elif action == "default":
                reg.set_default(p["name"])
                self._reply_json(200, {"default": p["name"]})
            else:
                raise ValueError(f"unknown admin action {action!r}")
        except Exception as e:          # noqa: BLE001 - mapped to HTTP
            self._reply_error(e)


class ServingServer:
    """Threaded HTTP server bound to one engine.

    ``port=0`` picks a free port (read it back via ``.port``).  The
    server owns only the HTTP layer: ``close()`` stops accepting
    connections but leaves the engine to its owner (``tools/serve.py``
    closes both)."""

    def __init__(self, engine: Optional[InferenceEngine],
                 host: str = "127.0.0.1",
                 port: int = 8000, request_timeout: float = 60.0,
                 verbose: bool = False, generation=None,
                 ready: bool = True, retry_after_s: float = 1.0,
                 registry: Optional[ModelRegistry] = None,
                 fleet=None):
        if engine is None and generation is None and registry is None:
            raise ValueError("attach an InferenceEngine, a "
                             "GenerationEngine, a ModelRegistry, or a "
                             "combination")
        self._httpd = ThreadingHTTPServer((host, port), _Handler)
        self._httpd.daemon_threads = True
        self._httpd.engine = engine
        self._httpd.generation = generation
        # a registry takes over /predict + /generate routing and
        # enables the /admin/models control plane; a direct engine/
        # generation may still be attached (it serves /metrics detail)
        self._httpd.registry = registry
        # a FleetView (observability.fleet) turns on GET /admin/fleet's
        # live per-replica aggregation; without one the route degrades
        # to the spool-level summary
        self._httpd.fleet = fleet
        self._httpd.request_timeout = request_timeout
        self._httpd.verbose = verbose
        # readiness split: ``ready=False`` lets a supervised replica
        # bind its port early (liveness probes answer) and admit
        # traffic only after warmup via mark_ready(); retry_after_s is
        # the Retry-After hint on every 503 probe
        self._httpd.ready = bool(ready)
        self._httpd.retry_after_s = retry_after_s
        self._thread: Optional[threading.Thread] = None

    @property
    def host(self) -> str:
        return self._httpd.server_address[0]

    @property
    def port(self) -> int:
        return self._httpd.server_address[1]

    @property
    def url(self) -> str:
        return f"http://{self.host}:{self.port}"

    @property
    def ready(self) -> bool:
        return self._httpd.ready

    @property
    def fleet(self):
        return self._httpd.fleet

    def attach_fleet(self, fleet) -> None:
        """Attach/replace the :class:`FleetView` behind
        ``GET /admin/fleet`` (None detaches)."""
        self._httpd.fleet = fleet

    def mark_ready(self) -> None:
        """Readiness gate up: warmup (or re-warm after a supervised
        restart) is done — /healthz turns 200 and traffic may land."""
        self._httpd.ready = True

    def mark_unready(self) -> None:
        """Readiness gate down without killing liveness: /healthz turns
        503 + Retry-After while the engine keeps finishing accepted
        work (drain windows, planned restarts)."""
        self._httpd.ready = False

    def start(self) -> "ServingServer":
        self._thread = threading.Thread(target=self._httpd.serve_forever,
                                        name="serving-http", daemon=True)
        self._thread.start()
        return self

    def serve_forever(self) -> None:
        self._httpd.serve_forever()

    def close(self) -> None:
        self._httpd.ready = False
        self._httpd.shutdown()
        self._httpd.server_close()
        if self._thread is not None:
            self._thread.join(5.0)

    def __enter__(self):
        return self.start()

    def __exit__(self, *exc_info):
        self.close()
        return False


def serve(engine: InferenceEngine, host: str = "127.0.0.1",
          port: int = 8000, verbose: bool = True) -> None:
    """Blocking convenience: serve until KeyboardInterrupt, then drain."""
    srv = ServingServer(engine, host, port, verbose=verbose)
    try:
        srv.serve_forever()
    except KeyboardInterrupt:
        pass
    finally:
        srv.close()
        engine.close()


class Client:
    """Stdlib client for the HTTP front-end, with keep-alive reuse.

    Each thread holds ONE persistent ``http.client.HTTPConnection``
    (the server speaks HTTP/1.1 with Content-Length or chunked bodies,
    so connections survive across requests) — closed-loop bench/smoke
    clients pay connection setup once, not per request.  A stale pooled
    connection (server restarted, idle timeout) is dropped and the
    request retried once on a fresh connection; ``connections_opened``
    counts physical connects across all threads (the reuse gate's
    observable).

    **Restart ride-through.**  When the FRESH connection also fails
    (refused/reset — the replica is mid-restart under a supervisor,
    not merely holding a stale socket), the client backs off once with
    jitter — honoring the server's last ``Retry-After`` hint — and
    retries on another fresh connection.  Each such recovery counts as
    ``client.reconnects`` (``self.reconnects`` + the monitor stat), so
    a supervised restart window costs a bounded delay instead of a
    hard failure.  Requests are idempotent (inference is pure,
    generation deterministic), so the replay is safe.

    503/504 responses are raised as the matching engine exceptions
    (:class:`QueueFull` / :class:`DeadlineExceeded` / ...), so a caller
    can back off on shed exactly as an in-process caller would."""

    def __init__(self, base_url: str, timeout: float = 60.0,
                 reconnect_backoff_s: float = 0.2,
                 model: Optional[str] = None,
                 tenant: Optional[str] = None,
                 trace_id: Optional[str] = None):
        # multi-model routing: ``model`` pins every request from this
        # client to one registry entry (per-call ``model=`` overrides);
        # ``tenant`` attributes them to a quota bucket.  Both are None
        # for single-model servers — the wire format is unchanged.
        self.model = model
        self.tenant = tenant
        # distributed tracing: every request carries an ``X-Trace-Id``
        # — ``trace_id`` pins one id to every request from this client;
        # None (default) mints one per request.  The id used by the
        # most recent call is kept on ``last_trace_id`` so a caller can
        # correlate its request with the fleet timeline.
        self.trace_id = trace_id
        self.last_trace_id: Optional[str] = None
        self.base_url = base_url.rstrip("/")
        u = urlsplit(self.base_url)
        if u.scheme not in ("http", ""):
            raise ValueError(f"unsupported scheme {u.scheme!r}")
        self._host = u.hostname or "127.0.0.1"
        self._port = u.port or 80
        self.timeout = timeout
        self.reconnect_backoff_s = float(reconnect_backoff_s)
        self._local = threading.local()
        self._count_lock = threading.Lock()
        self.connections_opened = 0
        self.reconnects = 0
        self._retry_after = 0.0     # last Retry-After the server sent

    # -- connection pool (one per thread) ----------------------------------
    def _conn(self) -> httpclient.HTTPConnection:
        c = getattr(self._local, "conn", None)
        if c is None:
            c = httpclient.HTTPConnection(self._host, self._port,
                                          timeout=self.timeout)
            self._local.conn = c
            with self._count_lock:
                self.connections_opened += 1
        return c

    def _drop_conn(self) -> None:
        c = getattr(self._local, "conn", None)
        if c is not None:
            try:
                c.close()
            except Exception:
                pass
            self._local.conn = None

    def close(self) -> None:
        """Close this thread's pooled connection (other threads' pools
        close when their threads die or on their own ``close()``)."""
        self._drop_conn()

    def __enter__(self):
        return self

    def __exit__(self, *exc_info):
        self.close()
        return False

    def _request(self, method: str, path: str, body: Optional[bytes]
                 = None, headers: Optional[dict] = None
                 ) -> httpclient.HTTPResponse:
        """One round trip on the pooled connection; retries once on a
        stale keep-alive socket, and once more — after a jittered
        backoff that honors the server's last ``Retry-After`` — when
        the fresh connection also failed (a supervised replica
        restart; see the class docstring).  (Serving requests are
        idempotent — inference is pure and generation is deterministic
        — so the replay is safe.)  A *timeout* is never replayed: the
        server is slow, not gone, and a replay would double its work
        while masking the real condition.  The caller must fully read
        the response."""
        headers = dict(headers or {})
        last: Optional[BaseException] = None
        for attempt in (0, 1, 2):
            c = self._conn()
            try:
                c.request(method, path, body=body, headers=headers)
                return c.getresponse()
            except (httpclient.HTTPException, ConnectionError,
                    BrokenPipeError, OSError) as e:
                self._drop_conn()
                if isinstance(e, TimeoutError):
                    raise               # slow server: surface, don't replay
                last = e
                if attempt == 1:
                    # attempt 0 may have been a stale pooled socket, but
                    # attempt 1 was a FRESH connection: the replica is
                    # down (restart window) — back off once, jittered,
                    # before the final try
                    delay = max(self._retry_after,
                                self.reconnect_backoff_s)
                    time.sleep(delay * (0.5 + random.random()))
                    with self._count_lock:
                        self.reconnects += 1
                    monitor.stat_add("client.reconnects")
        raise ServingError(f"connection to {self.base_url} failed: "
                           f"{type(last).__name__}: {last}") from last

    def _finish(self, r: httpclient.HTTPResponse) -> None:
        """Keep the connection reusable — or drop it when the server
        asked to close.  Also notes any ``Retry-After`` hint (it
        floors the reconnect backoff)."""
        ra = r.getheader("Retry-After")
        if ra is not None:
            try:
                self._retry_after = float(ra)
            except (TypeError, ValueError):
                pass
        if r.will_close:
            self._drop_conn()

    def _raise_for(self, status: int, raw: bytes):
        try:
            payload = json.loads(raw.decode() or "{}")
        except Exception:
            payload = {}
        kind = payload.get("error", "")
        msg = payload.get("message", "")
        for cls in (QueueFull, DeadlineExceeded, EngineClosed,
                    UnknownModel, QuotaExceeded):
            if kind == cls.__name__:
                raise cls(msg) from None
        raise ServingError(f"HTTP {status}: {kind or ''} {msg}")

    def _route(self, body: dict, model: Optional[str],
               tenant: Optional[str]) -> dict:
        """Stamp multi-model routing fields (per-call override, then
        the client defaults) into a JSON request body."""
        m = model if model is not None else self.model
        t = tenant if tenant is not None else self.tenant
        if m is not None:
            body["model"] = m
        if t is not None:
            body["tenant"] = t
        return body

    def _trace_begin(self, path: str, headers: dict):
        """Stamp distributed-trace headers onto one logical request —
        BEFORE :meth:`_request`'s retry loop, so a reconnect replay
        (supervised replica restart) carries the SAME trace id and the
        ride-through renders as one request on the fleet timeline.
        When tracing is on in this process, a ``client<path>`` span
        opens and its id rides ``X-Parent-Span`` — the server's subtree
        hangs off it across the process hop.  Returns ``(tracer, span
        id)`` for :meth:`_trace_end` (both None when tracing is off)."""
        tid = self.trace_id or _mint_trace_id()
        self.last_trace_id = tid
        headers["X-Trace-Id"] = tid
        trc = obs_hook._tracer
        if trc is None:
            return None, None
        sid = trc.begin_span("client" + path.replace("/", "."),
                             trace=tid)
        headers["X-Parent-Span"] = str(sid)
        trc.set_trace(tid)
        return trc, sid

    @staticmethod
    def _trace_end(trc, sid) -> None:
        if trc is not None:
            trc.end_span(sid)
            trc.clear_trace()

    def _post(self, path: str, body: bytes, headers: dict) -> bytes:
        headers = dict(headers)
        trc, sid = self._trace_begin(path, headers)
        try:
            r = self._request("POST", path, body=body, headers=headers)
            raw = r.read()
            self._finish(r)
        finally:
            self._trace_end(trc, sid)
        if r.status >= 400:
            self._raise_for(r.status, raw)
        return raw

    def _get_json(self, path: str, headers: Optional[dict] = None):
        headers = dict(headers or {})
        trc, sid = self._trace_begin(path, headers)
        try:
            r = self._request("GET", path, headers=headers)
            raw = r.read()
            self._finish(r)
        finally:
            self._trace_end(trc, sid)
        if r.status >= 400:
            if path == "/healthz":      # 503 healthz still carries status
                try:
                    return json.loads(raw.decode())
                except Exception:
                    pass
            self._raise_for(r.status, raw)
        return json.loads(raw.decode())

    def predict(self, inputs, deadline_ms: Optional[float] = None,
                model: Optional[str] = None,
                tenant: Optional[str] = None) -> List[np.ndarray]:
        """JSON round trip; returns host arrays with the server dtypes.

        Wire format (unambiguous by construction): ``inputs`` is ALWAYS
        a list of per-input arrays or a name->array dict.  A bare
        ndarray argument is wrapped as the single input; a bare
        list/tuple argument is interpreted as the per-input list."""
        if isinstance(inputs, dict):
            payload = {k: np.asarray(v).tolist() for k, v in inputs.items()}
        else:
            if isinstance(inputs, np.ndarray) or not isinstance(
                    inputs, (list, tuple)):
                inputs = [inputs]
            payload = [np.asarray(a).tolist() for a in inputs]
        body = self._route({"inputs": payload}, model, tenant)
        if deadline_ms is not None:
            body["deadline_ms"] = deadline_ms
        raw = self._post("/predict", json.dumps(body).encode(),
                         {"Content-Type": "application/json"})
        res = json.loads(raw.decode())
        return [np.asarray(o, dtype=np.dtype(dt))
                for o, dt in zip(res["outputs"], res["dtypes"])]

    def predict_npy(self, arr: np.ndarray,
                    deadline_ms: Optional[float] = None,
                    model: Optional[str] = None,
                    tenant: Optional[str] = None) -> np.ndarray:
        buf = io.BytesIO()
        np.save(buf, np.asarray(arr), allow_pickle=False)
        headers = {"Content-Type": _NPY}
        if deadline_ms is not None:
            headers["X-Deadline-Ms"] = str(deadline_ms)
        # npy bodies have no JSON envelope: routing rides the headers
        m = model if model is not None else self.model
        t = tenant if tenant is not None else self.tenant
        if m is not None:
            headers["X-Model"] = m
        if t is not None:
            headers["X-Tenant"] = t
        raw = self._post("/predict", buf.getvalue(), headers)
        return np.load(io.BytesIO(raw), allow_pickle=False)

    def healthz(self) -> dict:
        return self._get_json("/healthz")

    def metrics(self) -> dict:
        return self._get_json("/metrics")

    def perf(self) -> dict:
        """The server's ``/perf`` drift report + last SLO evaluation."""
        return self._get_json("/perf")

    def metrics_text(self) -> str:
        """Prometheus text exposition (the scraper's view of /metrics)."""
        headers = {"Accept": "text/plain"}
        trc, sid = self._trace_begin("/metrics", headers)
        try:
            r = self._request("GET", "/metrics", headers=headers)
            raw = r.read()
            self._finish(r)
        finally:
            self._trace_end(trc, sid)
        if r.status >= 400:
            self._raise_for(r.status, raw)
        return raw.decode()

    # -- generation --------------------------------------------------------
    def _generate_body(self, prompt, stream: bool, kw: dict,
                       model: Optional[str] = None,
                       tenant: Optional[str] = None) -> bytes:
        body = {"prompt": [int(t) for t in np.asarray(prompt).reshape(-1)],
                "stream": stream}
        body.update({k: v for k, v in kw.items() if v is not None})
        return json.dumps(self._route(body, model, tenant)).encode()

    def generate(self, prompt, max_new_tokens: int = 32,
                 eos_id: Optional[int] = None, temperature: float = 0.0,
                 seed: int = 0,
                 deadline_ms: Optional[float] = None,
                 model: Optional[str] = None,
                 tenant: Optional[str] = None) -> List[int]:
        """Blocking generation; returns the full token list."""
        raw = self._post("/generate", self._generate_body(
            prompt, False, {"max_new_tokens": max_new_tokens,
                            "eos_id": eos_id, "temperature": temperature,
                            "seed": seed, "deadline_ms": deadline_ms},
            model, tenant),
            {"Content-Type": "application/json"})
        return list(json.loads(raw.decode())["tokens"])

    def generate_stream(self, prompt, max_new_tokens: int = 32,
                        eos_id: Optional[int] = None,
                        temperature: float = 0.0, seed: int = 0,
                        deadline_ms: Optional[float] = None,
                        model: Optional[str] = None,
                        tenant: Optional[str] = None
                        ) -> Iterator[int]:
        """Yield tokens as the server streams them (chunked NDJSON).

        In-band server errors re-raise as the matching engine
        exceptions.  Abandoning the iterator mid-stream drops the
        pooled connection (it would otherwise carry unread chunks)."""
        headers = {"Content-Type": "application/json"}
        trc, sid = self._trace_begin("/generate", headers)
        try:
            r = self._request("POST", "/generate", self._generate_body(
                prompt, True, {"max_new_tokens": max_new_tokens,
                               "eos_id": eos_id,
                               "temperature": temperature,
                               "seed": seed, "deadline_ms": deadline_ms},
                model, tenant),
                headers)
            if r.status >= 400:
                raw = r.read()
                self._finish(r)
                self._raise_for(r.status, raw)
            done = False
            try:
                while True:
                    line = r.readline()
                    if not line:
                        break
                    msg = json.loads(line.decode())
                    if "token" in msg:
                        yield int(msg["token"])
                    elif "error" in msg:
                        self._raise_for(200, line)
                    if msg.get("done"):
                        break
                # drain the terminating chunk so the socket is clean
                while r.readline():
                    pass
                done = True
            finally:
                if done:
                    self._finish(r)
                else:       # abandoned/errored mid-stream: unread data
                    self._drop_conn()
        finally:
            self._trace_end(trc, sid)

    # -- model registry admin ----------------------------------------------
    def _admin(self, payload: dict) -> dict:
        raw = self._post("/admin/models", json.dumps(payload).encode(),
                         {"Content-Type": "application/json"})
        return json.loads(raw.decode())

    def admin_models(self) -> dict:
        """``GET /admin/models``: states, versions, engines, aliases,
        in-flight counts, quotas."""
        return self._get_json("/admin/models")

    def load_model(self, name: str, artifact: str, **kw) -> dict:
        """Load + warm an artifact under ``name``; extra kwargs pass
        through to :meth:`ModelRegistry.load` (``weights_dir``,
        ``aliases``, ``weight``, ``rest_shapes``, ...)."""
        return self._admin({"action": "load", "name": name,
                            "artifact": artifact, **kw})

    def unload_model(self, name: str, timeout: float = 30.0) -> dict:
        """Unload ``name``; returns the drain/page-pool summary."""
        return self._admin({"action": "unload", "name": name,
                            "timeout": timeout})

    def alias_model(self, alias: str, target: str) -> dict:
        return self._admin({"action": "alias", "alias": alias,
                            "target": target})

    def set_quota(self, tenant: str, rate: float,
                  burst: Optional[float] = None) -> dict:
        return self._admin({"action": "quota", "tenant": tenant,
                            "rate": rate, "burst": burst})
