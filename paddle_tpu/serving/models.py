"""Reference generative model for the paged decode contract.

:class:`PagedDecoderLM` is a minimal functional transformer decoder that
implements the two-method contract
:class:`~paddle_tpu.serving.generation.GenerationEngine` drives:

- ``prefill(tokens, length, kv, page_table)`` — dense causal attention
  over one (padded) prompt, writing every position's K/V into the
  sequence's pages, returning the logits at the last valid position;
- ``decode(tokens, positions, kv, page_tables)`` — one token per active
  slot, K/V scattered into pages, attention via
  :func:`paddle_tpu.ops.attention.paged_attention` over the page table.

It is deliberately tiny and dependency-free (a params dict of jnp
arrays, no Layer machinery) so bench/chaos/smoke can build it in
milliseconds; ``dyadic=True`` rounds every weight to k/64 so float
accumulation stays exactly reproducible across batch compositions (the
serving chaos suite's bitwise trick).  It also exposes the
``BeamSearchDecoder`` cell contract (:meth:`cell` /
:meth:`init_cell_state`) over a dense padded KV cache — the per-request
``dynamic_decode`` baseline the ISSUE benchmarks the engine against.
"""
from __future__ import annotations

import math
from typing import Dict, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..ops import attention as _attn
from .kv_cache import write_prompt, write_token

__all__ = ["PagedDecoderLM"]

_NEG = -1e30


class PagedDecoderLM:
    """Pre-norm-free residual transformer LM over raw jnp params.

    Geometry attributes (``num_layers`` / ``num_kv_heads`` /
    ``head_dim`` / ``vocab_size``) are the engine's KV-cache contract.
    """

    def __init__(self, vocab_size: int = 64, hidden: int = 32,
                 num_layers: int = 2, num_heads: int = 4,
                 num_kv_heads: int = 0, ffn: int = 0, seed: int = 0,
                 dyadic: bool = False):
        if hidden % num_heads:
            raise ValueError("hidden must divide by num_heads")
        self.vocab_size = int(vocab_size)
        self.hidden = int(hidden)
        self.num_layers = int(num_layers)
        self.num_heads = int(num_heads)
        self.num_kv_heads = int(num_kv_heads) or int(num_heads)
        if self.num_heads % self.num_kv_heads:
            raise ValueError("num_heads must divide by num_kv_heads")
        self.head_dim = self.hidden // self.num_heads
        self.ffn = int(ffn) or 2 * self.hidden
        rng = np.random.RandomState(seed)
        E, F, V = self.hidden, self.ffn, self.vocab_size
        kvd = self.num_kv_heads * self.head_dim

        def w(shape, fan_in):
            a = rng.standard_normal(shape).astype(np.float32)
            a *= 1.0 / math.sqrt(fan_in)
            if dyadic:
                # weights on the k/64 dyadic grid: products/sums with
                # dyadic activations are exact in f32 (chaos bitwise gate)
                a = np.round(a * 64.0) / 64.0
            return jnp.asarray(a)

        p: Dict[str, jnp.ndarray] = {"embed": w((V, E), E)}
        for l in range(self.num_layers):
            p[f"wq{l}"] = w((E, E), E)
            p[f"wk{l}"] = w((E, kvd), E)
            p[f"wv{l}"] = w((E, kvd), E)
            p[f"wo{l}"] = w((E, E), E)
            p[f"w1{l}"] = w((E, F), E)
            p[f"w2{l}"] = w((F, E), F)
        self.params = p
        self._scale = 1.0 / math.sqrt(self.head_dim)

    # -- shared pieces -----------------------------------------------------
    def _qkv(self, x, l):
        """x: [..., E] -> q [..., H, D], k/v [..., Hkv, D]."""
        p = self.params
        lead = x.shape[:-1]
        q = (x @ p[f"wq{l}"]).reshape(lead + (self.num_heads,
                                              self.head_dim))
        k = (x @ p[f"wk{l}"]).reshape(lead + (self.num_kv_heads,
                                              self.head_dim))
        v = (x @ p[f"wv{l}"]).reshape(lead + (self.num_kv_heads,
                                              self.head_dim))
        return q, k, v

    def _mlp_residual(self, x, attn_out, l):
        p = self.params
        x = x + attn_out.reshape(x.shape) @ p[f"wo{l}"]
        return x + jax.nn.relu(x @ p[f"w1{l}"]) @ p[f"w2{l}"]

    def _group(self, kv):
        """Broadcast KV heads over query-head groups (GQA)."""
        if self.num_kv_heads == self.num_heads:
            return kv
        return jnp.repeat(kv, self.num_heads // self.num_kv_heads,
                          axis=-2)

    # -- paged contract (GenerationEngine) ---------------------------------
    def prefill(self, tokens, length, kv, page_table
                ) -> Tuple[jnp.ndarray, Tuple[jnp.ndarray, jnp.ndarray]]:
        """tokens: [T] int32 (padded prompt); length: int32 scalar;
        kv: (k_pool, v_pool) [L, N, page, Hkv, D]; page_table: [P] int32.
        Returns (logits [V] at position length-1, updated kv)."""
        k_pool, v_pool = kv
        T = tokens.shape[0]
        x = self.params["embed"][tokens]                    # [T, E]
        pos = jnp.arange(T, dtype=jnp.int32)
        # causal AND length-bounded: key j visible to query i iff
        # j <= i and j < length
        mask = (pos[None, :] <= pos[:, None]) & (pos[None, :] < length)
        for l in range(self.num_layers):
            q, k, v = self._qkv(x, l)                       # [T, H/Hkv, D]
            k_pool = write_prompt(k_pool, l, k, page_table, length)
            v_pool = write_prompt(v_pool, l, v, page_table, length)
            kk, vv = self._group(k), self._group(v)         # [T, H, D]
            s = jnp.einsum("ihd,jhd->hij", q.astype(jnp.float32),
                           kk.astype(jnp.float32)) * self._scale
            s = jnp.where(mask[None], s, _NEG)
            w = jax.nn.softmax(s, axis=-1)
            attn = jnp.einsum("hij,jhd->ihd", w,
                              vv.astype(jnp.float32)).astype(x.dtype)
            x = self._mlp_residual(x, attn, l)
        last = jnp.take(x, length - 1, axis=0)              # [E]
        return last @ self.params["embed"].T, (k_pool, v_pool)

    def decode(self, tokens, positions, kv, page_tables
               ) -> Tuple[jnp.ndarray, Tuple[jnp.ndarray, jnp.ndarray]]:
        """tokens/positions: [S] int32; page_tables: [S, P] int32.
        Returns (logits [S, V], updated kv)."""
        k_pool, v_pool = kv
        x = self.params["embed"][tokens]                    # [S, E]
        lengths = positions + 1
        for l in range(self.num_layers):
            q, k, v = self._qkv(x, l)
            k_pool = write_token(k_pool, l, k, page_tables, positions)
            v_pool = write_token(v_pool, l, v, page_tables, positions)
            # tier selection: the registered Pallas decode kernel when
            # the gate accepts (TPU / explicit interpret opt-in), else
            # the gather reference — resolved at trace time, so the
            # compiled decode step bakes one tier in
            attn = _attn.paged_attention_select(
                q, k_pool, v_pool, page_tables, lengths,
                scale=self._scale, layer=l)
            x = self._mlp_residual(x, attn, l)
        return x @ self.params["embed"].T, (k_pool, v_pool)

    # -- BeamSearchDecoder cell contract (dynamic_decode baseline) ---------
    def init_cell_state(self, prompt, t_max: int):
        """Dense-cache prefill for the per-request baseline.

        Feeds ``prompt[:-1]`` through the network (the last prompt token
        becomes ``dynamic_decode``'s start token), caching K/V into
        fixed [1, L, t_max, Hkv, D] buffers.  Returns the cell-state
        pytree (leading batch dim 1) for ``dynamic_decode(inits=...)``.
        """
        prompt = np.asarray(prompt, np.int32).reshape(-1)
        if prompt.size < 1:
            raise ValueError("prompt must carry at least one token")
        n_ctx = prompt.size - 1
        t_max = int(t_max)
        if n_ctx > t_max:
            raise ValueError(f"prompt needs {n_ctx} cache rows, "
                             f"t_max={t_max}")
        L, Hkv, D = self.num_layers, self.num_kv_heads, self.head_dim
        k_cache = jnp.zeros((1, L, t_max, Hkv, D), jnp.float32)
        v_cache = jnp.zeros((1, L, t_max, Hkv, D), jnp.float32)
        if n_ctx:
            x = self.params["embed"][jnp.asarray(prompt[:-1])]  # [n, E]
            pos = jnp.arange(n_ctx)
            mask = pos[None, :] <= pos[:, None]
            for l in range(self.num_layers):
                q, k, v = self._qkv(x, l)
                k_cache = k_cache.at[0, l, :n_ctx].set(k)
                v_cache = v_cache.at[0, l, :n_ctx].set(v)
                kk, vv = self._group(k), self._group(v)
                s = jnp.einsum("ihd,jhd->hij", q.astype(jnp.float32),
                               kk.astype(jnp.float32)) * self._scale
                s = jnp.where(mask[None], s, _NEG)
                w = jax.nn.softmax(s, axis=-1)
                attn = jnp.einsum("hij,jhd->ihd", w,
                                  vv.astype(jnp.float32)).astype(x.dtype)
                x = self._mlp_residual(x, attn, l)
        return {"k": k_cache, "v": v_cache,
                "pos": jnp.full((1,), n_ctx, jnp.int32),
                "gen": jnp.zeros((1,), jnp.int32),
                "limit": jnp.full((1,), 0, jnp.int32)}

    def make_cell(self, eos_id: int):
        """A ``cell(tokens, states) -> (logits, states)`` closure over a
        dense padded KV cache — the BeamSearchDecoder contract.  When a
        row's ``gen`` count reaches its ``limit``, logits collapse onto
        ``eos_id`` so dynamic_decode's early exit ends the row (this is
        how one compiled trace serves ragged per-request budgets)."""
        from ..core.tensor import Tensor

        def _arr(t):
            return t.data if isinstance(t, Tensor) else jnp.asarray(t)

        def cell(tok, states):
            x0 = _arr(tok).astype(jnp.int32)                # [N]
            st = {k: _arr(v) for k, v in states.items()}
            k_cache, v_cache = st["k"], st["v"]             # [N,L,T,Hkv,D]
            pos, gen, limit = st["pos"], st["gen"], st["limit"]
            N, _, T = k_cache.shape[:3]
            x = self.params["embed"][x0]                    # [N, E]
            onehot = (jnp.arange(T)[None, :] == pos[:, None])   # [N, T]
            visible = (jnp.arange(T)[None, :] <= pos[:, None])
            for l in range(self.num_layers):
                q, k, v = self._qkv(x, l)                   # [N, Hkv, D]
                # write this token's K/V at pos (O(T) masked update —
                # the dense baseline's inherent raggedness tax)
                k_cache = k_cache.at[:, l].set(
                    jnp.where(onehot[:, :, None, None],
                              k[:, None], k_cache[:, l]))
                v_cache = v_cache.at[:, l].set(
                    jnp.where(onehot[:, :, None, None],
                              v[:, None], v_cache[:, l]))
                kk = self._group(k_cache[:, l])             # [N, T, H, D]
                vv = self._group(v_cache[:, l])
                s = jnp.einsum("nhd,nthd->nht", q.astype(jnp.float32),
                               kk.astype(jnp.float32)) * self._scale
                s = jnp.where(visible[:, None, :], s, _NEG)
                w = jax.nn.softmax(s, axis=-1)
                attn = jnp.einsum("nht,nthd->nhd", w,
                                  vv.astype(jnp.float32)).astype(x.dtype)
                x = self._mlp_residual(x, attn, l)
            logits = x @ self.params["embed"].T             # [N, V]
            done = gen >= limit
            eos_row = jnp.full((self.vocab_size,), _NEG, jnp.float32)
            eos_row = eos_row.at[eos_id].set(0.0)
            logits = jnp.where(done[:, None], eos_row[None], logits)
            new = {"k": k_cache, "v": v_cache, "pos": pos + 1,
                   "gen": gen + 1, "limit": limit}
            return logits, new

        return cell
