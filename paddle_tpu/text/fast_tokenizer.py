"""FastWordPieceTokenizer — ctypes binding to the native C++ tokenizer.

Reference analog: paddle's fast_tokenizer C++ library / the
faster_tokenizer op family: the input pipeline's tokenization runs in
native threads WITHOUT the GIL, overlapping accelerator steps — a Python
wordpiece loop serializes the host into the step budget.

The shared object builds on first use with the system g++ (cached next
to the source); when no compiler is available the pure-Python fallback
(`_py_encode`, also the parity oracle in tests) is used transparently.
"""
from __future__ import annotations

import ctypes
import os
import subprocess
import threading
import warnings
from typing import Dict, List, Optional, Sequence, Union

import numpy as np

__all__ = ["FastWordPieceTokenizer"]

_CSRC = os.path.join(os.path.dirname(__file__), "csrc")
_LOCK = threading.Lock()
_LIB = None
_LIB_TRIED = False


def _load_lib():
    """Compile (once) and dlopen the native tokenizer; None on failure."""
    global _LIB, _LIB_TRIED
    with _LOCK:
        if _LIB_TRIED:
            return _LIB
        _LIB_TRIED = True
        src = os.path.join(_CSRC, "fast_tokenizer.cpp")
        so = os.path.join(_CSRC, "libfast_tokenizer.so")
        try:
            # rebuild only when the source is present AND newer; a
            # shipped prebuilt .so without csrc/ loads as-is
            if os.path.exists(src) and (
                    not os.path.exists(so)
                    or os.path.getmtime(so) < os.path.getmtime(src)):
                tmp = so + ".tmp"
                subprocess.run(
                    ["g++", "-O2", "-std=c++17", "-shared", "-fPIC",
                     "-pthread", src, "-o", tmp],
                    check=True, capture_output=True)
                os.replace(tmp, so)
            lib = ctypes.CDLL(so)
        except (OSError, subprocess.CalledProcessError) as e:
            warnings.warn(f"native tokenizer unavailable "
                          f"({type(e).__name__}); using the Python "
                          f"fallback")
            return None
        lib.ft_new.restype = ctypes.c_void_p
        lib.ft_new.argtypes = [ctypes.c_char_p] + [ctypes.c_int32] * 5
        lib.ft_free.argtypes = [ctypes.c_void_p]
        lib.ft_vocab_size.restype = ctypes.c_int32
        lib.ft_vocab_size.argtypes = [ctypes.c_void_p]
        lib.ft_encode_batch.argtypes = [
            ctypes.c_void_p, ctypes.POINTER(ctypes.c_char_p),
            ctypes.c_int64, ctypes.c_int32, ctypes.c_int32,
            ctypes.POINTER(ctypes.c_int32), ctypes.POINTER(ctypes.c_int32)]
        _LIB = lib
        return _LIB


def _is_punct(c: str) -> bool:
    o = ord(c)
    return (33 <= o <= 47) or (58 <= o <= 64) or (91 <= o <= 96) or \
        (123 <= o <= 126)


class FastWordPieceTokenizer:
    """BERT-style basic + WordPiece tokenization to padded id matrices.

    ``vocab``: dict token->id, a list of tokens (id = index), or a path
    to a newline-separated vocab file."""

    def __init__(self, vocab: Union[Dict[str, int], Sequence[str], str],
                 unk_token="[UNK]", cls_token="[CLS]", sep_token="[SEP]",
                 pad_token="[PAD]", lowercase: bool = True,
                 use_native: bool = True):
        if isinstance(vocab, str):
            with open(vocab) as f:
                tokens = [ln.rstrip("\n") for ln in f]
        elif isinstance(vocab, dict):
            tokens = [None] * len(vocab)
            for t, i in vocab.items():
                tokens[i] = t
            assert all(t is not None for t in tokens), \
                "vocab ids must be dense 0..n-1"
        else:
            tokens = list(vocab)
        self._tokens = tokens
        self.vocab = {t: i for i, t in enumerate(tokens)}
        self.unk_id = self.vocab.get(unk_token, 0)
        self.cls_id = self.vocab.get(cls_token, 0)
        self.sep_id = self.vocab.get(sep_token, 0)
        self.pad_id = self.vocab.get(pad_token, 0)
        self.lowercase = lowercase
        self._handle = None
        self._lib = _load_lib() if use_native else None
        if self._lib is not None:
            blob = "\n".join(tokens).encode("utf-8")
            self._handle = self._lib.ft_new(
                blob, self.unk_id, self.cls_id, self.sep_id, self.pad_id,
                1 if lowercase else 0)

    @property
    def is_native(self) -> bool:
        return self._handle is not None

    def __len__(self):
        return len(self._tokens)

    def __del__(self):
        lib, h = getattr(self, "_lib", None), getattr(self, "_handle", None)
        if lib is not None and h:
            lib.ft_free(h)

    # -- encoding ----------------------------------------------------------
    def encode_batch(self, texts: Sequence[str], max_len: int = 128,
                     n_threads: int = 0):
        """texts -> (ids [B, max_len] int32, lens [B] int32), with
        [CLS]...[SEP] framing and [PAD] fill."""
        if max_len < 2:
            # [CLS] + [SEP] framing needs >= 2 slots; smaller values would
            # drive a negative resize through the C extension
            raise ValueError(
                f"encode_batch: max_len must be >= 2, got {max_len}")
        n = len(texts)
        ids = np.empty((n, max_len), np.int32)
        lens = np.empty((n,), np.int32)
        if self._handle is not None:
            buf = [t.encode("utf-8") for t in texts]
            arr = (ctypes.c_char_p * n)(*buf)
            self._lib.ft_encode_batch(
                self._handle, arr, n, max_len, n_threads,
                ids.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
                lens.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)))
            return ids, lens
        for i, t in enumerate(texts):
            row = self._py_encode(t, max_len)
            lens[i] = len(row)
            ids[i] = row + [self.pad_id] * (max_len - len(row))
        return ids, lens

    def __call__(self, texts, max_len: int = 128):
        if isinstance(texts, str):
            texts = [texts]
        return self.encode_batch(texts, max_len)[0]

    # -- pure-Python oracle / fallback -------------------------------------
    # NOTE: semantics are byte-level ASCII (space = " \t\n\r", lowercase =
    # A-Z only, multi-byte UTF-8 passes through as word bytes) — the same
    # spec the C++ kernel implements, so native and fallback paths are
    # bit-identical on any input.
    def _basic(self, text: str) -> List[str]:
        out, cur = [], []
        for c in text:
            if c in " \t\n\r":
                if cur:
                    out.append("".join(cur))
                    cur = []
            elif _is_punct(c):
                if cur:
                    out.append("".join(cur))
                    cur = []
                out.append(c)
            else:
                if self.lowercase and "A" <= c <= "Z":
                    c = c.lower()
                cur.append(c)
        if cur:
            out.append("".join(cur))
        return out

    def _wordpiece(self, word: str) -> List[int]:
        if len(word) > 100:
            return [self.unk_id]
        pieces, start = [], 0
        while start < len(word):
            end = len(word)
            cur = None
            while start < end:
                sub = word[start:end]
                if start > 0:
                    sub = "##" + sub
                if sub in self.vocab:
                    cur = self.vocab[sub]
                    break
                end -= 1
            if cur is None:
                return [self.unk_id]
            pieces.append(cur)
            start = end
        return pieces

    def _py_encode(self, text: str, max_len: int) -> List[int]:
        ids = [self.cls_id]
        for w in self._basic(text):
            if len(ids) >= max_len - 1:
                break
            ids += self._wordpiece(w)
        ids = ids[:max_len - 1]
        ids.append(self.sep_id)
        return ids
