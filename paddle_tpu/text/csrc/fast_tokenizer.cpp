// Fast WordPiece tokenizer — the framework's native (C++) runtime
// component for input pipelines.
//
// Reference analog: PaddleNLP/paddle's fast_tokenizer C++ library and the
// faster_tokenizer op family: batch text -> padded id matrices without
// holding the Python GIL, so tokenization overlaps accelerator steps.
// Exposed through a plain C ABI consumed via ctypes (no pybind11
// dependency); built on demand by paddle_tpu/text/fast_tokenizer.py.
//
// Algorithm: BERT basic tokenization (lowercase option, punctuation
// splitting, CJK isolation, whitespace) followed by greedy
// longest-match-first WordPiece with "##" continuation pieces.
#include <cstdint>
#include <cstring>
#include <string>
#include <thread>
#include <unordered_map>
#include <vector>

namespace {

struct Tokenizer {
  std::unordered_map<std::string, int32_t> vocab;
  int32_t unk_id = 0;
  int32_t cls_id = 0;
  int32_t sep_id = 0;
  int32_t pad_id = 0;
  bool lowercase = true;
  size_t max_word_chars = 100;
};

bool is_punct(unsigned char c) {
  return (c >= 33 && c <= 47) || (c >= 58 && c <= 64) ||
         (c >= 91 && c <= 96) || (c >= 123 && c <= 126);
}

// split one text into basic tokens (ASCII-oriented; multi-byte UTF-8
// sequences pass through as word chars)
void basic_tokenize(const char* text, bool lowercase,
                    std::vector<std::string>* out) {
  std::string cur;
  for (const char* p = text; *p; ++p) {
    unsigned char c = static_cast<unsigned char>(*p);
    if (c == ' ' || c == '\t' || c == '\n' || c == '\r') {
      if (!cur.empty()) { out->push_back(cur); cur.clear(); }
    } else if (is_punct(c)) {
      if (!cur.empty()) { out->push_back(cur); cur.clear(); }
      out->push_back(std::string(1, static_cast<char>(c)));
    } else {
      cur.push_back(lowercase && c >= 'A' && c <= 'Z'
                        ? static_cast<char>(c - 'A' + 'a')
                        : static_cast<char>(c));
    }
  }
  if (!cur.empty()) out->push_back(cur);
}

// greedy longest-match-first wordpiece for one basic token
void wordpiece(const Tokenizer& tk, const std::string& word,
               std::vector<int32_t>* ids) {
  if (word.size() > tk.max_word_chars) {
    ids->push_back(tk.unk_id);
    return;
  }
  std::vector<int32_t> pieces;
  size_t start = 0;
  while (start < word.size()) {
    size_t end = word.size();
    int32_t cur_id = -1;
    while (start < end) {
      std::string sub = word.substr(start, end - start);
      if (start > 0) sub = "##" + sub;
      auto it = tk.vocab.find(sub);
      if (it != tk.vocab.end()) { cur_id = it->second; break; }
      --end;
    }
    if (cur_id < 0) {  // no piece matched: whole word is UNK
      ids->push_back(tk.unk_id);
      return;
    }
    pieces.push_back(cur_id);
    start = end;
  }
  ids->insert(ids->end(), pieces.begin(), pieces.end());
}

void encode_range(const Tokenizer* tk, const char* const* texts,
                  int64_t begin, int64_t endi, int32_t max_len,
                  int32_t* out_ids, int32_t* out_lens) {
  for (int64_t i = begin; i < endi; ++i) {
    std::vector<std::string> words;
    basic_tokenize(texts[i], tk->lowercase, &words);
    std::vector<int32_t> ids;
    ids.reserve(max_len);
    ids.push_back(tk->cls_id);
    for (const auto& w : words) {
      if (static_cast<int32_t>(ids.size()) >= max_len - 1) break;
      wordpiece(*tk, w, &ids);
    }
    if (static_cast<int32_t>(ids.size()) > max_len - 1)
      ids.resize(max_len - 1);
    ids.push_back(tk->sep_id);
    out_lens[i] = static_cast<int32_t>(ids.size());
    int32_t* row = out_ids + i * max_len;
    for (int32_t j = 0; j < max_len; ++j)
      row[j] = j < static_cast<int32_t>(ids.size()) ? ids[j] : tk->pad_id;
  }
}

}  // namespace

extern "C" {

// vocab_blob: '\n'-joined tokens, id = line index
void* ft_new(const char* vocab_blob, int32_t unk_id, int32_t cls_id,
             int32_t sep_id, int32_t pad_id, int32_t lowercase) {
  auto* tk = new Tokenizer();
  tk->unk_id = unk_id;
  tk->cls_id = cls_id;
  tk->sep_id = sep_id;
  tk->pad_id = pad_id;
  tk->lowercase = lowercase != 0;
  int32_t id = 0;
  const char* p = vocab_blob;
  while (*p) {
    const char* nl = strchr(p, '\n');
    size_t n = nl ? static_cast<size_t>(nl - p) : strlen(p);
    tk->vocab.emplace(std::string(p, n), id++);
    if (!nl) break;
    p = nl + 1;
  }
  return tk;
}

void ft_free(void* handle) { delete static_cast<Tokenizer*>(handle); }

int32_t ft_vocab_size(void* handle) {
  return static_cast<int32_t>(
      static_cast<Tokenizer*>(handle)->vocab.size());
}

// texts: array of n C strings; out_ids: [n, max_len] int32 (caller-
// allocated); out_lens: [n] int32.  n_threads <= 0 -> hardware count.
void ft_encode_batch(void* handle, const char* const* texts, int64_t n,
                     int32_t max_len, int32_t n_threads, int32_t* out_ids,
                     int32_t* out_lens) {
  if (n <= 0) return;
  const auto* tk = static_cast<Tokenizer*>(handle);
  // max_len < 2 would resize(max_len - 1) with a negative value, whose
  // size_t conversion throws length_error across the extern "C"/thread
  // boundary and aborts the process — reject defensively (the Python
  // wrapper also validates), pad-filling ids like the normal path
  if (max_len < 2) {
    for (int64_t i = 0; i < n; ++i) {
      out_lens[i] = 0;
      for (int32_t j = 0; j < max_len; ++j) out_ids[i * max_len + j] = tk->pad_id;
    }
    return;
  }
  int64_t workers = n_threads > 0
                        ? n_threads
                        : static_cast<int64_t>(
                              std::thread::hardware_concurrency());
  if (workers < 1) workers = 1;
  if (workers > n) workers = n;
  if (workers == 1) {
    encode_range(tk, texts, 0, n, max_len, out_ids, out_lens);
    return;
  }
  std::vector<std::thread> pool;
  int64_t chunk = (n + workers - 1) / workers;
  for (int64_t w = 0; w < workers; ++w) {
    int64_t b = w * chunk;
    int64_t e = b + chunk < n ? b + chunk : n;
    if (b >= e) break;
    pool.emplace_back(encode_range, tk, texts, b, e, max_len, out_ids,
                      out_lens);
  }
  for (auto& t : pool) t.join();
}

}  // extern "C"
