"""paddle_tpu.text (reference: python/paddle/text/).

``paddle.text.datasets`` holds real-format parsers for the 7 reference
datasets (imdb, imikolov, movielens, conll05st, uci_housing, wmt14,
wmt16) — see datasets.py.  Zero-egress divergence: archives must be
local files in the ORIGINAL formats; there is no downloader.

The native fast WordPiece tokenizer (C++ MaxMatch) lives in
fast_tokenizer.py."""
from __future__ import annotations

from . import datasets  # noqa: F401  (paddle.text.datasets.* namespace)
from .datasets import (Conll05st, Imdb, Imikolov, Movielens,  # noqa: F401
                       UCIHousing, WMT14, WMT16)
from .fast_tokenizer import FastWordPieceTokenizer  # noqa: F401
