"""paddle_tpu.text (reference: python/paddle/text/datasets/: imdb.py,
wmt14.py, wmt16.py, conll05.py, movielens.py, uci_housing.py).

Zero-egress: datasets synthesize deterministic corpora with realistic
shapes/vocabulary when no local file is provided (documented divergence —
the reference downloads from bcebos.com)."""
from __future__ import annotations

import os

import numpy as np

from ..io import Dataset


class _SyntheticSeqDataset(Dataset):
    def __init__(self, n, seq_len, vocab_size, num_classes, seed):
        rs = np.random.RandomState(seed)
        self.data = rs.randint(1, vocab_size, (n, seq_len)).astype(np.int64)
        self.labels = rs.randint(0, num_classes, n).astype(np.int64)
        # weak signal: class parity of token sums
        for i in range(n):
            self.labels[i] = int(self.data[i].sum() % num_classes)

    def __getitem__(self, idx):
        return self.data[idx], np.asarray(self.labels[idx])

    def __len__(self):
        return len(self.data)


class Imdb(_SyntheticSeqDataset):
    """reference: text/datasets/imdb.py (binary sentiment)."""

    def __init__(self, data_file=None, mode="train", cutoff=150):
        self.vocab_size = 5147
        super().__init__(2000 if mode == "train" else 500, 128,
                         self.vocab_size, 2,
                         seed=10 if mode == "train" else 11)
        self.word_idx = {f"w{i}": i for i in range(self.vocab_size)}


class WMT14(Dataset):
    """reference: text/datasets/wmt14.py (en-fr pairs)."""

    def __init__(self, data_file=None, mode="train", dict_size=30000):
        self.dict_size = dict_size
        rs = np.random.RandomState(20 if mode == "train" else 21)
        n = 1000 if mode == "train" else 200
        self.src = rs.randint(3, dict_size, (n, 24)).astype(np.int64)
        self.tgt = rs.randint(3, dict_size, (n, 24)).astype(np.int64)

    def __getitem__(self, idx):
        src = self.src[idx]
        tgt = self.tgt[idx]
        return src, tgt[:-1], tgt[1:]

    def __len__(self):
        return len(self.src)

    def get_dict(self, lang="en", reverse=False):
        d = {f"tok{i}": i for i in range(self.dict_size)}
        return {v: k for k, v in d.items()} if reverse else d


class WMT16(WMT14):
    pass


class UCIHousing(Dataset):
    """reference: text/datasets/uci_housing.py (13-feature regression)."""

    def __init__(self, data_file=None, mode="train"):
        if data_file and os.path.exists(data_file):
            raw = np.loadtxt(data_file).astype(np.float32)
        else:
            rs = np.random.RandomState(30)
            X = rs.rand(506, 13).astype(np.float32)
            w = rs.rand(13).astype(np.float32)
            y = (X @ w + 0.1 * rs.rand(506)).astype(np.float32)
            raw = np.concatenate([X, y[:, None]], axis=1)
        n_train = int(len(raw) * 0.8)
        self.data = raw[:n_train] if mode == "train" else raw[n_train:]

    def __getitem__(self, idx):
        row = self.data[idx]
        return row[:-1], row[-1:]

    def __len__(self):
        return len(self.data)


class Conll05st(_SyntheticSeqDataset):
    def __init__(self, data_file=None, mode="train"):
        super().__init__(500, 32, 5000, 10, seed=40)


class Movielens(Dataset):
    def __init__(self, data_file=None, mode="train"):
        rs = np.random.RandomState(50)
        n = 2000 if mode == "train" else 400
        self.users = rs.randint(0, 944, n).astype(np.int64)
        self.movies = rs.randint(0, 1683, n).astype(np.int64)
        self.ratings = ((self.users * 7 + self.movies * 3) % 5 + 1
                        ).astype(np.float32)

    def __getitem__(self, idx):
        return (self.users[idx], self.movies[idx],
                np.asarray([self.ratings[idx]]))

    def __len__(self):
        return len(self.users)


datasets = None  # namespacing below mirrors paddle.text.datasets.*


class _DatasetsNS:
    Imdb = Imdb
    WMT14 = WMT14
    WMT16 = WMT16
    UCIHousing = UCIHousing
    Conll05st = Conll05st
    Movielens = Movielens


datasets = _DatasetsNS()


from .fast_tokenizer import FastWordPieceTokenizer  # noqa: F401,E402
