"""paddle.text.datasets parity: real-format parsers for the 7 reference
text datasets.

Reference: python/paddle/text/datasets/{imdb,imikolov,movielens,conll05,
uci_housing,wmt14,wmt16}.py.  Each class keeps the reference's
constructor signature, archive layout, vocab-building rules, and
__getitem__ tuple contract.

Zero-egress divergence (documented): the reference downloads from
dataset.bj.bcebos.com; this environment has no network, so ``data_file``
(and friends) must point at a local archive in the ORIGINAL format —
parsing is the real component, downloading is not.  Passing nothing
raises with the expected layout spelled out.
"""
from __future__ import annotations

import collections
import gzip
import os
import re
import string
import tarfile
import zipfile

import numpy as np

from ..io import Dataset

__all__ = ["Imdb", "Imikolov", "Movielens", "Conll05st", "UCIHousing",
           "WMT14", "WMT16"]


def _need_file(path, name, layout):
    if path is None:
        raise ValueError(
            f"{name}: data_file must point at a local archive (no network "
            f"in this environment; downloads are not supported). Expected "
            f"format: {layout}")
    if not os.path.exists(path):
        raise FileNotFoundError(f"{name}: {path} does not exist")
    return path


class Imdb(Dataset):
    """reference: text/datasets/imdb.py:33 (aclImdb tar; pos=0 / neg=1;
    freq>cutoff vocab sorted by (-freq, word) with trailing <unk>)."""

    def __init__(self, data_file=None, mode="train", cutoff=150,
                 download=True):
        assert mode.lower() in ("train", "test"), mode
        self.mode = mode.lower()
        self.data_file = _need_file(
            data_file, "Imdb",
            "aclImdb_v1.tar.gz with members aclImdb/{train,test}/"
            "{pos,neg}/*.txt")
        self.word_idx = self._build_word_dict(cutoff)
        self._load_anno()

    def _tokenize(self, pattern):
        docs = []
        table = bytes.maketrans(b"", b"")
        punct = string.punctuation.encode()
        with tarfile.open(self.data_file) as tf:
            for m in tf:
                if pattern.match(m.name):
                    raw = tf.extractfile(m).read().rstrip(b"\n\r")
                    docs.append(raw.translate(table, punct).lower().split())
        return docs

    def _build_word_dict(self, cutoff):
        freq = collections.defaultdict(int)
        pat = re.compile(r"aclImdb/((train)|(test))/((pos)|(neg))/.*\.txt$")
        for doc in self._tokenize(pat):
            for w in doc:
                freq[w] += 1
        kept = sorted(((w, c) for w, c in freq.items() if c > cutoff),
                      key=lambda x: (-x[1], x[0]))
        word_idx = {w: i for i, (w, _) in enumerate(kept)}
        word_idx[b"<unk>"] = len(word_idx)
        return word_idx

    def _load_anno(self):
        unk = self.word_idx[b"<unk>"]
        self.docs, self.labels = [], []
        for label, sub in ((0, "pos"), (1, "neg")):
            pat = re.compile(rf"aclImdb/{self.mode}/{sub}/.*\.txt$")
            for doc in self._tokenize(pat):
                self.docs.append([self.word_idx.get(w, unk) for w in doc])
                self.labels.append(label)

    def __getitem__(self, idx):
        return np.array(self.docs[idx]), np.array([self.labels[idx]])

    def __len__(self):
        return len(self.docs)


class Imikolov(Dataset):
    """reference: text/datasets/imikolov.py:76 (PTB tar; NGRAM windows or
    SEQ <s>/<e> pairs; vocab from train+valid with freq>min_word_freq)."""

    def __init__(self, data_file=None, data_type="NGRAM", window_size=-1,
                 mode="train", min_word_freq=50, download=True):
        assert data_type.upper() in ("NGRAM", "SEQ"), data_type
        assert mode.lower() in ("train", "test"), mode
        self.data_type = data_type.upper()
        self.mode = mode.lower()
        self.window_size = window_size
        self.data_file = _need_file(
            data_file, "Imikolov",
            "simple-examples tar with ./simple-examples/data/"
            "ptb.{train,valid,test}.txt")
        self.word_idx = self._build_word_dict(min_word_freq)
        self._load_anno()

    @staticmethod
    def _word_count(f, freq):
        for line in f:
            for w in line.strip().split():
                freq[w] += 1
            freq[b"<s>"] += 1
            freq[b"<e>"] += 1
        return freq

    def _member(self, tf, suffix):
        # suffix match tolerates both "./simple-examples/..." and
        # "simple-examples/..." member spellings
        for name in tf.getnames():
            if name.endswith(suffix):
                return tf.extractfile(name)
        raise KeyError(f"Imikolov: no member ending in {suffix} in "
                       f"{self.data_file}")

    def _build_word_dict(self, cutoff):
        with tarfile.open(self.data_file) as tf:
            freq = collections.defaultdict(int)
            self._word_count(self._member(tf, "data/ptb.train.txt"), freq)
            self._word_count(self._member(tf, "data/ptb.valid.txt"), freq)
        freq.pop(b"<unk>", None)
        kept = sorted(((w, c) for w, c in freq.items() if c > cutoff),
                      key=lambda x: (-x[1], x[0]))
        word_idx = {w: i for i, (w, _) in enumerate(kept)}
        word_idx[b"<unk>"] = len(word_idx)
        return word_idx

    def _load_anno(self):
        self.data = []
        unk = self.word_idx[b"<unk>"]
        # reference: imikolov.py maps mode directly onto the split file —
        # test mode reads ptb.test.txt (valid is only for vocab building)
        fname = f"data/ptb.{self.mode}.txt"
        with tarfile.open(self.data_file) as tf:
            for line in self._member(tf, fname):
                if self.data_type == "NGRAM":
                    assert self.window_size > -1, "Invalid gram length"
                    toks = [b"<s>"] + line.strip().split() + [b"<e>"]
                    if len(toks) >= self.window_size:
                        ids = [self.word_idx.get(w, unk) for w in toks]
                        for i in range(self.window_size, len(ids) + 1):
                            self.data.append(
                                tuple(ids[i - self.window_size:i]))
                else:  # SEQ
                    ids = [self.word_idx.get(w, unk)
                           for w in line.strip().split()]
                    src = [self.word_idx[b"<s>"]] + ids
                    trg = ids + [self.word_idx[b"<e>"]]
                    if 0 < self.window_size < len(src):
                        continue
                    self.data.append((src, trg))

    def __getitem__(self, idx):
        return tuple(np.array(d) for d in self.data[idx])

    def __len__(self):
        return len(self.data)


class _MovieInfo:
    """reference: movielens.py:42 MovieInfo value layout."""

    def __init__(self, index, categories, title):
        self.index = int(index)
        self.categories = categories
        self.title = title

    def value(self, categories_dict, title_dict):
        return [[self.index],
                [categories_dict[c] for c in self.categories],
                [title_dict[w.lower()] for w in self.title.split()]]


class _UserInfo:
    """reference: movielens.py:67 UserInfo value layout."""

    def __init__(self, index, gender, age, job_id):
        self.index = int(index)
        self.is_male = gender == "M"
        self.age = int(age)
        self.job_id = int(job_id)

    def value(self):
        return [[self.index], [0 if self.is_male else 1], [self.age],
                [self.job_id]]


class Movielens(Dataset):
    """reference: text/datasets/movielens.py:96 (ml-1m zip: movies.dat /
    users.dat / ratings.dat with :: separators; seeded random train/test
    split; rating rescaled to [-5, 5] via r*2-5)."""

    def __init__(self, data_file=None, mode="train", test_ratio=0.1,
                 rand_seed=0, download=True):
        assert mode.lower() in ("train", "test"), mode
        self.mode = mode.lower()
        self.data_file = _need_file(
            data_file, "Movielens",
            "ml-1m.zip with ml-1m/{movies,users,ratings}.dat "
            "('::'-separated, latin-1)")
        self.test_ratio = test_ratio
        np.random.seed(rand_seed)
        self._load_meta_info()
        self._load_data()

    def _load_meta_info(self):
        pat = re.compile(r"^(.*)\((\d+)\)$")
        self.movie_info, self.user_info = {}, {}
        title_words, categories = set(), set()
        with zipfile.ZipFile(self.data_file) as z:
            with z.open("ml-1m/movies.dat") as f:
                for line in f:
                    mid, title, cats = (line.decode("latin-1").strip()
                                        .split("::"))
                    cats = cats.split("|")
                    categories.update(cats)
                    title = pat.match(title).group(1).strip()
                    title_words.update(w.lower() for w in title.split())
                    self.movie_info[int(mid)] = _MovieInfo(mid, cats, title)
            with z.open("ml-1m/users.dat") as f:
                for line in f:
                    uid, gender, age, job, _ = (line.decode("latin-1")
                                                .strip().split("::"))
                    self.user_info[int(uid)] = _UserInfo(uid, gender, age,
                                                         job)
        self.movie_title_dict = {w: i for i, w in enumerate(title_words)}
        self.categories_dict = {c: i for i, c in enumerate(categories)}

    def _load_data(self):
        self.data = []
        is_test = self.mode == "test"
        with zipfile.ZipFile(self.data_file) as z:
            with z.open("ml-1m/ratings.dat") as f:
                for line in f:
                    if (np.random.random() < self.test_ratio) != is_test:
                        continue
                    uid, mid, rating, _ = (line.decode("latin-1").strip()
                                           .split("::"))
                    usr = self.user_info[int(uid)]
                    mov = self.movie_info[int(mid)]
                    self.data.append(
                        usr.value()
                        + mov.value(self.categories_dict,
                                    self.movie_title_dict)
                        + [[float(rating) * 2 - 5.0]])

    def __getitem__(self, idx):
        return tuple(np.array(d) for d in self.data[idx])

    def __len__(self):
        return len(self.data)


class Conll05st(Dataset):
    """reference: text/datasets/conll05.py:99 (conll05st-release tar with
    gzipped test.wsj words/props columns; separate word/verb/target dict
    files; emits the 9-slot SRL tuple with predicate context windows)."""

    UNK_IDX = 0

    def __init__(self, data_file=None, word_dict_file=None,
                 verb_dict_file=None, target_dict_file=None, emb_file=None,
                 download=True):
        self.data_file = _need_file(
            data_file, "Conll05st",
            "conll05st-release tar with conll05st-release/test.wsj/"
            "{words/test.wsj.words.gz,props/test.wsj.props.gz}")
        self.word_dict_file = _need_file(word_dict_file, "Conll05st",
                                         "word dict, one token per line")
        self.verb_dict_file = _need_file(verb_dict_file, "Conll05st",
                                         "verb dict, one token per line")
        self.target_dict_file = _need_file(
            target_dict_file, "Conll05st",
            "target label dict with B-*/I-*/O tags")
        self.emb_file = emb_file
        self.word_dict = self._load_dict(self.word_dict_file)
        self.predicate_dict = self._load_dict(self.verb_dict_file)
        self.label_dict = self._load_label_dict(self.target_dict_file)
        self._load_anno()

    @staticmethod
    def _load_dict(path):
        with open(path) as f:
            return {ln.strip(): i for i, ln in enumerate(f)}

    @staticmethod
    def _load_label_dict(path):
        tags = set()
        with open(path) as f:
            for ln in f:
                ln = ln.strip()
                if ln.startswith(("B-", "I-")):
                    tags.add(ln[2:])
        d, i = {}, 0
        for tag in sorted(tags):
            d["B-" + tag] = i
            d["I-" + tag] = i + 1
            i += 2
        d["O"] = i
        return d

    @staticmethod
    def _parse_props(lbl):
        """Star-bracket props column -> BIO sequence (conll05.py:200)."""
        out, cur, inside = [], "O", False
        for tok in lbl:
            if tok == "*":
                out.append("I-" + cur if inside else "O")
            elif tok == "*)":
                out.append("I-" + cur)
                inside = False
            elif "(" in tok:
                cur = tok[1:tok.find("*")]
                out.append("B-" + cur)
                inside = ")" not in tok
            else:
                raise RuntimeError(f"Unexpected label: {tok}")
        return out

    def _load_anno(self):
        self.sentences, self.predicates, self.labels = [], [], []
        with tarfile.open(self.data_file) as tf:
            wf = tf.extractfile(
                "conll05st-release/test.wsj/words/test.wsj.words.gz")
            pf = tf.extractfile(
                "conll05st-release/test.wsj/props/test.wsj.props.gz")
            with gzip.GzipFile(fileobj=wf) as words, \
                    gzip.GzipFile(fileobj=pf) as props:
                sentence, columns = [], []
                for wline, pline in zip(words, props):
                    word = wline.decode().strip()
                    cols = pline.decode().strip().split()
                    if cols:  # in-sentence row: word + one col per verb
                        sentence.append(word)
                        columns.append(cols)
                        continue
                    # end of sentence: column 0 = verbs, 1.. = props
                    if columns:
                        verbs = [c[0] for c in columns if c[0] != "-"]
                        n_props = len(columns[0]) - 1
                        for v in range(n_props):
                            lbl = [c[v + 1] for c in columns]
                            self.sentences.append(list(sentence))
                            self.predicates.append(verbs[v])
                            self.labels.append(self._parse_props(lbl))
                    sentence, columns = [], []

    def __getitem__(self, idx):
        sent, pred, labels = (self.sentences[idx], self.predicates[idx],
                              self.labels[idx])
        n = len(sent)
        vi = labels.index("B-V")
        mark = [0] * n
        ctx = {}
        for off, key in ((-2, "n2"), (-1, "n1"), (0, "0"), (1, "p1"),
                         (2, "p2")):
            j = vi + off
            if 0 <= j < n:
                mark[j] = 1
                ctx[key] = sent[j]
            else:
                ctx[key] = "bos" if off < 0 else "eos"
        wd, UNK = self.word_dict, self.UNK_IDX
        word_idx = [wd.get(w, UNK) for w in sent]
        ctxs = [[wd.get(ctx[k], UNK)] * n
                for k in ("n2", "n1", "0", "p1", "p2")]
        pred_idx = [self.predicate_dict.get(pred)] * n
        label_idx = [self.label_dict.get(w) for w in labels]
        return tuple(np.array(a) for a in
                     [word_idx, *ctxs, pred_idx, mark, label_idx])

    def __len__(self):
        return len(self.sentences)

    def get_dict(self):
        return self.word_dict, self.predicate_dict, self.label_dict

    def get_embedding(self):
        if self.emb_file is None:
            raise ValueError("Conll05st: emb_file was not provided")
        return self.emb_file


class UCIHousing(Dataset):
    """reference: text/datasets/uci_housing.py:69 (whitespace floats, 14
    per row; feature-wise (x-avg)/(max-min) normalisation; 80/20
    train/test split)."""

    def __init__(self, data_file=None, mode="train", download=True):
        assert mode.lower() in ("train", "test"), mode
        self.mode = mode.lower()
        self.data_file = _need_file(
            data_file, "UCIHousing",
            "housing.data: whitespace-separated floats, 14 per record")
        self._load_data()

    def _load_data(self, feature_num=14, ratio=0.8):
        data = np.fromfile(self.data_file, sep=" ")
        data = data.reshape(data.shape[0] // feature_num, feature_num)
        mx, mn, avg = (data.max(axis=0), data.min(axis=0),
                       data.mean(axis=0))
        for i in range(feature_num - 1):
            data[:, i] = (data[:, i] - avg[i]) / (mx[i] - mn[i])
        offset = int(data.shape[0] * ratio)
        self.data = data[:offset] if self.mode == "train" else data[offset:]

    def __getitem__(self, idx):
        row = self.data[idx]
        return (row[:-1].astype(np.float32), row[-1:].astype(np.float32))

    def __len__(self):
        return len(self.data)


class WMT14(Dataset):
    """reference: text/datasets/wmt14.py:44 (tgz with *src.dict /
    *trg.dict and {mode}/{mode} tab-separated bitext; <s>/<e> wrapping,
    UNK_IDX=2, sequences longer than 80 dropped)."""

    START, END, UNK_IDX = "<s>", "<e>", 2

    def __init__(self, data_file=None, mode="train", dict_size=-1,
                 download=True):
        assert mode.lower() in ("train", "test", "gen"), mode
        self.mode = mode.lower()
        self.data_file = _need_file(
            data_file, "WMT14",
            "wmt14.tgz with members *src.dict, *trg.dict and "
            "{train/train,test/test,gen/gen} bitext (src\\ttrg lines)")
        assert dict_size > 0, "dict_size should be set as positive number"
        self.dict_size = dict_size
        self._load_data()

    @staticmethod
    def _to_dict(f, size):
        d = {}
        for i, line in enumerate(f):
            if i >= size:
                break
            d[line.decode().strip()] = i
        return d

    def _load_data(self):
        self.src_ids, self.trg_ids, self.trg_ids_next = [], [], []
        with tarfile.open(self.data_file) as tf:
            src = [n for n in tf.getnames() if n.endswith("src.dict")]
            trg = [n for n in tf.getnames() if n.endswith("trg.dict")]
            assert len(src) == 1 and len(trg) == 1, (src, trg)
            self.src_dict = self._to_dict(tf.extractfile(src[0]),
                                          self.dict_size)
            self.trg_dict = self._to_dict(tf.extractfile(trg[0]),
                                          self.dict_size)
            wanted = f"{self.mode}/{self.mode}"
            for name in tf.getnames():
                if not name.endswith(wanted):
                    continue
                for line in tf.extractfile(name):
                    parts = line.decode().strip().split("\t")
                    if len(parts) != 2:
                        continue
                    sw = parts[0].split()
                    src_ids = [self.src_dict.get(w, self.UNK_IDX)
                               for w in [self.START] + sw + [self.END]]
                    tw = parts[1].split()
                    trg = [self.trg_dict.get(w, self.UNK_IDX) for w in tw]
                    if len(src_ids) > 80 or len(trg) > 80:
                        continue
                    self.src_ids.append(src_ids)
                    self.trg_ids.append([self.trg_dict[self.START]] + trg)
                    self.trg_ids_next.append(trg + [self.trg_dict[self.END]])

    def __getitem__(self, idx):
        return (np.array(self.src_ids[idx]), np.array(self.trg_ids[idx]),
                np.array(self.trg_ids_next[idx]))

    def __len__(self):
        return len(self.src_ids)

    def get_dict(self, reverse=False):
        if reverse:
            return ({v: k for k, v in self.src_dict.items()},
                    {v: k for k, v in self.trg_dict.items()})
        return self.src_dict, self.trg_dict


class WMT16(Dataset):
    """reference: text/datasets/wmt16.py:39 (tar with wmt16/{train,test,
    val} tab-separated en\\tde lines; dict built from the train split by
    frequency with <s>/<e>/<unk> heads, cached as {lang}_{size}.dict)."""

    START_MARK, END_MARK, UNK_MARK = "<s>", "<e>", "<unk>"

    def __init__(self, data_file=None, mode="train", src_dict_size=-1,
                 trg_dict_size=-1, lang="en", download=True,
                 dict_cache_dir=None):
        assert mode.lower() in ("train", "test", "val"), mode
        self.mode = mode.lower()
        self.data_file = _need_file(
            data_file, "WMT16",
            "wmt16.tar with members wmt16/{train,test,val} "
            "(en\\tde lines)")
        self.lang = lang
        assert src_dict_size > 0 and trg_dict_size > 0, \
            "dict_size should be set as positive number"
        self.src_dict_size = src_dict_size
        self.trg_dict_size = trg_dict_size
        # cache under a DATA_HOME-style dir (reference parity: the
        # archive's mount may be read-only), keyed by the archive's
        # identity so a different/modified archive never reuses a stale
        # vocabulary
        self._cache = dict_cache_dir or os.environ.get(
            "PADDLE_TPU_DATA_HOME",
            os.path.join(os.path.expanduser("~"), ".cache", "paddle_tpu",
                         "wmt16"))
        os.makedirs(self._cache, exist_ok=True)
        st = os.stat(self.data_file)
        import hashlib
        self._archive_key = hashlib.sha1(
            f"{os.path.abspath(self.data_file)}:{st.st_size}:"
            f"{st.st_mtime_ns}".encode()).hexdigest()[:12]
        self.src_dict = self._load_dict(lang, src_dict_size)
        self.trg_dict = self._load_dict("de" if lang == "en" else "en",
                                        trg_dict_size)
        self._load_data()

    def _dict_path(self, lang, size):
        return os.path.join(
            self._cache, f"wmt16_{self._archive_key}_{lang}_{size}.dict")

    def _build_dict(self, path, size, lang):
        freq = collections.defaultdict(int)
        col = 0 if lang == "en" else 1
        with tarfile.open(self.data_file) as tf:
            for line in tf.extractfile("wmt16/train"):
                parts = line.decode().strip().split("\t")
                if len(parts) != 2:
                    continue
                for w in parts[col].split():
                    freq[w] += 1
        with open(path + ".tmp", "w") as f:
            f.write(f"{self.START_MARK}\n{self.END_MARK}\n{self.UNK_MARK}\n")
            for i, (w, _) in enumerate(
                    sorted(freq.items(), key=lambda x: x[1], reverse=True)):
                if i + 3 == size:
                    break
                f.write(w + "\n")
        os.replace(path + ".tmp", path)  # no partial cache on a crash

    def _load_dict(self, lang, size, reverse=False):
        path = self._dict_path(lang, size)
        # <= size: the build loop stops early when the corpus vocabulary
        # is smaller than dict_size, which is still a complete dict
        ok = (os.path.exists(path)
              and len(open(path).readlines()) <= size)
        if not ok:
            self._build_dict(path, size, lang)
        d = {}
        with open(path) as f:
            for i, line in enumerate(f):
                if reverse:
                    d[i] = line.strip()
                else:
                    d[line.strip()] = i
        return d

    def _load_data(self):
        start = self.src_dict[self.START_MARK]
        end = self.src_dict[self.END_MARK]
        unk = self.src_dict[self.UNK_MARK]
        src_col = 0 if self.lang == "en" else 1
        self.src_ids, self.trg_ids, self.trg_ids_next = [], [], []
        with tarfile.open(self.data_file) as tf:
            for line in tf.extractfile(f"wmt16/{self.mode}"):
                parts = line.decode().strip().split("\t")
                if len(parts) != 2:
                    continue
                sw = parts[src_col].split()
                tw = parts[1 - src_col].split()
                trg = [self.trg_dict.get(w, unk) for w in tw]
                self.src_ids.append(
                    [start] + [self.src_dict.get(w, unk) for w in sw]
                    + [end])
                self.trg_ids.append([start] + trg)
                self.trg_ids_next.append(trg + [end])

    def __getitem__(self, idx):
        return (np.array(self.src_ids[idx]), np.array(self.trg_ids[idx]),
                np.array(self.trg_ids_next[idx]))

    def __len__(self):
        return len(self.src_ids)

    def get_dict(self, lang, reverse=False):
        size = (self.src_dict_size if lang == self.lang
                else self.trg_dict_size)
        return self._load_dict(lang, size, reverse)
