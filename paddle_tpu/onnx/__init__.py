"""paddle.onnx.export — interop export to ONNX.

Reference: python/paddle/onnx/export.py (paddle.onnx.export -> paddle2onnx
over the inference program).  TPU-native path: the layer is traced to a
jaxpr (the same trace jit.save uses) and a documented primitive subset is
mapped 1:1 onto ONNX ops; everything else raises loudly with the
offending primitive named.  The protobuf bytes are hand-encoded
(onnx/_proto.py) because no onnx package exists in this environment;
``protoc --decode`` verifies schema conformance in the tests.

Supported primitives (the MLP/CNN serving surface): dot_general (2-D) →
MatMul/Gemm, conv_general_dilated (NCHW) → Conv, add/sub/mul/div/max/min
→ elementwise, neg → Neg, tanh → Tanh, logistic → Sigmoid, exp → Exp,
log → Log, rsqrt/sqrt → Sqrt(+Reciprocal), integer_pow → Pow, reshape →
Reshape, transpose → Transpose, broadcast_in_dim → Reshape+Expand,
squeeze → Reshape, reduce_sum/max/min → ReduceSum/Max/Min,
reduce_window (max/avg pattern) → MaxPool/AveragePool, select_n → Where,
convert_element_type → Cast, stop_gradient/copy → Identity.  Nested
call-like primitives (pjit, custom_jvp/vjp, remat, closed_call) are
inlined.
"""
from __future__ import annotations

import os
from typing import Dict, List, Optional

import jax
import jax.numpy as jnp
import numpy as np

from ..core import autograd, rng
from ..core.tensor import Tensor
from ..jit.bind import bind, buffer_arrays, param_list
from . import _proto as P

__all__ = ["export"]

_ELEMWISE = {
    "add": "Add", "sub": "Sub", "mul": "Mul", "div": "Div",
    "max": "Max", "min": "Min", "tanh": "Tanh", "logistic": "Sigmoid",
    "exp": "Exp", "log": "Log", "neg": "Neg", "sqrt": "Sqrt",
    "sign": "Sign", "abs": "Abs", "floor": "Floor", "ceil": "Ceil",
    "erf": "Erf",
}

_REDUCE = {"reduce_sum": "ReduceSum", "reduce_max": "ReduceMax",
           "reduce_min": "ReduceMin"}

_CALL_PRIMS = {"pjit", "jit", "closed_call", "custom_jvp_call",
               "custom_vjp_call", "custom_jvp_call_jaxpr", "remat2",
               "checkpoint", "custom_vjp_call_jaxpr"}


class _Converter:
    def __init__(self):
        self.nodes: List[bytes] = []
        self.initializers: List[bytes] = []
        self.names: Dict[int, str] = {}     # id(var) -> onnx name
        self.counter = 0

    def fresh(self, hint="t"):
        self.counter += 1
        return f"{hint}_{self.counter}"

    def name_of(self, var, jaxpr_consts):
        from jax.extend.core import Literal
        if isinstance(var, Literal):
            return self.add_const(np.asarray(var.val))
        return self.names[id(var)]

    def add_const(self, arr: np.ndarray, hint="const"):
        nm = self.fresh(hint)
        self.initializers.append(P.tensor_proto(nm, arr))
        return nm

    def emit(self, op, ins, n_out=1, attrs=(), hint=None):
        outs = [self.fresh(hint or op.lower()) for _ in range(n_out)]
        self.nodes.append(P.node(op, ins, outs, name=outs[0] + "_node",
                                 attrs=list(attrs)))
        return outs

    # -- the conversion ----------------------------------------------------
    def convert(self, jaxpr, consts, in_names):
        for v, nm in zip(jaxpr.invars, in_names):
            self.names[id(v)] = nm
        for v, c in zip(jaxpr.constvars, consts):
            self.names[id(v)] = self.add_const(np.asarray(c), "param")
        for eqn in jaxpr.eqns:
            self._eqn(eqn)
        return [self.name_of(v, None) for v in jaxpr.outvars]

    def _inline(self, inner_jaxpr, inner_consts, eqn):
        in_names = [self.name_of(v, None) for v in eqn.invars]
        sub_out = _Converter.convert_into(self, inner_jaxpr, inner_consts,
                                          in_names)
        for v, nm in zip(eqn.outvars, sub_out):
            self.names[id(v)] = nm

    @staticmethod
    def convert_into(conv, jaxpr, consts, in_names):
        saved = dict(conv.names)
        out = conv.convert(jaxpr, consts, in_names)
        # keep outer names intact for vars outside the sub-jaxpr
        conv.names.update(saved)
        return out

    def _eqn(self, eqn):
        prim = eqn.primitive.name
        ins = [self.name_of(v, None) for v in eqn.invars]

        def setout(names):
            for v, nm in zip(eqn.outvars, names):
                self.names[id(v)] = nm

        if prim in _CALL_PRIMS:
            params = eqn.params
            inner = (params.get("jaxpr") or params.get("call_jaxpr")
                     or params.get("fun_jaxpr"))
            if inner is None:
                raise NotImplementedError(
                    f"ONNX export: call primitive '{prim}' with no "
                    f"inlineable jaxpr")
            closed = inner if hasattr(inner, "jaxpr") else None
            jx = closed.jaxpr if closed is not None else inner
            consts = closed.consts if closed is not None else []
            sub = _Converter.convert_into(self, jx, consts, ins)
            setout(sub)
            return
        if prim in _ELEMWISE:
            setout(self.emit(_ELEMWISE[prim], ins))
            return
        if prim == "rsqrt":
            (s,) = self.emit("Sqrt", ins)
            setout(self.emit("Reciprocal", [s]))
            return
        if prim == "integer_pow":
            e = self.add_const(np.asarray(float(eqn.params["y"]),
                                          np.float32))
            setout(self.emit("Pow", [ins[0], e]))
            return
        if prim in ("stop_gradient", "copy"):
            setout(self.emit("Identity", ins))
            return
        if prim == "convert_element_type":
            to = P._NP2ONNX.get(np.dtype(eqn.params["new_dtype"]))
            if to is None:
                raise NotImplementedError(
                    f"ONNX export: cast to {eqn.params['new_dtype']}")
            setout(self.emit("Cast", ins, attrs=[P.attr_int("to", to)]))
            return
        if prim == "reshape":
            shp = self.add_const(
                np.asarray(eqn.outvars[0].aval.shape, np.int64), "shape")
            setout(self.emit("Reshape", [ins[0], shp]))
            return
        if prim == "squeeze":
            shp = self.add_const(
                np.asarray(eqn.outvars[0].aval.shape, np.int64), "shape")
            setout(self.emit("Reshape", [ins[0], shp]))
            return
        if prim == "transpose":
            perm = list(eqn.params["permutation"])
            setout(self.emit("Transpose", ins,
                             attrs=[P.attr_ints("perm", perm)]))
            return
        if prim == "broadcast_in_dim":
            out_shape = list(eqn.outvars[0].aval.shape)
            bdims = list(eqn.params["broadcast_dimensions"])
            mid = [1] * len(out_shape)
            for src, dst in enumerate(bdims):
                mid[dst] = eqn.invars[0].aval.shape[src]
            shp = self.add_const(np.asarray(mid, np.int64), "shape")
            (r,) = self.emit("Reshape", [ins[0], shp])
            tgt = self.add_const(np.asarray(out_shape, np.int64), "shape")
            setout(self.emit("Expand", [r, tgt]))
            return
        if prim in _REDUCE:
            axes = list(eqn.params["axes"])
            if prim == "reduce_sum":
                # opset 13 moved ReduceSum's axes from attribute to a
                # second INPUT (ReduceMax/Min move only at opset 18)
                ax = self.add_const(np.asarray(axes, np.int64), "axes")
                setout(self.emit("ReduceSum", [ins[0], ax],
                                 attrs=[P.attr_int("keepdims", 0)]))
                return
            setout(self.emit(
                _REDUCE[prim], ins,
                attrs=[P.attr_ints("axes", axes),
                       P.attr_int("keepdims", 0)]))
            return
        if prim == "dot_general":
            ((lc, rc), (lb, rb)) = eqn.params["dimension_numbers"]
            la = eqn.invars[0].aval
            ra = eqn.invars[1].aval
            if (not lb and not rb and la.ndim == 2 and ra.ndim == 2
                    and lc == (1,) and rc == (0,)):
                setout(self.emit("MatMul", ins))
                return
            raise NotImplementedError(
                f"ONNX export: dot_general with dims "
                f"{eqn.params['dimension_numbers']} (only plain 2-D "
                f"matmul is mapped; reshape batched dims first)")
        if prim == "conv_general_dilated":
            dn = eqn.params["dimension_numbers"]
            if (dn.lhs_spec != tuple(range(len(dn.lhs_spec)))
                    or dn.rhs_spec != tuple(range(len(dn.rhs_spec)))):
                raise NotImplementedError(
                    "ONNX export: conv supports NCHW/OIHW layouts only")
            pads = eqn.params["padding"]
            attrs = [
                P.attr_ints("strides",
                            list(eqn.params["window_strides"])),
                P.attr_ints("dilations",
                            list(eqn.params.get("rhs_dilation")
                                 or [1] * len(pads))),
                P.attr_ints("pads", [p[0] for p in pads]
                            + [p[1] for p in pads]),
                P.attr_int("group",
                           int(eqn.params.get("feature_group_count", 1))),
            ]
            setout(self.emit("Conv", ins, attrs=attrs))
            return
        if prim == "reduce_window_max":
            setout(self.emit("MaxPool", [ins[0]],
                             attrs=self._pool_attrs(eqn)))
            return
        if prim == "reduce_window_sum":
            # avg pool appears as window-sum / window-size; emit the sum
            # as AveragePool * window_size so the following div folds.
            # count_include_pad=1: padded zeros must count, or the
            # product differs from the true window sum at padded edges
            attrs = self._pool_attrs(eqn) + [
                P.attr_int("count_include_pad", 1)]
            (ap,) = self.emit("AveragePool", [ins[0]], attrs=attrs)
            wd = eqn.params["window_dimensions"]
            scale = float(np.prod(wd))
            sc = self.add_const(np.asarray(scale, np.float32))
            setout(self.emit("Mul", [ap, sc]))
            return
        if prim == "select_n":
            if len(ins) != 3:
                raise NotImplementedError(
                    "ONNX export: select_n with more than 2 cases")
            # lax.select_n(pred, on_false, on_true) -> Where(pred, true, false)
            setout(self.emit("Where", [ins[0], ins[2], ins[1]]))
            return
        if prim in ("pow",):
            setout(self.emit("Pow", ins))
            return
        raise NotImplementedError(
            f"ONNX export: primitive '{prim}' is outside the supported "
            f"subset (see paddle_tpu.onnx docstring); simplify the model "
            f"or extend the mapping")

    def _pool_attrs(self, eqn):
        wd = list(eqn.params["window_dimensions"])
        ws = list(eqn.params["window_strides"])
        pads = list(eqn.params["padding"])
        if wd[0] != 1 or wd[1] != 1:
            raise NotImplementedError(
                "ONNX export: pooling over batch/channel dims")
        spatial = len(wd) - 2
        return [
            P.attr_ints("kernel_shape", wd[2:]),
            P.attr_ints("strides", ws[2:]),
            P.attr_ints("pads", [p[0] for p in pads[2:]]
                        + [p[1] for p in pads[2:]]),
        ]


def export(layer, path: str, input_spec=None, opset_version: int = 13,
           **configs) -> str:
    """Export ``layer`` to ``<path>.onnx`` (reference: paddle.onnx.export).

    ``input_spec``: list of InputSpec/arrays fixing input shapes.  The
    exported graph is SHAPE-SPECIALIZED: a ``None`` dim traces (and is
    recorded) as 1 — re-export per serving batch size, exactly like the
    AOT shape buckets the Predictor compiles.  Symbolic batch dims are
    not emitted (the traced constants, e.g. Reshape targets, would still
    pin them)."""
    from ..jit.static_function import InputSpec

    specs = []
    for s in (input_spec or []):
        if isinstance(s, InputSpec):
            shape = [1 if d is None else int(d) for d in s.shape]
            specs.append(jax.ShapeDtypeStruct(tuple(shape),
                                              jnp.dtype(s.dtype)))
        else:
            a = s.data if isinstance(s, Tensor) else jnp.asarray(s)
            specs.append(jax.ShapeDtypeStruct(a.shape, a.dtype))
    if not specs:
        raise ValueError("paddle.onnx.export needs input_spec")

    params = [p.data for p in param_list(layer)]
    bufs = buffer_arrays(layer)
    layer.eval()
    key = jax.random.key(0)   # outside the trace: unused in eval mode,
    # so no RNG primitives land in the jaxpr

    def fwd(*xs):
        with autograd.no_grad(), rng.seed_scope(key):
            with bind(layer, list(params), list(bufs)):
                out = layer(*[Tensor(x) for x in xs])
        return jax.tree.map(
            lambda t: t.data if isinstance(t, Tensor) else t, out,
            is_leaf=lambda x: isinstance(x, Tensor))

    closed = jax.make_jaxpr(fwd)(*specs)
    conv = _Converter()
    in_names = [f"input_{i}" for i in range(len(specs))]
    out_names = conv.convert(closed.jaxpr, closed.consts, in_names)

    g_inputs = [
        P.value_info(nm, P._NP2ONNX[np.dtype(s.dtype)], list(s.shape))
        for nm, s in zip(in_names, specs)]
    out_avals = [v.aval for v in closed.jaxpr.outvars]
    g_outputs = [
        P.value_info(nm, P._NP2ONNX[np.dtype(a.dtype)], list(a.shape))
        for nm, a in zip(out_names, out_avals)]
    gb = P.graph(conv.nodes, getattr(layer, "__class__").__name__,
                 conv.initializers, g_inputs, g_outputs)
    mb = P.model(gb, opset=opset_version)
    out_path = path if path.endswith(".onnx") else path + ".onnx"
    d = os.path.dirname(out_path)
    if d:
        os.makedirs(d, exist_ok=True)
    with open(out_path, "wb") as f:
        f.write(mb)
    return out_path
