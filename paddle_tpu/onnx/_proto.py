"""Minimal protobuf wire-format writer for ONNX.

Reference: python/paddle/onnx/export.py delegates to paddle2onnx; this
build has no onnx package available, so the ModelProto is emitted
directly in protobuf wire format (varint tags + length-delimited
submessages).  Field numbers follow the public onnx.proto schema
(github.com/onnx/onnx/blob/main/onnx/onnx.proto — stable since IR v3);
tests re-decode the bytes with ``protoc --decode`` against a vendored
schema subset to prove conformance.
"""
from __future__ import annotations

import struct
from typing import List, Sequence

import numpy as np

# onnx.TensorProto.DataType
FLOAT, UINT8, INT8, INT32, INT64, BOOL = 1, 2, 3, 6, 7, 9
_NP2ONNX = {
    np.dtype(np.float32): FLOAT,
    np.dtype(np.uint8): UINT8,
    np.dtype(np.int8): INT8,
    np.dtype(np.int32): INT32,
    np.dtype(np.int64): INT64,
    np.dtype(np.bool_): BOOL,
}


def _varint(v: int) -> bytes:
    out = bytearray()
    v &= (1 << 64) - 1
    while True:
        b = v & 0x7F
        v >>= 7
        if v:
            out.append(b | 0x80)
        else:
            out.append(b)
            return bytes(out)


def _tag(field: int, wire: int) -> bytes:
    return _varint((field << 3) | wire)


def f_varint(field: int, v: int) -> bytes:
    return _tag(field, 0) + _varint(int(v))


def f_bytes(field: int, b: bytes) -> bytes:
    return _tag(field, 2) + _varint(len(b)) + b


def f_str(field: int, s: str) -> bytes:
    return f_bytes(field, s.encode("utf-8"))


def f_float(field: int, v: float) -> bytes:
    return _tag(field, 5) + struct.pack("<f", float(v))


def tensor_proto(name: str, arr: np.ndarray) -> bytes:
    dt = _NP2ONNX.get(arr.dtype)
    if dt is None:
        raise NotImplementedError(f"ONNX export: dtype {arr.dtype}")
    out = b""
    for d in arr.shape:
        out += f_varint(1, d)            # dims
    out += f_varint(2, dt)               # data_type
    out += f_str(8, name)                # name
    out += f_bytes(9, np.ascontiguousarray(arr).tobytes())  # raw_data
    return out


def attr_int(name: str, v: int) -> bytes:
    return f_str(1, name) + f_varint(3, v) + f_varint(20, 2)   # type=INT


def attr_ints(name: str, vs: Sequence[int]) -> bytes:
    out = f_str(1, name)
    for v in vs:
        out += f_varint(8, v)
    return out + f_varint(20, 7)                               # type=INTS


def attr_float(name: str, v: float) -> bytes:
    return f_str(1, name) + f_float(2, v) + f_varint(20, 1)    # type=FLOAT


def attr_str(name: str, s: str) -> bytes:
    return f_str(1, name) + f_bytes(4, s.encode()) + f_varint(20, 3)


def node(op_type: str, inputs: Sequence[str], outputs: Sequence[str],
         name: str = "", attrs: Sequence[bytes] = ()) -> bytes:
    out = b""
    for i in inputs:
        out += f_str(1, i)
    for o in outputs:
        out += f_str(2, o)
    if name:
        out += f_str(3, name)
    out += f_str(4, op_type)
    for a in attrs:
        out += f_bytes(5, a)
    return out


def value_info(name: str, elem_type: int,
               shape: Sequence[object]) -> bytes:
    dims = b""
    for d in shape:
        if isinstance(d, str):
            dims += f_bytes(1, f_str(2, d))          # dim_param
        else:
            dims += f_bytes(1, f_varint(1, int(d)))  # dim_value
    tensor_type = f_varint(1, elem_type) + f_bytes(2, dims)
    type_proto = f_bytes(1, tensor_type)
    return f_str(1, name) + f_bytes(2, type_proto)


def graph(nodes: List[bytes], name: str, initializers: List[bytes],
          inputs: List[bytes], outputs: List[bytes]) -> bytes:
    out = b""
    for n in nodes:
        out += f_bytes(1, n)
    out += f_str(2, name)
    for t in initializers:
        out += f_bytes(5, t)
    for i in inputs:
        out += f_bytes(11, i)
    for o in outputs:
        out += f_bytes(12, o)
    return out


def model(graph_bytes: bytes, opset: int = 13,
          producer: str = "paddle_tpu") -> bytes:
    opset_import = f_str(1, "") + f_varint(2, opset)
    out = f_varint(1, 8)                 # ir_version 8
    out += f_str(2, producer)
    out += f_bytes(7, graph_bytes)
    out += f_bytes(8, opset_import)
    return out
