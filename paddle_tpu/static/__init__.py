"""paddle.static — the static-graph facade.

Reference: python/paddle/static/ (Program/Executor/program_guard/data/
save+load_inference_model).  TPU-native design: a Program is an op list
recorded through the SAME dispatch point eager mode uses (core/dispatch);
the Executor interprets it inside one ``jax.jit``, so a whole reference-
style static training script — data → layers → loss → minimize →
``exe.run(feed, fetch_list)`` — compiles to a single donated XLA
computation per feed signature.

Known deviations (documented, by design):
- random ops (dropout) draw their key at build time — static programs are
  deterministic per build (reference static dropout has per-run seeds).
- dygraph Layers with running-stat buffers (BatchNorm) keep their eager
  buffers constant inside a static program; use static.nn.batch_norm or
  dygraph mode for running-stat training.
"""
from __future__ import annotations

from . import nn  # noqa: F401
from .executor import Executor, global_scope  # noqa: F401
from .io import load_inference_model, save_inference_model  # noqa: F401
from .program import (Program, Variable, data, default_main_program,  # noqa
                      default_startup_program, program_guard,
                      reset_default_programs)
from ..jit.static_function import InputSpec  # noqa: F401

__all__ = [
    "Program", "Variable", "data", "default_main_program",
    "default_startup_program", "program_guard", "Executor",
    "global_scope", "save_inference_model", "load_inference_model",
    "InputSpec", "nn", "CompiledProgram", "reset_default_programs",
]


class CompiledProgram:
    """Parity shim (reference: fluid/compiler.py CompiledProgram): the
    Executor already compiles whole programs; this wrapper exists so
    reference scripts run unchanged."""

    def __init__(self, program, build_strategy=None):
        self._program = program
        self._build_strategy = build_strategy

    def __getattr__(self, item):
        return getattr(self._program, item)
