"""paddle.static — the static-graph facade.

Reference: python/paddle/static/ (Program/Executor/program_guard/data/
save+load_inference_model).  TPU-native design: a Program is an op list
recorded through the SAME dispatch point eager mode uses (core/dispatch);
the Executor interprets it inside one ``jax.jit``, so a whole reference-
style static training script — data → layers → loss → minimize →
``exe.run(feed, fetch_list)`` — compiles to a single donated XLA
computation per feed signature.

Hot-path semantics (see executor.py for the full design):

- **Device-resident state**: after first compile, parameters and
  optimizer slots live inside the Executor as jax buffers threaded
  run-to-run with ``donate_argnums`` (``FLAGS_static_donate``, on by
  default) — weights update in place on device and no Python loop
  touches parameters per step.  ``Parameter.data`` reads resolve
  through the live state (and are aliasing-safe under donation); state
  flushes back on ``exe.close()`` or when the Program is edited.
- **Async dispatch**: ``run(..., return_numpy=False)`` returns device
  Tensors without blocking — use it in train loops and sync once when
  a value is actually needed; ``return_numpy=True`` (the default)
  syncs per call.  Feeds that are already jax arrays / Tensors pass
  through with no NumPy round-trip (a previous run's un-synced fetch
  feeds straight back in).
- **In-graph scalars**: lr / step / RNG counters ride in a donated aux
  carry — zero per-step host→device uploads (lr re-uploads only when
  a scheduler moves it).

Random ops (dropout) reseed per ``exe.run`` — the per-run key is folded
in-graph from the donated run counter (reference static dropout
semantics); pass ``exe.run(seed=...)`` to reproduce a specific run.

Known deviations (documented, by design):
- dygraph Layers with running-stat buffers (BatchNorm) keep their eager
  buffers constant inside a static program; use static.nn.batch_norm or
  dygraph mode for running-stat training.
"""
from __future__ import annotations

from . import analysis  # noqa: F401
from . import nn  # noqa: F401
from .executor import Executor, global_scope  # noqa: F401
from .io import load_inference_model, save_inference_model  # noqa: F401
from .program import (Program, Variable, data, default_main_program,  # noqa
                      default_startup_program, program_guard,
                      reset_default_programs)
from ..jit.static_function import InputSpec  # noqa: F401

__all__ = [
    "Program", "Variable", "data", "default_main_program",
    "default_startup_program", "program_guard", "Executor",
    "global_scope", "save_inference_model", "load_inference_model",
    "InputSpec", "nn", "BuildStrategy", "CompiledProgram",
    "reset_default_programs", "analysis",
]


class BuildStrategy:
    """reference: fluid/compiler.py BuildStrategy (pass toggles consumed
    by ParallelExecutor's graph passes).

    On TPU every listed pass is XLA's job and runs UNCONDITIONALLY as
    part of normal compilation, so the toggles in ``_ABSORBED`` are
    accepted (setting them is satisfied by construction).  Knobs that
    would select a *different execution strategy* the XLA design does
    not have raise loudly instead of being swallowed (round-3 rule:
    every toggle real or loud)."""

    # reference pass -> what XLA does instead, always on
    _ABSORBED = {
        "fuse_elewise_add_act_ops": "XLA elementwise fusion",
        "fuse_bn_act_ops": "XLA elementwise fusion",
        "fuse_bn_add_act_ops": "XLA elementwise fusion",
        "fuse_broadcast_ops": "XLA fusion",
        "fuse_all_optimizer_ops": "whole-step jit (one executable)",
        "fuse_all_reduce_ops": "GSPMD collective combining",
        "fuse_relu_depthwise_conv": "XLA conv fusion",
        "enable_inplace": "XLA buffer assignment + donation",
        "memory_optimize": "XLA buffer reuse",
        "enable_auto_fusion": "XLA fusion",
        "cache_runtime_context": "compiled-executable caching",
        "sync_batch_norm": "mesh-wide psum in nn.SyncBatchNorm",
        "enable_addto": "XLA buffer assignment",
    }
    _UNSUPPORTED = {
        "reduce_strategy": "Reduce-mode grad scattering (vs AllReduce) — "
                           "sharded grads are strategy.sharding (ZeRO)",
        "gradient_scale_strategy": "customized per-device loss scaling — "
                                   "scale inside the loss function",
        "build_cuda_graph": "CUDA-only",
        "fused_attention": "use FLAGS_use_pallas_kernels (flash kernel)",
        "fused_feedforward": "XLA fuses the FFN automatically",
    }

    def __init__(self):
        for k in self._ABSORBED:
            object.__setattr__(self, k, False)

    def __setattr__(self, key, value):
        if key in self._ABSORBED:
            object.__setattr__(self, key, value)
            return
        if key in self._UNSUPPORTED:
            raise NotImplementedError(
                f"BuildStrategy.{key}: {self._UNSUPPORTED[key]} "
                f"(no silent toggles — fluid/compiler.py parity shim)")
        raise AttributeError(
            f"BuildStrategy has no toggle {key!r}; known toggles: "
            f"{sorted(self._ABSORBED)}")


class CompiledProgram:
    """Parity shim (reference: fluid/compiler.py CompiledProgram): the
    Executor already compiles whole programs in one jit, so compilation
    itself needs no wrapper.  ``build_strategy`` is VALIDATED, not
    ignored: pass toggles XLA subsumes are accepted, anything else
    raises (see BuildStrategy)."""

    def __init__(self, program, build_strategy=None):
        self._program = program
        if build_strategy is not None and not isinstance(build_strategy,
                                                         BuildStrategy):
            raise TypeError(
                f"CompiledProgram(build_strategy=...) expects a "
                f"paddle.static.BuildStrategy (got "
                f"{type(build_strategy).__name__}); its toggles are "
                f"checked against what XLA actually does — there is no "
                f"silent pass-through")
        self._build_strategy = build_strategy

    def with_data_parallel(self, loss_name=None, build_strategy=None,
                           exec_strategy=None, places=None):
        """reference: compiler.py with_data_parallel — superseded by the
        SPMD path; raises to avoid pretending multi-device replication
        happened (use paddle_tpu.parallel.SpmdTrainStep)."""
        raise NotImplementedError(
            "CompiledProgram.with_data_parallel: multi-device execution "
            "is SPMD over a mesh (parallel.SpmdTrainStep / "
            "static.Executor runs one donated XLA program); replicated "
            "ParallelExecutor graphs do not exist in this design")

    def __getattr__(self, item):
        return getattr(self._program, item)
