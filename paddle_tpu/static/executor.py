"""Static-graph Executor.

TPU-native re-design of the reference Executor (reference:
python/paddle/fluid/executor.py Executor:916 run:1391,
framework/executor.cc:460 op-by-op loop).  Instead of running the op list
one kernel at a time, the whole Program — forward, backward, and optimizer
update — is interpreted once under ``jax.jit`` and compiled to a single
XLA computation per feed signature (the design the reference approaches
with ParallelExecutor + fuse passes).

Training: ``optimizer.minimize(loss)`` under ``paddle.enable_static()``
attaches (optimizer, loss) to the Program; ``run`` then computes grads
with ``jax.grad`` over the program's Parameters and applies the update
in-graph, writing the new values back into the Parameter objects (the
scope write-back of the reference's sgd ops into the global Scope).
"""
from __future__ import annotations

from typing import Dict, List, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from ..core.flags import get_flag
from ..core.tensor import Tensor
from .program import Program, Variable, default_main_program

__all__ = ["Executor", "global_scope"]


class _Scope:
    """Name → array map shim (reference: framework/scope.h)."""

    def __init__(self):
        self.vars: Dict[str, object] = {}

    def find_var(self, name):
        return self.vars.get(name)


_global_scope = _Scope()


def global_scope() -> _Scope:
    return _global_scope


def _interp(nodes, env, pmap):
    """Run the op list; ``env`` maps Variable name → array, ``pmap`` maps
    id(Parameter) → array.  Composite control-flow nodes re-run user
    closures under a replay scope resolving Variables via ``env``."""
    from ..core import autograd
    from ..core.tensor import Parameter
    from .program import replay_scope

    def lookup(v):
        if isinstance(v, Parameter):
            return pmap.get(id(v), v.data)
        return env[v.name]

    with replay_scope(lookup), autograd.no_grad():
        for node in nodes:
            args = []
            for tag, v in node.in_specs:
                if tag == "v":
                    args.append(env[v.name])
                elif tag == "p":
                    args.append(pmap[id(v)])
                else:  # const / literal
                    args.append(v)
            outs = node.fn(*args, **node.kw)
            outs = list(outs) if node.multi else [outs]
            for var, o in zip(node.out_vars, outs):
                env[var.name] = o
    return env


class Executor:
    """reference: fluid/executor.py:916.  ``place`` is accepted for parity;
    XLA owns device placement."""

    def __init__(self, place=None):
        self.place = place
        self._cache: Dict[tuple, object] = {}
        # keyed by Program._serial (monotonic, never recycled) — id()
        # keys could be reused after GC, handing a new Program a dead
        # program's run counter / optimizer slots.  Serials never
        # repeat, so entries for dead programs must be evicted: stale
        # VERSIONS are dropped on recompile (below); a per-program
        # finalizer reaps counters/opt state once the Program is
        # collectable (note the compiled cache itself pins the Program
        # through the node closures, so a sweep creating many programs
        # should call close() between trials).
        self._opt_states: Dict[int, list] = {}
        self._run_counts: Dict[int, int] = {}
        self._verified: set = set()  # (serial, version) already checked
        self._tracked: set = set()   # serials with a finalizer attached

    def _track(self, program):
        serial = program._serial
        if serial in self._tracked:
            return
        self._tracked.add(serial)
        # the closure references the containers, NOT self: the finalizer
        # must not keep the Executor alive
        import weakref
        opt, runs, ver = (self._opt_states, self._run_counts,
                          self._verified)

        def _evict():
            opt.pop(serial, None)
            runs.pop(serial, None)
            for k in [k for k in ver if k[0] == serial]:
                ver.discard(k)

        weakref.finalize(program, _evict)

    def close(self):
        """Drop all compiled programs and per-program state (run
        counters, optimizer slots).  Long-lived processes that build
        many throwaway Programs on one Executor should call this
        between trials — the compiled cache pins each Program's graph
        until then."""
        self._cache.clear()
        self._opt_states.clear()
        self._run_counts.clear()
        self._verified.clear()

    # -- main entry --------------------------------------------------------
    def run(self, program: Optional[Program] = None, feed=None,
            fetch_list: Optional[Sequence] = None, return_numpy=True,
            seed=None, **unused):
        # loaded inference programs (load_inference_model) call through
        if hasattr(program, "_run_loaded"):
            return program._run_loaded(feed, fetch_list, return_numpy)
        if program is None:
            program = default_main_program()
        feed = feed or {}
        fetch_list = list(fetch_list or [])
        if not program.nodes:
            return []  # startup program: params already initialized eagerly

        fetch_names = []
        for f in fetch_list:
            if isinstance(f, Variable):
                fetch_names.append(f.name)
            elif isinstance(f, str):
                fetch_names.append(f)
            else:
                raise TypeError(f"fetch_list entry {f!r} is not a Variable")

        params = program.parameters()
        feed_items = sorted(feed.items())
        feed_names = tuple(n for n, _ in feed_items)
        feed_arrays = [jnp.asarray(np.asarray(a)) for _, a in feed_items]

        self._track(program)
        key = (program._serial, program._version, feed_names,
               tuple((a.shape, str(a.dtype)) for a in feed_arrays),
               tuple(fetch_names), program._optimizer is not None)
        compiled = self._cache.get(key)
        if compiled is None:
            # recompile for a NEW version: executables for older
            # versions of this program can never be requested again
            # (the version only grows), so drop them — each one pins
            # the node graph it closed over
            stale = [k for k in self._cache
                     if k[0] == program._serial and k[1] != key[1]]
            for k in stale:
                del self._cache[k]
            if get_flag("static_verify"):
                vkey = (program._serial, program._version)
                if vkey not in self._verified:
                    program.verify(fetch_list=fetch_list)
                    self._verified.add(vkey)
            compiled = self._build(program, params, feed_names, fetch_names)
            self._cache[key] = compiled

        # per-run randomness (reference: static dropout reseeds per run):
        # random ops in the program fold this key via seed_scope; an
        # explicit ``seed`` reproduces a run, the default auto-increments
        run_i = self._run_counts.get(program._serial, 0) + 1
        self._run_counts[program._serial] = run_i
        rng_key = jax.random.fold_in(
            jax.random.PRNGKey(program.random_seed),
            run_i if seed is None else int(seed))

        p_arrays = [p.data for p in params]
        if program._optimizer is not None:
            opt = program._optimizer[0]
            state = self._opt_states.get(program._serial)
            if state is None:
                state = opt.functional_init(
                    [p_arrays[i] for i in compiled._t_idx])
            opt._step_count += 1
            lr = jnp.asarray(opt.get_lr(), jnp.float32)
            step_i = jnp.asarray(opt._step_count, jnp.float32)
            fetches, new_p, new_state = compiled(
                p_arrays, state, lr, step_i, rng_key, *feed_arrays)
            self._opt_states[program._serial] = new_state
            for p, arr in zip(params, new_p):
                p.data = arr
        else:
            fetches = compiled(p_arrays, rng_key, *feed_arrays)

        if return_numpy:
            return [np.asarray(f) for f in fetches]
        return [Tensor(f) for f in fetches]

    # -- compilation -------------------------------------------------------
    def _build(self, program: Program, params, feed_names, fetch_names):
        nodes = list(program.nodes)
        opt_pack = program._optimizer

        def forward_env(p_arrays, feed_arrays):
            env = {}
            for name, arr in zip(feed_names, feed_arrays):
                env[name] = arr
            pmap = {id(p): a for p, a in zip(params, p_arrays)}
            return _interp(nodes, env, pmap)

        from ..core import rng as _rng

        if opt_pack is None:
            @jax.jit
            def run_fn(p_arrays, rng_key, *feed_arrays):
                # random ops (dropout) draw from the per-run key
                with _rng.seed_scope(rng_key):
                    env = forward_env(p_arrays, feed_arrays)
                return [env[n] for n in fetch_names]
            return run_fn

        opt, loss_var, param_filter, no_grad_set = (opt_pack + (None,
                                                                None))[:4]
        # respect stop_gradient / trainable and minimize's parameters= /
        # no_grad_set= (reference: append_backward skips no-grad vars)
        allow = (None if param_filter is None
                 else {id(p) for p in param_filter})
        deny = ({id(p) for p in no_grad_set} if no_grad_set else set())

        def trainable(p):
            return (p.trainable and not p.stop_gradient
                    and (allow is None or id(p) in allow)
                    and id(p) not in deny)

        t_idx = [i for i, p in enumerate(params) if trainable(p)]
        params_meta = [params[i] for i in t_idx]

        @jax.jit
        def train_fn(p_arrays, opt_state, lr, step_i, rng_key,
                     *feed_arrays):
            p_arrays = list(p_arrays)

            def loss_of(tlist):
                full = list(p_arrays)
                for j, a in zip(t_idx, tlist):
                    full[j] = a
                with _rng.seed_scope(rng_key):
                    env = forward_env(full, feed_arrays)
                return env[loss_var.name], env

            t_arrays = [p_arrays[i] for i in t_idx]
            (loss, env), grads = jax.value_and_grad(
                loss_of, has_aux=True)(t_arrays)
            new_t, new_s = opt.functional_update(
                t_arrays, grads, opt_state, lr, step_i,
                params_meta=params_meta)
            new_p = list(p_arrays)
            for j, a in zip(t_idx, new_t):
                new_p[j] = a
            return [env[n] for n in fetch_names], new_p, new_s

        def compiled(*args):
            return train_fn(*args)

        compiled._t_idx = t_idx
        return compiled
