"""Static-graph Executor.

TPU-native re-design of the reference Executor (reference:
python/paddle/fluid/executor.py Executor:916 run:1391,
framework/executor.cc:460 op-by-op loop).  Instead of running the op list
one kernel at a time, the whole Program — forward, backward, and optimizer
update — is interpreted once under ``jax.jit`` and compiled to a single
XLA computation per feed signature (the design the reference approaches
with ParallelExecutor + fuse passes).

Hot path (the donated, device-resident, async-dispatch design):

- After first compile, parameter arrays and optimizer slots live in a
  per-Program ``_ExecState`` as jax buffers threaded run-to-run through
  the compiled step with ``donate_argnums`` (``FLAGS_static_donate``),
  so weights update in place on device and no Python loop touches every
  parameter each step.  ``Parameter.data`` resolves reads through the
  live state lazily (core/tensor.py) and is flushed back on ``close()``
  or program edit; any array a user reads escapes the donated set via a
  copy before the next run, so donation never invalidates user-held
  references.
- ``lr``/step counters/RNG folding are in-graph (donated aux carry):
  ``run`` performs zero per-step host->device scalar uploads (the lr is
  re-uploaded only when the schedule moves it, mirroring jit.TrainStep).
- Dispatch is asynchronous: ``run(..., return_numpy=False)`` returns
  device-array Tensors without ``block_until_ready``; only
  ``return_numpy=True`` syncs.  Feeds that are already jax arrays (or
  Tensors) pass through untouched — no NumPy round-trip.

Training: ``optimizer.minimize(loss)`` under ``paddle.enable_static()``
attaches (optimizer, loss) to the Program; ``run`` then computes grads
with ``jax.grad`` over the program's Parameters and applies the update
in-graph (the scope write-back of the reference's sgd ops is now the
lazy ``Parameter.data`` resolution above).
"""
from __future__ import annotations

import weakref
from typing import Dict, List, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from ..core import obs_hook
from ..core.flags import get_flag
from ..core.tensor import Tensor
from .program import Program, Variable, default_main_program

__all__ = ["Executor", "global_scope"]


class _Scope:
    """Name → array map shim (reference: framework/scope.h)."""

    def __init__(self):
        self.vars: Dict[str, object] = {}

    def find_var(self, name):
        return self.vars.get(name)


_global_scope = _Scope()


def global_scope() -> _Scope:
    return _global_scope


def _interp(nodes, env, pmap):
    """Run the op list; ``env`` maps Variable name → array, ``pmap`` maps
    id(Parameter) → array.  Composite control-flow nodes re-run user
    closures under a replay scope resolving Variables via ``env``."""
    from ..core import autograd
    from ..core.tensor import Parameter
    from .program import replay_scope

    def lookup(v):
        if isinstance(v, Parameter):
            return pmap.get(id(v), v.data)
        return env[v.name]

    with replay_scope(lookup), autograd.no_grad():
        for node in nodes:
            args = []
            for tag, v in node.in_specs:
                if tag == "v":
                    args.append(env[v.name])
                elif tag == "p":
                    args.append(pmap[id(v)])
                else:  # const / literal
                    args.append(v)
            outs = node.fn(*args, **node.kw)
            outs = list(outs) if node.multi else [outs]
            for var, o in zip(node.out_vars, outs):
                env[var.name] = o
    return env


class _ExecState:
    """Per-Program device-resident execution state (the donated hot path).

    The authoritative parameter arrays (and, once training starts, the
    optimizer slots and the aux carry: run/step counters) live HERE as
    jax buffers, threaded run-to-run through the compiled executable —
    donated under FLAGS_static_donate, so XLA updates weights in place.
    Bound Parameters resolve ``.data`` reads through this object
    (core/tensor.py Parameter.data); ``flush()`` materialises the
    current arrays back into the Parameter slots (close(), program
    edit, or another state taking the params over).

    Aliasing safety: ``fetch_param`` marks the read index as escaped;
    ``shield_escaped`` copies those slots out of the donated set before
    the next donated dispatch, so arrays handed to user code are never
    invalidated.  Binding changes anywhere in the process bump the
    class-wide generation counter; ``refresh`` revalidates bindings only
    when it moved — O(1) steady state while one state owns its params
    exclusively (the single-program train loop).  When two Programs
    SHARE Parameters and alternate runs, each switch deliberately steals
    the bindings back (O(n) rebind + one protective copy per stolen
    param under donation): correctness-first — values flow through, they
    never fork — at the cost of the zero-copy property across the
    switch.  Keep shared-param programs on the same values, or turn
    FLAGS_static_donate off, if that copy matters.
    """

    _GEN = [0]  # process-wide binding generation (shared mutable cell)

    __slots__ = ("serial", "version", "params", "p_arrays", "opt_state",
                 "aux", "t_idx", "escaped", "gen", "lr_value", "lr_device",
                 "seed_val", "base_key", "no_seed", "synced_step",
                 "__weakref__")

    def __init__(self, program, params):
        self.serial = program._serial
        self.version = program._version
        self.params = list(params)
        self.p_arrays: List = [None] * len(self.params)
        self.opt_state = None
        self.aux = None
        self.t_idx = None
        self.escaped = set()
        self.gen = -1
        self.lr_value = None
        self.lr_device = None
        self.seed_val = None
        self.base_key = None
        self.no_seed = None
        self.synced_step = None
        self._bind_all()

    # -- binding -----------------------------------------------------------
    def _bind_all(self):
        """(Re)claim every param: keep arrays already bound to us, read
        the rest through ``Parameter.data`` (which resolves a previous
        owner's live state or the raw slot) and bind them here.  Freshly
        read arrays are user-visible, so they start escaped — the first
        donated run copies them instead of invalidating them."""
        changed = False
        for i, p in enumerate(self.params):
            src = getattr(p, "_exec_src", None)
            if src is not None and src[0] is self and src[1] == i:
                continue
            self.p_arrays[i] = jnp.asarray(p.data)
            p._exec_src = (self, i)
            self.escaped.add(i)
            changed = True
        if changed:
            # two Parameters may share one buffer (tied init, user
            # aliasing) — a buffer must appear in the donated set once
            seen: Dict[int, int] = {}
            for i, a in enumerate(self.p_arrays):
                if id(a) in seen:
                    self.p_arrays[i] = jnp.array(a, copy=True)
                else:
                    seen[id(a)] = i
            _ExecState._GEN[0] += 1
        self.gen = _ExecState._GEN[0]

    def refresh(self):
        """O(1) when no binding moved since our last run; revalidates
        (absorbing user writes to ``Parameter.data`` and params stolen
        by another Executor/state) otherwise."""
        if self.gen != _ExecState._GEN[0]:
            self._bind_all()

    def flush(self):
        """Write the current arrays back into the Parameter slots and
        unbind (lazy write-back resolution point)."""
        for i, p in enumerate(self.params):
            src = getattr(p, "_exec_src", None)
            if src is not None and src[0] is self:
                p.data = self.p_arrays[i]  # setter unbinds + writes slot

    # -- Parameter.data protocol (called from core/tensor.py) --------------
    def fetch_param(self, i):
        self.escaped.add(i)
        return self.p_arrays[i]

    def param_written(self, i):
        # the Parameter unbound itself; force revalidation everywhere
        _ExecState._GEN[0] += 1

    # -- donation safety ---------------------------------------------------
    def shield_escaped(self):
        """Copy escaped arrays out of the donated set: the user may hold
        the old reference, and the next donated dispatch would otherwise
        delete its buffer."""
        if self.escaped:
            for i in self.escaped:
                self.p_arrays[i] = jnp.array(self.p_arrays[i], copy=True)
            self.escaped.clear()

    # -- optimizer.state_dict support --------------------------------------
    def export_slots(self):
        """Optimizer slot arrays keyed by the param's position in
        ``program.parameters()`` — static-mode ``optimizer.state_dict``
        reads slots from here (they never live in Optimizer._slots on
        the static path)."""
        out = {}
        if self.opt_state and self.t_idx is not None:
            for pos, i in enumerate(self.t_idx):
                s = self.opt_state[pos]
                if s:
                    out[str(i)] = {k: np.asarray(v) for k, v in s.items()}
        return out


class Executor:
    """reference: fluid/executor.py:916.  ``place`` is accepted for parity;
    XLA owns device placement."""

    def __init__(self, place=None):
        self.place = place
        self._cache: Dict[tuple, object] = {}
        # keyed by Program._serial (monotonic, never recycled) — id()
        # keys could be reused after GC, handing a new Program a dead
        # program's run counter / optimizer slots.  Serials never
        # repeat, so entries for dead programs must be evicted: stale
        # VERSIONS are dropped on recompile (below); a per-program
        # finalizer reaps counters/state once the Program is
        # collectable (note the compiled cache itself pins the Program
        # through the node closures, so a sweep creating many programs
        # should call close() between trials).
        self._states: Dict[int, _ExecState] = {}
        self._run_counts: Dict[int, int] = {}
        self._verified: set = set()  # (serial, version) already checked
        self._tracked: set = set()   # serials with a finalizer attached
        # legacy (pre-change) path bookkeeping — see _run_legacy
        self._legacy_cache: Dict[tuple, object] = {}
        self._opt_states: Dict[int, list] = {}
        # observability: tests/bench/CI assert one compile per feed
        # signature and zero host feed conversions on the donated path
        self._compile_count = 0
        self._host_feed_converts = 0

    @property
    def compile_count(self) -> int:
        """Number of XLA compiles this Executor performed (one per
        (program version, feed signature, fetch set))."""
        return self._compile_count

    @property
    def host_feed_converts(self) -> int:
        """Number of feeds that took the NumPy host round-trip.  Stays 0
        when every feed is already a jax array / Tensor."""
        return self._host_feed_converts

    def _track(self, program):
        serial = program._serial
        if serial in self._tracked:
            return
        self._tracked.add(serial)
        # the closure references the containers, NOT self: the finalizer
        # must not keep the Executor alive
        states, opt, runs, ver = (self._states, self._opt_states,
                                  self._run_counts, self._verified)

        def _evict():
            states.pop(serial, None)
            opt.pop(serial, None)
            runs.pop(serial, None)
            for k in [k for k in ver if k[0] == serial]:
                ver.discard(k)

        weakref.finalize(program, _evict)

    def close(self):
        """Flush device-resident parameter state back into the
        ``Parameter`` objects, then drop all compiled programs and
        per-program state (run counters, optimizer slots).  Long-lived
        processes that build many throwaway Programs on one Executor
        should call this between trials — the compiled cache pins each
        Program's graph until then."""
        for state in self._states.values():
            state.flush()
        self._states.clear()
        self._cache.clear()
        self._legacy_cache.clear()
        self._opt_states.clear()
        self._run_counts.clear()
        self._verified.clear()

    # -- feeds -------------------------------------------------------------
    def _feed_array(self, a):
        """Feed → device array.  jax arrays and Tensors pass through
        untouched (no device→host→device bounce; also makes feeding a
        previous run's un-synced fetch safe); everything else takes the
        NumPy conversion path once, counted for the hot-path guards."""
        if isinstance(a, Tensor):
            a = a.data
        if isinstance(a, jax.Array):
            return a
        self._host_feed_converts += 1
        return jnp.asarray(np.asarray(a))

    # -- state -------------------------------------------------------------
    def _state_for(self, program, params) -> _ExecState:
        state = self._states.get(program._serial)
        if state is not None and state.version != program._version:
            # program edited since: flush the live values into the
            # Parameters and rebuild (the edit may add/remove params)
            state.flush()
            state = None
        if state is None:
            state = _ExecState(program, params)
            self._states[program._serial] = state
        else:
            state.refresh()
        return state

    # -- main entry --------------------------------------------------------
    def run(self, program: Optional[Program] = None, feed=None,
            fetch_list: Optional[Sequence] = None, return_numpy=True,
            seed=None, **unused):
        # loaded inference programs (load_inference_model) call through
        if hasattr(program, "_run_loaded"):
            return program._run_loaded(feed, fetch_list, return_numpy)
        if program is None:
            program = default_main_program()
        # observability: a span per run when tracing is on (one
        # module-attribute None-check when off), and any exception
        # escaping the step feeds the crash flight recorder before
        # propagating — the executor is where a training step dies
        trc = obs_hook._tracer
        sid = (trc.begin_span("executor.run", program=program._serial)
               if trc is not None else None)
        try:
            return self._run(program, feed, fetch_list, return_numpy,
                             seed)
        except Exception as e:
            h = obs_hook._crash
            if h is not None:
                h(e, f"executor.run(program#{program._serial})")
            raise
        finally:
            if sid is not None:
                trc.end_span(sid)

    def _run(self, program, feed, fetch_list, return_numpy, seed):
        # chaos hook: lets fault specs crash a training step on demand
        # (preemption drills around the checkpoint/restore path)
        from ..testing import fault
        fault.point("executor.run", program._serial)
        feed = feed or {}
        fetch_list = list(fetch_list or [])
        if not program.nodes:
            return []  # startup program: params already initialized eagerly

        fetch_names = []
        for f in fetch_list:
            if isinstance(f, Variable):
                fetch_names.append(f.name)
            elif isinstance(f, str):
                fetch_names.append(f)
            else:
                raise TypeError(f"fetch_list entry {f!r} is not a Variable")

        params = program.parameters()
        feed_items = sorted(feed.items())
        feed_names = tuple(n for n, _ in feed_items)
        feed_arrays = [self._feed_array(a) for _, a in feed_items]

        self._track(program)
        donate = bool(get_flag("static_donate"))
        # per-run counter doubles as the step correlation id: events
        # this run emits (compiles, checkpoint saves, fault fires)
        # carry it on the trace
        run_i = self._run_counts.get(program._serial, 0) + 1
        self._run_counts[program._serial] = run_i
        trc = obs_hook._tracer
        if trc is not None:
            trc.set_step(run_i)

        key = (program._serial, program._version, feed_names,
               tuple((a.shape, str(a.dtype)) for a in feed_arrays),
               tuple(fetch_names), program._optimizer is not None, donate)
        compiled = self._cache.get(key)
        if compiled is None:
            # recompile for a NEW version: executables for older
            # versions of this program can never be requested again
            # (the version only grows), so drop them — each one pins
            # the node graph it closed over
            stale = [k for k in self._cache
                     if k[0] == program._serial and k[1] != key[1]]
            for k in stale:
                del self._cache[k]
            if get_flag("static_verify"):
                vkey = (program._serial, program._version)
                if vkey not in self._verified:
                    program.verify(fetch_list=fetch_list)
                    self._verified.add(vkey)
            compiled = self._build(program, params, feed_names, fetch_names,
                                   donate)
            self._cache[key] = compiled
            self._compile_count += 1
            # static cost model: predicted FLOPs / peak bytes ride the
            # attribution record (and monitor gauges) so
            # explain_compiles-style tooling can show predicted-vs-
            # measured drift per compiled (program, signature).
            # Best-effort by contract: compile_summary returns None
            # rather than raising on a cost-model gap.
            from .analysis.cost import compile_summary
            predicted = compile_summary(program, donate=donate)
            if predicted is not None:
                from ..utils import monitor
                monitor.stat_set("predicted.executor.flops",
                                 predicted["flops"])
                monitor.stat_set("predicted.executor.peak_bytes",
                                 predicted["peak_bytes"])
            # recompile attribution: the first changed field (most
            # significant first) names the cause of this compile
            from ..observability import record_compile
            record_compile("executor", program._serial, {
                "program_version": program._version,
                "feed_signature": tuple(
                    (tuple(a.shape), str(a.dtype)) for a in feed_arrays),
                "feed_names": feed_names,
                "fetch_set": tuple(fetch_names),
                "optimizer": program._optimizer is not None,
                "donate": donate,
            }, predicted=predicted)

        state = self._state_for(program, params)

        # per-run randomness (reference: static dropout reseeds per run):
        # random ops fold the per-run key via seed_scope; an explicit
        # ``seed`` reproduces a run, the default auto-increments (the
        # counter lives ON DEVICE for the train path — no upload)
        if state.seed_val != program.random_seed:
            state.seed_val = program.random_seed
            state.base_key = jax.random.PRNGKey(program.random_seed)

        if program._optimizer is not None:
            opt = program._optimizer[0]
            if state.opt_state is None:
                state.t_idx = compiled._t_idx
                state.opt_state = opt.functional_init(
                    [state.p_arrays[i] for i in compiled._t_idx])
                # checkpoint restore: set_state_dict stashed slot arrays
                # keyed by program.parameters() position
                pending = getattr(opt, "_static_pending_slots", None)
                if pending:
                    for pos, i in enumerate(compiled._t_idx):
                        s = pending.get(str(i))
                        if s:
                            state.opt_state[pos] = {
                                k: jnp.asarray(v) for k, v in s.items()}
                    opt._static_pending_slots = None
                state.aux = {
                    "run": jnp.asarray(run_i - 1, jnp.int32),
                    "step": jnp.asarray(opt._step_count, jnp.int32)}
                state.synced_step = opt._step_count
                # static-mode optimizer.state_dict reads slots from here
                opt._static_state_provider = weakref.ref(state)
            opt._step_count += 1
            if state.synced_step != opt._step_count - 1:
                # the optimizer counter moved outside this loop
                # (set_state_dict / eager steps): resync the device one
                state.aux = dict(
                    state.aux,
                    step=jnp.asarray(opt._step_count - 1, jnp.int32))
            state.synced_step = opt._step_count
            lr_val = float(opt.get_lr())
            if lr_val != state.lr_value:
                # upload the lr only when the schedule moves it
                state.lr_value = lr_val
                state.lr_device = jnp.asarray(lr_val, jnp.float32)
            if seed is None:
                seed_args = state.no_seed
                if seed_args is None:
                    # cached (flag=0, dummy): the common path uploads nothing
                    seed_args = state.no_seed = (
                        jnp.asarray(0, jnp.int32), jnp.asarray(0, jnp.int32))
            else:
                # a separate flag (not a sentinel value) so every seed —
                # including negative ones — reproduces faithfully
                seed_args = (jnp.asarray(1, jnp.int32),
                             jnp.asarray(int(seed), jnp.int32))
            if donate:
                state.shield_escaped()
            fetches, new_p, new_s, new_aux = compiled(
                state.p_arrays, state.opt_state, state.aux,
                state.lr_device, state.base_key, *seed_args, *feed_arrays)
            state.p_arrays = list(new_p)
            state.opt_state = new_s
            state.aux = new_aux
        else:
            rng_key = jax.random.fold_in(
                state.base_key, run_i if seed is None else int(seed))
            fetches = compiled(state.p_arrays, rng_key, *feed_arrays)

        if return_numpy:
            return [np.asarray(f) for f in fetches]
        return [Tensor(f) for f in fetches]

    # -- compilation -------------------------------------------------------
    def _build(self, program: Program, params, feed_names, fetch_names,
               donate):
        nodes = list(program.nodes)
        opt_pack = program._optimizer

        def forward_env(p_arrays, feed_arrays):
            env = {}
            for name, arr in zip(feed_names, feed_arrays):
                env[name] = arr
            pmap = {id(p): a for p, a in zip(params, p_arrays)}
            return _interp(nodes, env, pmap)

        from ..core import rng as _rng

        if opt_pack is None:
            @jax.jit
            def run_fn(p_arrays, rng_key, *feed_arrays):
                # random ops (dropout) draw from the per-run key
                with _rng.seed_scope(rng_key):
                    env = forward_env(p_arrays, feed_arrays)
                return [env[n] for n in fetch_names]
            return run_fn

        opt, loss_var, param_filter, no_grad_set = (opt_pack + (None,
                                                                None))[:4]
        # respect stop_gradient / trainable and minimize's parameters= /
        # no_grad_set= (reference: append_backward skips no-grad vars)
        allow = (None if param_filter is None
                 else {id(p) for p in param_filter})
        deny = ({id(p) for p in no_grad_set} if no_grad_set else set())

        def trainable(p):
            return (p.trainable and not p.stop_gradient
                    and (allow is None or id(p) in allow)
                    and id(p) not in deny)

        t_idx = [i for i, p in enumerate(params) if trainable(p)]
        params_meta = [params[i] for i in t_idx]

        def train_fn(p_arrays, opt_state, aux, lr, base_key, sflag, rseed,
                     *feed_arrays):
            p_arrays = list(p_arrays)
            # counters live in the donated aux carry: no per-step scalar
            # uploads.  'run' keys RNG (advances every run); 'step' is
            # the optimizer update count (Adam bias correction).
            run_i = aux["run"] + 1
            step_i = (aux["step"] + 1).astype(jnp.float32)
            rng_key = jax.random.fold_in(
                base_key, jnp.where(sflag > 0, rseed, run_i))

            def loss_of(tlist):
                full = list(p_arrays)
                for j, a in zip(t_idx, tlist):
                    full[j] = a
                with _rng.seed_scope(rng_key):
                    env = forward_env(full, feed_arrays)
                return env[loss_var.name], env

            t_arrays = [p_arrays[i] for i in t_idx]
            (loss, env), grads = jax.value_and_grad(
                loss_of, has_aux=True)(t_arrays)
            new_t, new_s = opt.functional_update(
                t_arrays, grads, opt_state, lr, step_i,
                params_meta=params_meta)
            new_p = list(p_arrays)
            for j, a in zip(t_idx, new_t):
                new_p[j] = a
            new_aux = {"run": run_i, "step": aux["step"] + 1}
            return ([env[n] for n in fetch_names], new_p, new_s, new_aux)

        # donate params, optimizer slots and the aux carry — NOT lr /
        # base_key / seed args (cached and reused across runs) and NOT
        # the feeds (users legitimately feed the same arrays every step)
        jitted = (jax.jit(train_fn, donate_argnums=(0, 1, 2)) if donate
                  else jax.jit(train_fn))

        def compiled(*args):
            return jitted(*args)

        compiled._t_idx = t_idx
        return compiled

    # -- pre-change reference path (bench comparison + oracle) -------------
    # The hot loop below is the Executor.run/_build pair as it stood
    # BEFORE the donated device-resident redesign: feeds bounce through
    # NumPy, every Parameter is read and written back per step, lr and
    # step scalars are re-uploaded per run, and fetches always sync.
    # bench.py's static suite measures the speedup against it and tests
    # use it as a numerical oracle.  Not part of the public API.

    def _run_legacy(self, program, feed=None, fetch_list=None,
                    return_numpy=True, seed=None):
        feed = feed or {}
        fetch_list = list(fetch_list or [])
        if not program.nodes:
            return []
        fetch_names = [f.name if isinstance(f, Variable) else f
                       for f in fetch_list]
        params = program.parameters()
        feed_items = sorted(feed.items())
        feed_names = tuple(n for n, _ in feed_items)
        feed_arrays = [jnp.asarray(np.asarray(a)) for _, a in feed_items]
        self._track(program)
        key = (program._serial, program._version, feed_names,
               tuple((a.shape, str(a.dtype)) for a in feed_arrays),
               tuple(fetch_names), program._optimizer is not None)
        compiled = self._legacy_cache.get(key)
        if compiled is None:
            compiled = self._build_legacy(program, params, feed_names,
                                          fetch_names)
            self._legacy_cache[key] = compiled
            self._compile_count += 1
            from ..observability import record_compile
            record_compile("executor_legacy", program._serial, {
                "program_version": program._version,
                "feed_signature": tuple(
                    (tuple(a.shape), str(a.dtype)) for a in feed_arrays),
                "feed_names": feed_names,
                "fetch_set": tuple(fetch_names),
                "optimizer": program._optimizer is not None,
            })
        run_i = self._run_counts.get(program._serial, 0) + 1
        self._run_counts[program._serial] = run_i
        rng_key = jax.random.fold_in(
            jax.random.PRNGKey(program.random_seed),
            run_i if seed is None else int(seed))
        p_arrays = [p.data for p in params]
        if program._optimizer is not None:
            opt = program._optimizer[0]
            state = self._opt_states.get(program._serial)
            if state is None:
                state = opt.functional_init(
                    [p_arrays[i] for i in compiled._t_idx])
            opt._step_count += 1
            lr = jnp.asarray(opt.get_lr(), jnp.float32)
            step_i = jnp.asarray(opt._step_count, jnp.float32)
            fetches, new_p, new_state = compiled(
                p_arrays, state, lr, step_i, rng_key, *feed_arrays)
            self._opt_states[program._serial] = new_state
            for p, arr in zip(params, new_p):
                p.data = arr
        else:
            fetches = compiled(p_arrays, rng_key, *feed_arrays)
        if return_numpy:
            return [np.asarray(f) for f in fetches]
        return [Tensor(f) for f in fetches]

    def _build_legacy(self, program, params, feed_names, fetch_names):
        nodes = list(program.nodes)
        opt_pack = program._optimizer

        def forward_env(p_arrays, feed_arrays):
            env = {}
            for name, arr in zip(feed_names, feed_arrays):
                env[name] = arr
            pmap = {id(p): a for p, a in zip(params, p_arrays)}
            return _interp(nodes, env, pmap)

        from ..core import rng as _rng

        if opt_pack is None:
            @jax.jit
            def run_fn(p_arrays, rng_key, *feed_arrays):
                with _rng.seed_scope(rng_key):
                    env = forward_env(p_arrays, feed_arrays)
                return [env[n] for n in fetch_names]
            return run_fn

        opt, loss_var, param_filter, no_grad_set = (opt_pack + (None,
                                                                None))[:4]
        allow = (None if param_filter is None
                 else {id(p) for p in param_filter})
        deny = ({id(p) for p in no_grad_set} if no_grad_set else set())

        def trainable(p):
            return (p.trainable and not p.stop_gradient
                    and (allow is None or id(p) in allow)
                    and id(p) not in deny)

        t_idx = [i for i, p in enumerate(params) if trainable(p)]
        params_meta = [params[i] for i in t_idx]

        @jax.jit
        def train_fn(p_arrays, opt_state, lr, step_i, rng_key,
                     *feed_arrays):
            p_arrays = list(p_arrays)

            def loss_of(tlist):
                full = list(p_arrays)
                for j, a in zip(t_idx, tlist):
                    full[j] = a
                with _rng.seed_scope(rng_key):
                    env = forward_env(full, feed_arrays)
                return env[loss_var.name], env

            t_arrays = [p_arrays[i] for i in t_idx]
            (loss, env), grads = jax.value_and_grad(
                loss_of, has_aux=True)(t_arrays)
            new_t, new_s = opt.functional_update(
                t_arrays, grads, opt_state, lr, step_i,
                params_meta=params_meta)
            new_p = list(p_arrays)
            for j, a in zip(t_idx, new_t):
                new_p[j] = a
            return [env[n] for n in fetch_names], new_p, new_s

        def compiled(*args):
            return train_fn(*args)

        compiled._t_idx = t_idx
        return compiled
